"""AttrStore — typed row/column attributes with anti-entropy checksums.

The reference stores attrs in BoltDB (key = big-endian u64 id, value =
protobuf AttrMap) with an in-memory cache and SHA1 block checksums per
100 ids for sync diffing (reference: attr.go:43-254, 411-508).  This
implementation uses stdlib sqlite3 (embedded, transactional, no new
deps) with JSON-encoded values; the block/diff protocol semantics are
the same.

Value types: str | int | bool | float (reference: attr.go:34-40);
``None`` deletes a key (reference: attr.go:285-289).
"""

from __future__ import annotations

import hashlib
import json
import os
import sqlite3
import threading
from typing import Any

# reference: attr.go:31-32
ATTR_BLOCK_SIZE = 100


def _to_db_id(id_: int) -> int:
    """Map a uint64 id into SQLite's signed 64-bit INTEGER (two's
    complement); the reference's boltdb keys are raw big-endian u64 so
    ids up to 2^64-1 are legal at the API."""
    id_ &= (1 << 64) - 1
    return id_ - (1 << 64) if id_ >= (1 << 63) else id_


def _from_db_id(id_: int) -> int:
    return id_ + (1 << 64) if id_ < 0 else id_


def validate_attrs(attrs: dict[str, Any]) -> None:
    for k, v in attrs.items():
        if v is None:
            continue
        if not isinstance(v, (str, int, bool, float)):
            raise TypeError(f"invalid attr type for {k!r}: {type(v).__name__}")


class AttrStore:
    """sqlite-backed attribute store with in-memory cache."""

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.RLock()
        self._cache: dict[int, dict[str, Any]] = {}
        self._db: sqlite3.Connection | None = None

    # --- lifecycle ---

    def open(self) -> None:
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        self._db = sqlite3.connect(self.path, check_same_thread=False)
        self._db.execute(
            "CREATE TABLE IF NOT EXISTS attrs (id INTEGER PRIMARY KEY, data TEXT)"
        )
        self._db.commit()

    def close(self) -> None:
        if self._db is not None:
            self._db.close()
            self._db = None
        self._cache.clear()

    def _conn(self) -> sqlite3.Connection:
        if self._db is None:
            raise RuntimeError("attr store is not open")
        return self._db

    # --- reads ---

    def attrs(self, id_: int) -> dict[str, Any]:
        with self._lock:
            if id_ in self._cache:
                return dict(self._cache[id_])
            row = self._conn().execute(
                "SELECT data FROM attrs WHERE id = ?", (_to_db_id(id_),)
            ).fetchone()
            m = json.loads(row[0]) if row else {}
            self._cache[id_] = m
            return dict(m)

    # --- writes ---

    def set_attrs(self, id_: int, attrs: dict[str, Any]) -> None:
        """Merge attrs into the stored map; None values delete keys
        (reference: attr.go:120-155, 268-303)."""
        validate_attrs(attrs)
        with self._lock:
            cur = self.attrs(id_)
            for k, v in attrs.items():
                if v is None:
                    cur.pop(k, None)
                else:
                    cur[k] = v
            self._conn().execute(
                "INSERT OR REPLACE INTO attrs (id, data) VALUES (?, ?)",
                (_to_db_id(id_), json.dumps(cur, sort_keys=True)),
            )
            self._conn().commit()
            self._cache[id_] = cur

    # SQLite's bound-parameter ceiling is 999 before 3.32; stay under it.
    _SELECT_BATCH = 500

    def set_bulk_attrs(self, attr_sets: dict[int, dict[str, Any]]) -> None:
        """Sorted batch write in ONE transaction (reference:
        SetBulkAttrs, attr.go:158-191 runs a single bolt Update): the
        current values of all touched ids load via batched ``IN``
        selects instead of a per-id Python-loop SELECT, the merged rows
        land through one executemany, and a failure anywhere rolls the
        whole batch back."""
        if not attr_sets:
            return
        with self._lock:
            ids = sorted(attr_sets)
            for id_ in ids:
                validate_attrs(attr_sets[id_])
            conn = self._conn()
            missing = [i for i in ids if i not in self._cache]
            for lo in range(0, len(missing), self._SELECT_BATCH):
                chunk = missing[lo : lo + self._SELECT_BATCH]
                marks = ",".join("?" * len(chunk))
                rows = conn.execute(
                    f"SELECT id, data FROM attrs WHERE id IN ({marks})",
                    [_to_db_id(i) for i in chunk],
                ).fetchall()
                for db_id, data in rows:
                    self._cache[_from_db_id(db_id)] = json.loads(data)
            params: list[tuple[int, str]] = []
            merged: dict[int, dict[str, Any]] = {}
            for id_ in ids:
                cur = dict(self._cache.get(id_, {}))
                for k, v in attr_sets[id_].items():
                    if v is None:
                        cur.pop(k, None)
                    else:
                        cur[k] = v
                params.append((_to_db_id(id_), json.dumps(cur, sort_keys=True)))
                merged[id_] = cur
            try:
                conn.executemany(
                    "INSERT OR REPLACE INTO attrs (id, data) VALUES (?, ?)",
                    params,
                )
                conn.commit()
            except sqlite3.Error:
                conn.rollback()
                raise
            # Cache updates only after the transaction commits — a
            # rolled-back batch must not leave phantom attrs in memory.
            self._cache.update(merged)

    # --- anti-entropy (reference: attr.go:193-254, 411-441) ---

    def blocks(self) -> list[tuple[int, bytes]]:
        """[(block_id, sha1)] over all ids, blocked per 100 ids."""
        with self._lock:
            rows = self._conn().execute(
                "SELECT id, data FROM attrs"
            ).fetchall()
        # Sort by the *unsigned* id so block order matches the
        # reference's big-endian key order.
        rows = sorted((_from_db_id(i), d) for i, d in rows)
        out: list[tuple[int, bytes]] = []
        h = None
        cur_block = None
        for id_, data in rows:
            if json.loads(data) == {}:
                continue
            b = id_ // ATTR_BLOCK_SIZE
            if b != cur_block:
                if h is not None:
                    out.append((cur_block, h.digest()))
                cur_block, h = b, hashlib.sha1()
            h.update(id_.to_bytes(8, "big"))
            h.update(data.encode())
        if h is not None:
            out.append((cur_block, h.digest()))
        return out

    def block_data(self, block_id: int) -> dict[int, dict[str, Any]]:
        """All attrs in one block (reference: BlockData, attr.go:226-254)."""
        lo = block_id * ATTR_BLOCK_SIZE
        hi = lo + ATTR_BLOCK_SIZE
        dlo, dhi = _to_db_id(lo), _to_db_id(hi - 1)
        with self._lock:
            if dlo <= dhi:
                rows = self._conn().execute(
                    "SELECT id, data FROM attrs WHERE id >= ? AND id <= ?",
                    (dlo, dhi),
                ).fetchall()
            else:  # block straddles the uint63 sign boundary
                rows = self._conn().execute(
                    "SELECT id, data FROM attrs WHERE id >= ? OR id <= ?",
                    (dlo, dhi),
                ).fetchall()
        return {
            _from_db_id(id_): json.loads(data)
            for id_, data in sorted(rows)
            if json.loads(data)
        }


def diff_blocks(
    local: list[tuple[int, bytes]], remote: list[tuple[int, bytes]]
) -> list[int]:
    """Block ids that differ between two checksum lists (reference:
    AttrBlocks.Diff, attr.go:411-441): present on only one side, or
    present on both with different checksums."""
    lmap = dict(local)
    rmap = dict(remote)
    out = []
    for b in sorted(lmap.keys() | rmap.keys()):
        if lmap.get(b) != rmap.get(b):
            out.append(b)
    return out
