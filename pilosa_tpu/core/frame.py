"""Frame — a row namespace with per-frame config, views, and row attrs.

Reference behavior (reference: frame.go): owns views (standard/inverse/
time sub-views), a row AttrStore at ``<frame>/.data``, and persisted meta
(rowLabel, cacheType, cacheSize, inverseEnabled, timeQuantum —
reference: frame.go:33-67,278-334; meta here is JSON rather than
protobuf, the file name and fields are the same).  ``set_bit`` writes
the named view plus one generated view per time-quantum unit
(reference: frame.go:443-483); ``import_bulk`` groups bits by
(view, slice) including reversed row/col pairs for inverse views
(reference: frame.go:527-604).
"""

from __future__ import annotations

import json
import os
import sys
import threading
from datetime import datetime

import numpy as np

from pilosa_tpu import bsi
from pilosa_tpu.core import cache as cache_mod
from pilosa_tpu.core import timequantum as tq
from pilosa_tpu.core.attr import AttrStore
from pilosa_tpu.core.names import ValidationError, validate_label, validate_name
from pilosa_tpu.core import fragment as fragment_mod
from pilosa_tpu.core.view import (
    VIEW_INVERSE,
    VIEW_STANDARD,
    View,
    is_inverse_view,
    is_valid_view,
)
from pilosa_tpu.obs.stats import NopStatsClient
from pilosa_tpu.ops.bitplane import SLICE_WIDTH

# reference: frame.go:40-46
DEFAULT_ROW_LABEL = "rowID"
DEFAULT_CACHE_TYPE = cache_mod.TYPE_RANKED
DEFAULT_CACHE_SIZE = cache_mod.DEFAULT_CACHE_SIZE


class FrameError(RuntimeError):
    pass


class Frame:
    def __init__(self, path: str, index: str, name: str):
        validate_name(name)
        self.path = path
        self.index = index
        self.name = name
        self._mu = threading.RLock()
        self._views: dict[str, View] = {}
        self.row_label = DEFAULT_ROW_LABEL
        self.cache_type = DEFAULT_CACHE_TYPE
        self.cache_size = DEFAULT_CACHE_SIZE
        self.inverse_enabled = False
        self.time_quantum = ""
        # Tiered-storage retention overrides for this frame's
        # time-quantum sub-views (pilosa_tpu/tier): seconds past a
        # view's quantum end before it ages to the cold store, and
        # before it deletes outright.  0 = inherit the node's
        # ``[tier] retention-age-s`` / ``retention-delete-s``.
        self.retention_age_s = 0.0
        self.retention_delete_s = 0.0
        # BSI integer fields (pilosa_tpu/bsi): declared per frame when
        # range_enabled, each stored in its own ``field_<name>`` view.
        self.range_enabled = False
        self._fields: dict[str, bsi.BSIField] = {}
        self.row_attr_store = AttrStore(os.path.join(path, ".data"))
        self.on_create_slice = None  # wired by Index/Holder
        self.stats = NopStatsClient()  # re-tagged by Index._new_frame
        self.logger = lambda msg: print(msg, file=sys.stderr)  # re-wired alongside stats

    # --- lifecycle (reference: frame.go:218-334) ---

    @property
    def meta_path(self) -> str:
        return os.path.join(self.path, ".meta")

    def open(self) -> None:
        with self._mu:
            os.makedirs(self.path, exist_ok=True)
            self._load_meta()
            self.row_attr_store.open()
            views_path = os.path.join(self.path, "views")
            os.makedirs(views_path, exist_ok=True)
            for entry in sorted(os.listdir(views_path)):
                view = self._new_view(entry)
                view.open()
                self._views[entry] = view

    def close(self) -> None:
        with self._mu:
            self.row_attr_store.close()
            for view in self._views.values():
                view.close()
            self._views.clear()

    def _load_meta(self) -> None:
        try:
            with open(self.meta_path) as fh:
                meta = json.load(fh)
        except FileNotFoundError:
            return
        self.row_label = meta.get("rowLabel", DEFAULT_ROW_LABEL)
        self.cache_type = meta.get("cacheType", DEFAULT_CACHE_TYPE)
        self.cache_size = meta.get("cacheSize", DEFAULT_CACHE_SIZE)
        self.inverse_enabled = meta.get("inverseEnabled", False)
        self.time_quantum = meta.get("timeQuantum", "")
        self.range_enabled = meta.get("rangeEnabled", False)
        self.retention_age_s = float(meta.get("retentionAgeS", 0.0))
        self.retention_delete_s = float(meta.get("retentionDeleteS", 0.0))
        self._fields = {
            f["name"]: bsi.BSIField(
                name=f["name"], min=int(f["min"]), max=int(f["max"])
            )
            for f in meta.get("fields", [])
        }

    def save_meta(self) -> None:
        with self._mu:
            os.makedirs(self.path, exist_ok=True)
            tmp = self.meta_path + ".tmp"
            with open(tmp, "w") as fh:
                json.dump(
                    {
                        "rowLabel": self.row_label,
                        "cacheType": self.cache_type,
                        "cacheSize": self.cache_size,
                        "inverseEnabled": self.inverse_enabled,
                        "timeQuantum": self.time_quantum,
                        "rangeEnabled": self.range_enabled,
                        "retentionAgeS": self.retention_age_s,
                        "retentionDeleteS": self.retention_delete_s,
                        "fields": [
                            self._fields[n].to_dict()
                            for n in sorted(self._fields)
                        ],
                    },
                    fh,
                )
            os.replace(tmp, self.meta_path)

    def set_options(
        self,
        row_label: str | None = None,
        cache_type: str | None = None,
        cache_size: int | None = None,
        inverse_enabled: bool | None = None,
        time_quantum: str | None = None,
        range_enabled: bool | None = None,
        retention_age_s: float | None = None,
        retention_delete_s: float | None = None,
    ) -> None:
        with self._mu:
            if row_label is not None:
                validate_label(row_label)
                self.row_label = row_label
            if cache_type is not None:
                if cache_type not in (cache_mod.TYPE_RANKED, cache_mod.TYPE_LRU):
                    raise ValidationError(f"invalid cache type: {cache_type!r}")
                self.cache_type = cache_type
            if cache_size is not None:
                self.cache_size = cache_size
            if inverse_enabled is not None:
                self.inverse_enabled = inverse_enabled
            if time_quantum is not None:
                self.time_quantum = tq.parse_time_quantum(time_quantum)
            if range_enabled is not None:
                self.range_enabled = range_enabled
            if retention_age_s is not None:
                if float(retention_age_s) < 0:
                    raise ValidationError("retention age must be >= 0")
                self.retention_age_s = float(retention_age_s)
            if retention_delete_s is not None:
                if float(retention_delete_s) < 0:
                    raise ValidationError("retention delete must be >= 0")
                self.retention_delete_s = float(retention_delete_s)
            self.save_meta()

    def set_time_quantum(self, q: str) -> None:
        """reference: frame.go:397-414"""
        with self._mu:
            self.time_quantum = tq.parse_time_quantum(q)
            self.save_meta()
        # A quantum change alters which time views a Range() reads —
        # invalidate epoch-validated read caches (executor leaf batches)
        # exactly like a data write would.
        fragment_mod._bump_write_epoch()

    # --- BSI integer fields (pilosa_tpu/bsi) ---

    def bsi_field(self, name: str) -> bsi.BSIField | None:
        with self._mu:
            return self._fields.get(name)

    def bsi_fields(self) -> list[bsi.BSIField]:
        with self._mu:
            return [self._fields[n] for n in sorted(self._fields)]

    def create_field(self, name: str, min: int, max: int) -> bsi.BSIField:
        """Declare an integer field.  Requires ``rangeEnabled``; the
        ``field_<name>`` view (and its fragments) materialize lazily on
        the first value import."""
        with self._mu:
            if not self.range_enabled:
                raise FrameError("frame does not support range queries")
            if name in self._fields:
                raise FrameError(f"field already exists: {name!r}")
            bsi.validate_field(name, min, max)
            fld = bsi.BSIField(name=name, min=int(min), max=int(max))
            self._fields[name] = fld
            self.save_meta()
        # A new field changes how Range()/Sum() calls over this frame
        # plan (depth, view set) — invalidate epoch-validated caches.
        fragment_mod._bump_write_epoch()
        return fld

    def delete_field(self, name: str) -> None:
        with self._mu:
            fld = self._fields.pop(name, None)
            if fld is None:
                raise FrameError(f"field not found: {name!r}")
            self.save_meta()
        self.delete_view(bsi.field_view_name(name))
        fragment_mod._bump_write_epoch()

    def import_value(self, field: str, column_ids, values) -> None:
        """Columnar integer import: one value per column, grouped by
        slice, each slice written as ONE vectorized set+clear pass over
        the field view's bit-planes (a re-imported column's previous
        value is fully overwritten)."""
        with self._mu:
            fld = self._fields.get(field)
        if fld is None:
            raise FrameError(f"field not found: {field!r}")
        cols = np.asarray(column_ids, dtype=np.int64)
        if len(cols) == 0:
            return
        set_r, set_c, clr_r, clr_c = bsi.value_bit_rows(fld, cols, values)
        view = self.create_view_if_not_exists(fld.view)
        # Group both halves by slice in one pass: tag set bits 0 and
        # clear bits 1, then split per slice group.
        all_c = np.concatenate([set_c, clr_c])
        all_r = np.concatenate([set_r, clr_r])
        tags = np.concatenate(
            [np.zeros(len(set_c), np.int64), np.ones(len(clr_c), np.int64)]
        )
        from pilosa_tpu.ops.bitplane import np_group_by

        for s, (r_s, c_s, t_s) in np_group_by(
            all_c // SLICE_WIDTH, all_r, all_c, tags
        ):
            frag = view.create_fragment_if_not_exists(s)
            sm = t_s == 0
            frag.import_bulk(
                r_s[sm], c_s[sm],
                clear_row_ids=r_s[~sm], clear_column_ids=c_s[~sm],
            )

    def set_value(self, field: str, column_id: int, value: int) -> None:
        self.import_value(field, [column_id], [value])

    # --- views (reference: frame.go:336-395) ---

    def _new_view(self, name: str) -> View:
        view = View(
            os.path.join(self.path, "views", name),
            self.index,
            self.name,
            name,
            cache_type=self.cache_type,
            cache_size=self.cache_size,
            row_attr_store=self.row_attr_store,
            on_create_slice=self.on_create_slice,
        )
        view.stats = self.stats.with_tags(f"view:{name}")
        view.logger = self.logger
        return view

    def view(self, name: str) -> View | None:
        with self._mu:
            return self._views.get(name)

    def views(self) -> dict[str, View]:
        with self._mu:
            return dict(self._views)

    def create_view_if_not_exists(self, name: str) -> View:
        with self._mu:
            v = self._views.get(name)
            if v is None:
                v = self._new_view(name)
                v.open()
                self._views[name] = v
            return v

    def delete_view(self, name: str) -> None:
        with self._mu:
            v = self._views.pop(name, None)
            if v is not None:
                v.close()
                import shutil

                shutil.rmtree(v.path, ignore_errors=True)

    # --- slices ---

    def max_slice(self) -> int:
        """Max slice over non-inverse views (reference: frame.go:169-186)."""
        with self._mu:
            return max(
                (v.max_slice() for n, v in self._views.items() if not is_inverse_view(n)),
                default=0,
            )

    def max_inverse_slice(self) -> int:
        with self._mu:
            return max(
                (v.max_slice() for n, v in self._views.items() if is_inverse_view(n)),
                default=0,
            )

    # --- writes (reference: frame.go:443-525) ---

    def set_bit(
        self, view_name: str, row_id: int, col_id: int, t: datetime | None = None
    ) -> bool:
        if not is_valid_view(view_name):
            raise FrameError(f"invalid view: {view_name!r}")
        view = self.create_view_if_not_exists(view_name)
        changed = view.set_bit(row_id, col_id)
        if t is None:
            return changed
        for subname in tq.views_by_time(view_name, t, self.time_quantum):
            sub = self.create_view_if_not_exists(subname)
            if sub.set_bit(row_id, col_id):
                changed = True
        return changed

    def clear_bit(self, view_name: str, row_id: int, col_id: int) -> bool:
        """reference: frame.go:485-506 (standard view only; no time fanout)"""
        if not is_valid_view(view_name):
            raise FrameError(f"invalid view: {view_name!r}")
        view = self.create_view_if_not_exists(view_name)
        return view.clear_bit(row_id, col_id)

    def import_bulk(
        self,
        row_ids,
        column_ids,
        timestamps=None,
    ) -> None:
        """Bulk import grouped by (view, slice) (reference:
        frame.go:527-604)."""
        n = len(row_ids)
        has_ts = timestamps is not None and any(
            t is not None for t in timestamps
        )
        if self.time_quantum == "" and has_ts:
            raise FrameError("time quantum not set in either index or frame")

        if not has_ts:
            # Vectorized fast path: every bit goes to the standard view
            # (and the mirrored inverse view), so grouping by slice is a
            # numpy mask per unique slice, not a Python loop per bit.
            rows = np.asarray(row_ids, dtype=np.int64)
            cols = np.asarray(column_ids, dtype=np.int64)
            self._import_grouped(VIEW_STANDARD, cols // SLICE_WIDTH, rows, cols)
            if self.inverse_enabled:
                self._import_grouped(VIEW_INVERSE, rows // SLICE_WIDTH, cols, rows)
            return

        by_fragment: dict[tuple[str, int], tuple[list[int], list[int]]] = {}

        def attach(view_name: str, slice_i: int, r: int, c: int):
            rows, cols = by_fragment.setdefault((view_name, slice_i), ([], []))
            rows.append(r)
            cols.append(c)

        for i in range(n):
            row_id, col_id, ts = row_ids[i], column_ids[i], timestamps[i]
            if ts is None:
                standard = [VIEW_STANDARD]
                inverse = [VIEW_INVERSE]
            else:
                standard = tq.views_by_time(VIEW_STANDARD, ts, self.time_quantum)
                standard.append(VIEW_STANDARD)
                inverse = tq.views_by_time(VIEW_INVERSE, ts, self.time_quantum)
            for name in standard:
                attach(name, col_id // SLICE_WIDTH, row_id, col_id)
            if self.inverse_enabled:
                for name in inverse:
                    attach(name, row_id // SLICE_WIDTH, col_id, row_id)

        for (view_name, slice_i), (rows, cols) in by_fragment.items():
            view = self.create_view_if_not_exists(view_name)
            frag = view.create_fragment_if_not_exists(slice_i)
            frag.import_bulk(rows, cols)

    def _import_grouped(self, view_name, slices, rows, cols) -> None:
        from pilosa_tpu.ops.bitplane import np_group_by

        view = self.create_view_if_not_exists(view_name)
        for s, (r_s, c_s) in np_group_by(slices, rows, cols):
            frag = view.create_fragment_if_not_exists(s)
            frag.import_bulk(r_s, c_s)

    def schema_dict(self) -> dict:
        with self._mu:
            return {
                "name": self.name,
                "rowLabel": self.row_label,
                "cacheType": self.cache_type,
                "cacheSize": self.cache_size,
                "inverseEnabled": self.inverse_enabled,
                "timeQuantum": self.time_quantum,
                "rangeEnabled": self.range_enabled,
                "retentionAgeS": self.retention_age_s,
                "retentionDeleteS": self.retention_delete_s,
                "fields": [
                    self._fields[n].to_dict() for n in sorted(self._fields)
                ],
            }
