"""Holder — the root registry of indexes on one node.

Scans the data directory on open (reference: holder.go:72-119), offers
the Index/Frame/View/Fragment accessor chain (reference:
holder.go:175-316), exposes the schema, and runs the periodic cache
flush loop (reference: holder.go:318-352; driven by the server here).
"""

from __future__ import annotations

import json
import os
import sys
import shutil
import threading

from pilosa_tpu.core.fragment import Fragment
from pilosa_tpu.core.frame import Frame
from pilosa_tpu.core.index import Index
from pilosa_tpu.core.names import ValidationError
from pilosa_tpu.core.view import View
from pilosa_tpu.obs.stats import NopStatsClient

# reference: holder.go:30-31
DEFAULT_CACHE_FLUSH_INTERVAL_S = 60.0


class Holder:
    def __init__(self, path: str):
        self.path = path
        self._mu = threading.RLock()
        self._indexes: dict[str, Index] = {}
        self.on_create_slice = None  # wired by Server before open()
        # Tag-qualified stats chain down the storage hierarchy:
        # holder -> index:<n> -> frame:<n> -> view:<n> -> slice:<i>
        # (reference: holder.go:259, index.go:443, frame.go:438,
        # view.go:257).  Server replaces this with its configured client
        # before open().
        self.stats = NopStatsClient()
        # Logger chain mirrors the stats chain: Server injects its
        # configured logger before open(); default is stderr so a
        # bare Holder still surfaces repair notices.
        self.logger = lambda msg: print(msg, file=sys.stderr)

    # --- lifecycle ---

    def open(self) -> None:
        with self._mu:
            os.makedirs(self.path, exist_ok=True)
            for entry in sorted(os.listdir(self.path)):
                full = os.path.join(self.path, entry)
                if not os.path.isdir(full):
                    continue
                try:
                    index = self._new_index(entry)
                except ValidationError:
                    # Stray dirs (lost+found, editor backups) are skipped,
                    # not fatal (reference: holder.go:97-101).
                    continue
                index.open()
                self._indexes[entry] = index

    def close(self) -> None:
        # Persist the device-residency table FIRST: it reads the live
        # pool entries, which fragment close() releases.
        try:
            self.save_residency()
        except Exception as e:  # noqa: BLE001 — shutdown must proceed
            self.logger(f"residency table save failed: {e}")
        with self._mu:
            for index in self._indexes.values():
                index.close()
            self._indexes.clear()

    # --- indexes (reference: holder.go:175-257) ---

    def _new_index(self, name: str) -> Index:
        index = Index(os.path.join(self.path, name), name)
        index.on_create_slice = self.on_create_slice
        index.stats = self.stats.with_tags(f"index:{name}")
        index.logger = self.logger
        return index

    def index(self, name: str) -> Index | None:
        with self._mu:
            return self._indexes.get(name)

    def indexes(self) -> dict[str, Index]:
        with self._mu:
            return dict(self._indexes)

    def create_index(self, name: str, **options) -> Index:
        with self._mu:
            if name in self._indexes:
                raise ValueError(f"index already exists: {name!r}")
            return self._create_index(name, options)

    def create_index_if_not_exists(self, name: str, **options) -> Index:
        with self._mu:
            index = self._indexes.get(name)
            if index is not None:
                return index
            return self._create_index(name, options)

    def _create_index(self, name: str, options: dict) -> Index:
        index = self._new_index(name)
        index.open()
        if options.get("column_label"):
            index.set_column_label(options["column_label"])
        if options.get("time_quantum"):
            index.set_time_quantum(options["time_quantum"])
        index.save_meta()
        self._indexes[name] = index
        return index

    def delete_index(self, name: str) -> None:
        with self._mu:
            index = self._indexes.pop(name, None)
            if index is not None:
                index.close()
                shutil.rmtree(index.path, ignore_errors=True)

    # --- accessor chain (reference: holder.go:259-316) ---

    def frame(self, index: str, name: str) -> Frame | None:
        idx = self.index(index)
        return idx.frame(name) if idx else None

    def view(self, index: str, frame: str, name: str) -> View | None:
        f = self.frame(index, frame)
        return f.view(name) if f else None

    def fragment(self, index: str, frame: str, view: str, slice_i: int) -> Fragment | None:
        v = self.view(index, frame, view)
        return v.fragment(slice_i) if v else None

    # --- schema (reference: holder.go:151-169) ---

    def schema(self) -> list[dict]:
        with self._mu:
            return [
                idx.schema_dict() for _, idx in sorted(self._indexes.items())
            ]

    def max_slices(self) -> dict[str, int]:
        """Per-index max slice (reference: holder.go:128-138)."""
        with self._mu:
            return {name: idx.max_slice() for name, idx in self._indexes.items()}

    def max_inverse_slices(self) -> dict[str, int]:
        with self._mu:
            return {
                name: idx.max_inverse_slice()
                for name, idx in self._indexes.items()
            }

    def _all_fragments(self) -> list:
        return [
            frag
            for index in self.indexes().values()
            for frame in index.frames().values()
            for view in frame.views().values()
            for frag in view.fragments()
        ]

    def _budgeted_fragments(self, budget_bytes: int | None) -> list:
        """Fragments whose mirrors fit an HBM budget, largest planes
        first (they are the ones whose first-query staging hurts).
        ``budget_bytes=None`` adopts the residency pool's configured
        budget so staging never floods past what the pool would
        immediately evict back out; with the pool unbounded it falls
        back to a conservative 8 GiB."""
        if budget_bytes is None:
            from pilosa_tpu import device as device_mod

            budget_bytes = device_mod.pool().budget_bytes() or (8 << 30)
        frags = sorted(self._all_fragments(), key=lambda f: -f.plane_nbytes)
        spent = 0
        kept = []
        for frag in frags:
            if spent + frag.plane_nbytes > budget_bytes:
                continue
            spent += frag.plane_nbytes
            kept.append(frag)
        return kept

    def warm_device_mirrors(self, budget_bytes: int | None = None) -> int:
        """EAGERLY upload every fragment's dense plane to its home
        device, up to ``budget_bytes`` of HBM — the synchronous warming
        API (tests, ctl).  Server restarts use the lazy overlapped
        :meth:`stage_device_mirrors` instead: eager staging serialized
        ~254 MB of uploads before the first answer (cold e2e 4.79 s).
        Returns the number of fragments warmed.  Failures count to
        ``device.stage.errors`` and surface in /debug/hbm — never only
        a log line."""
        from pilosa_tpu import device as device_mod

        warmed = 0
        for frag in self._budgeted_fragments(budget_bytes):
            try:
                frag.device_plane()
            except Exception as e:  # noqa: BLE001 — warming is best-effort
                device_mod.pool().count_stage(errors=1, last_error=repr(e))
                self.logger(f"mirror warm failed for {frag.path}: {e}")
                continue
            warmed += 1
        return warmed

    def hot_slices(self, limit: int = 32) -> dict[str, list[int]]:
        """This node's hottest resident slices, ``{index: [slice,...]}``
        — the MRU tail of the pool's mirror entries, gossiped to peers
        (cluster/gossip.py hot_provider) so a restarting node stages
        what the cluster is actually querying first."""
        from pilosa_tpu import device as device_mod

        out: dict[str, dict[int, None]] = {}
        rows = device_mod.pool().snapshot()["fragments"]
        n = 0
        for row in reversed(rows):  # MRU first
            if row.get("kind") != "mirror" or "fragment" not in row:
                continue
            index = str(row["fragment"]).split("/", 1)[0]
            s = row.get("slice")
            if not isinstance(s, int) or self.index(index) is None:
                continue
            d = out.setdefault(index, {})
            if s not in d:
                d[s] = None
                n += 1
                if n >= limit:
                    break
        return {idx: list(d) for idx, d in out.items()}

    # --- lazy overlapped cold staging (the rolling-restart fast path) ---

    def _residency_path(self) -> str:
        return os.path.join(self.path, ".residency.json")

    def fragment_key(self, frag) -> str:
        return f"{frag.index}/{frag.frame}/{frag.view}/{frag.slice}"

    def save_residency(self) -> int:
        """Persist which of THIS holder's fragments hold device mirrors,
        in the pool's LRU->MRU order — the staging priority a restarted
        node replays (most recently used first) so the pre-restart hot
        set re-materializes before the cold tail.  Written atomically;
        returns the number of fragments recorded."""
        from pilosa_tpu import device as device_mod

        mine = {self.fragment_key(f) for f in self._all_fragments()}
        resident = [
            row["fragment"]
            for row in device_mod.pool().snapshot()["fragments"]
            if row.get("kind") == "mirror" and row.get("fragment") in mine
        ]
        path = self._residency_path()
        tmp = path + ".tmp"
        os.makedirs(self.path, exist_ok=True)
        with open(tmp, "w") as f:
            json.dump({"fragments": resident}, f)
        os.replace(tmp, path)
        return len(resident)

    def load_residency(self) -> list[str]:
        """The previous incarnation's resident-fragment keys (LRU->MRU),
        [] when none was persisted or it fails to parse."""
        try:
            with open(self._residency_path()) as f:
                doc = json.load(f)
            return [str(s) for s in doc.get("fragments", [])]
        except (OSError, ValueError):
            return []

    def stage_device_mirrors(
        self,
        prefetcher,
        hot_slices: dict[str, list[int]] | None = None,
        budget_bytes: int | None = None,
        throttle_s: float = 0.0,
        tracer=None,
    ):
        """Stage fragment mirrors into HBM in the BACKGROUND, in
        priority order, returning the :class:`device.prefetch.StageJob`
        progress handle immediately — the node serves while staging
        drains, and a query's own prefetch jumps this backlog (the
        prefetcher's query lane).

        Priority: (1) fragments of gossip-announced hot slices
        (``hot_slices``: index -> slice list — what peers are actually
        being asked about right now), (2) the pre-restart residency
        table persisted at shutdown, MRU first, (3) everything else,
        largest planes first."""
        frags = self._budgeted_fragments(budget_bytes)
        by_key = {self.fragment_key(f): f for f in frags}
        # MRU-first replay of the persisted LRU->MRU table.
        prev = [k for k in reversed(self.load_residency()) if k in by_key]
        # Announcement order preserved: peers gossip their hot slices
        # MRU-first (hot_slices()), so earlier entries stage earlier.
        hot_keys: list[str] = []
        for index, slices in (hot_slices or {}).items():
            by_slice: dict[int, list[str]] = {}
            for k, f in by_key.items():
                if f.index == index:
                    by_slice.setdefault(f.slice, []).append(k)
            for s in slices:
                hot_keys += by_slice.get(s, [])
        ordered: list = []
        seen: set[str] = set()
        for k in hot_keys + prev + list(by_key):
            if k not in seen:
                seen.add(k)
                ordered.append(by_key[k])
        job = prefetcher.stage(ordered, throttle_s=throttle_s)
        if tracer is not None:
            # A root "staging" trace spanning the whole background
            # drain, finalized (with the job's outcome) when it
            # completes — visible in /debug/traces next to the queries
            # it overlapped.
            root = tracer.start_trace(
                "staging",
                fragments=len(ordered),
                hot=len(hot_keys),
                from_residency_table=len(prev),
            )

            def _finish():
                job.wait()
                root.annotate(**job.snapshot())
                tracer.finish_root(root)

            threading.Thread(
                target=_finish, daemon=True, name="staging-trace"
            ).start()
        return job

    def flush_caches(self) -> None:
        """Persist every fragment's TopN cache and group-commit its
        buffered op-log records (reference: holder.go:318-352; the flush
        loop doubles as the op-log durability interval here)."""
        for index in self.indexes().values():
            for frame in index.frames().values():
                for view in frame.views().values():
                    for frag in view.fragments():
                        frag.flush_ops()
                        frag.flush_cache()
