"""Holder — the root registry of indexes on one node.

Scans the data directory on open (reference: holder.go:72-119), offers
the Index/Frame/View/Fragment accessor chain (reference:
holder.go:175-316), exposes the schema, and runs the periodic cache
flush loop (reference: holder.go:318-352; driven by the server here).
"""

from __future__ import annotations

import os
import sys
import shutil
import threading

from pilosa_tpu.core.fragment import Fragment
from pilosa_tpu.core.frame import Frame
from pilosa_tpu.core.index import Index
from pilosa_tpu.core.names import ValidationError
from pilosa_tpu.core.view import View
from pilosa_tpu.obs.stats import NopStatsClient

# reference: holder.go:30-31
DEFAULT_CACHE_FLUSH_INTERVAL_S = 60.0


class Holder:
    def __init__(self, path: str):
        self.path = path
        self._mu = threading.RLock()
        self._indexes: dict[str, Index] = {}
        self.on_create_slice = None  # wired by Server before open()
        # Tag-qualified stats chain down the storage hierarchy:
        # holder -> index:<n> -> frame:<n> -> view:<n> -> slice:<i>
        # (reference: holder.go:259, index.go:443, frame.go:438,
        # view.go:257).  Server replaces this with its configured client
        # before open().
        self.stats = NopStatsClient()
        # Logger chain mirrors the stats chain: Server injects its
        # configured logger before open(); default is stderr so a
        # bare Holder still surfaces repair notices.
        self.logger = lambda msg: print(msg, file=sys.stderr)

    # --- lifecycle ---

    def open(self) -> None:
        with self._mu:
            os.makedirs(self.path, exist_ok=True)
            for entry in sorted(os.listdir(self.path)):
                full = os.path.join(self.path, entry)
                if not os.path.isdir(full):
                    continue
                try:
                    index = self._new_index(entry)
                except ValidationError:
                    # Stray dirs (lost+found, editor backups) are skipped,
                    # not fatal (reference: holder.go:97-101).
                    continue
                index.open()
                self._indexes[entry] = index

    def close(self) -> None:
        with self._mu:
            for index in self._indexes.values():
                index.close()
            self._indexes.clear()

    # --- indexes (reference: holder.go:175-257) ---

    def _new_index(self, name: str) -> Index:
        index = Index(os.path.join(self.path, name), name)
        index.on_create_slice = self.on_create_slice
        index.stats = self.stats.with_tags(f"index:{name}")
        index.logger = self.logger
        return index

    def index(self, name: str) -> Index | None:
        with self._mu:
            return self._indexes.get(name)

    def indexes(self) -> dict[str, Index]:
        with self._mu:
            return dict(self._indexes)

    def create_index(self, name: str, **options) -> Index:
        with self._mu:
            if name in self._indexes:
                raise ValueError(f"index already exists: {name!r}")
            return self._create_index(name, options)

    def create_index_if_not_exists(self, name: str, **options) -> Index:
        with self._mu:
            index = self._indexes.get(name)
            if index is not None:
                return index
            return self._create_index(name, options)

    def _create_index(self, name: str, options: dict) -> Index:
        index = self._new_index(name)
        index.open()
        if options.get("column_label"):
            index.set_column_label(options["column_label"])
        if options.get("time_quantum"):
            index.set_time_quantum(options["time_quantum"])
        index.save_meta()
        self._indexes[name] = index
        return index

    def delete_index(self, name: str) -> None:
        with self._mu:
            index = self._indexes.pop(name, None)
            if index is not None:
                index.close()
                shutil.rmtree(index.path, ignore_errors=True)

    # --- accessor chain (reference: holder.go:259-316) ---

    def frame(self, index: str, name: str) -> Frame | None:
        idx = self.index(index)
        return idx.frame(name) if idx else None

    def view(self, index: str, frame: str, name: str) -> View | None:
        f = self.frame(index, frame)
        return f.view(name) if f else None

    def fragment(self, index: str, frame: str, view: str, slice_i: int) -> Fragment | None:
        v = self.view(index, frame, view)
        return v.fragment(slice_i) if v else None

    # --- schema (reference: holder.go:151-169) ---

    def schema(self) -> list[dict]:
        with self._mu:
            return [
                idx.schema_dict() for _, idx in sorted(self._indexes.items())
            ]

    def max_slices(self) -> dict[str, int]:
        """Per-index max slice (reference: holder.go:128-138)."""
        with self._mu:
            return {name: idx.max_slice() for name, idx in self._indexes.items()}

    def max_inverse_slices(self) -> dict[str, int]:
        with self._mu:
            return {
                name: idx.max_inverse_slice()
                for name, idx in self._indexes.items()
            }

    def warm_device_mirrors(self, budget_bytes: int | None = None) -> int:
        """Upload every fragment's dense plane to its home device, up to
        ``budget_bytes`` of HBM — so a restarted node's first queries
        gather on-device instead of paying the host->device staging (the
        dominant cold-query cost once compiles come from the persistent
        cache; the reference's analog is its mmap page-in warmup).
        Largest planes first: they are the ones whose first-query
        staging hurts.  Returns the number of fragments warmed.  Safe
        to run in the background while serving — device_plane() is the
        same call the query path makes.

        ``budget_bytes=None`` adopts the residency pool's configured
        HBM budget (device/pool.py) so warming never floods past what
        the pool would immediately evict back out; with the pool
        unbounded it falls back to a conservative 8 GiB."""
        if budget_bytes is None:
            from pilosa_tpu import device as device_mod

            budget_bytes = device_mod.pool().budget_bytes() or (8 << 30)
        frags = [
            frag
            for index in self.indexes().values()
            for frame in index.frames().values()
            for view in frame.views().values()
            for frag in view.fragments()
        ]
        frags.sort(key=lambda f: -f._plane.nbytes)
        spent = 0
        warmed = 0
        for frag in frags:
            if spent + frag._plane.nbytes > budget_bytes:
                continue
            try:
                frag.device_plane()
            except Exception as e:  # noqa: BLE001 — warming is best-effort
                self.logger(f"mirror warm failed for {frag.path}: {e}")
                continue
            spent += frag._plane.nbytes
            warmed += 1
        return warmed

    def flush_caches(self) -> None:
        """Persist every fragment's TopN cache and group-commit its
        buffered op-log records (reference: holder.go:318-352; the flush
        loop doubles as the op-log durability interval here)."""
        for index in self.indexes().values():
            for frame in index.frames().values():
                for view in frame.views().values():
                    for frag in view.fragments():
                        frag.flush_ops()
                        frag.flush_cache()
