"""Fragment — the storage/compute unit: one (frame, view, slice) bit-plane.

The reference keeps a fragment as an mmap'd roaring bitmap with an
appended op-log, a row cache, a ranked TopN cache, and SHA1 block
checksums for anti-entropy (reference: fragment.go).  The TPU-native
design separates the planes:

* **Authoritative storage** is a host numpy uint32 plane of shape
  (padded_rows, 32768) — bit ``rowID*2^20 + columnID%2^20`` — loaded
  from / persisted to the reference's roaring file format (cookie 12346
  + op-log), so files interoperate with the reference's check/inspect
  and backup tooling.
* **Compute** runs on a lazily-refreshed device mirror of the plane
  (`device_plane()`), so query algebra and TopN scoring execute as
  batched XLA kernels over HBM; the mirror is invalidated by a
  version counter bumped on every mutation.
* **Writes** go to the host plane and append 13-byte ops to the file;
  after MAX_OP_N ops the fragment snapshots: full roaring serialization
  to ``<path>.snapshotting`` atomically renamed over the data file
  (reference: fragment.go:1006-1074).

TopN keeps the reference's ranked-cache candidate selection but scores
all candidates in one batched kernel and selects on host, instead of the
reference's sequential per-row loop with threshold pruning
(reference: fragment.go:505-639) — same results, hardware-shaped loop.
"""

from __future__ import annotations

import fcntl
import hashlib
import io
import itertools
import json
import mmap
import os
import sys
import tarfile
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass, field
from collections.abc import Iterable, Sequence
from typing import Any

import numpy as np

from pilosa_tpu import device as device_mod
from pilosa_tpu.core import cache as cache_mod
from pilosa_tpu.ingest import scatter as ingest_scatter
from pilosa_tpu.ingest import wal as ingest_wal
from pilosa_tpu.core.bitmap import RowBitmap
from pilosa_tpu.core.cache import Pair
from pilosa_tpu.obs.stats import NopStatsClient
from pilosa_tpu.ops import bitplane as bp
from pilosa_tpu.ops import roaring

SLICE_WIDTH = bp.SLICE_WIDTH

# reference: fragment.go:58-65
HASH_BLOCK_SIZE = 100
DEFAULT_FRAGMENT_MAX_OP_N = 2000
# Dense-tier budget: up to this many rows live in the device-mirrored
# dense plane (128 KiB/row — the batched-kernel fast path).  Rows beyond
# the budget live in the SPARSE tier as sorted uint32 offset arrays,
# paying only for set bits — the dense-plane analog of roaring's
# pay-per-container storage (reference: roaring/roaring.go:43-52), so
# tall-sparse fragments (inverse views, where the row axis is the
# column space — up to 2^20 distinct rows per slice) are unbounded.
DENSE_ROW_BUDGET = 1 << 16
# Sparse rows whose bit count crosses this are promoted to the dense
# tier when budget remains: past it, offset arrays (4 B/bit) cost more
# than the 128 KiB plane row.
PROMOTE_BITS = 32 * 1024
# Paged-to-device sparse rows kept per fragment (LRU, 128 KiB each).
SPARSE_DEVICE_CACHE = 64
# Device bytes of one paged row (uint32[WORDS_PER_SLICE]).
ROW_NBYTES = bp.WORDS_PER_SLICE * 4
# Largest legal row id: op-log positions are u64 and pos = row*2^20+off.
MAX_ROW_ID = 1 << 44

# Process-wide mutation epoch: bumped on EVERY fragment content change
# (point writes, bulk imports, restores).  Read-side caches (the
# executor's assembled leaf batches) validate in O(1) against it and
# only fall back to per-fragment version checks when it moved —
# read-mostly query workloads never pay a per-slice validation walk.
_write_epoch = 0


def _bump_write_epoch() -> None:
    global _write_epoch
    _write_epoch += 1


def write_epoch() -> int:
    return _write_epoch


_fragment_serials = itertools.count(1)

# Fragment-close listeners: bound methods (held weakly, so an executor
# that is never close()d still gets collected) called with the fragment
# when it leaves service — shutdown or frame/index deletion.  Read-side
# caches that pin per-fragment device memory (the executor's TopN prep
# cache) drop their entries here instead of waiting for LRU
# displacement.
_close_listeners: "list" = []
_close_listeners_mu = threading.Lock()


def register_close_listener(method) -> None:
    import weakref

    with _close_listeners_mu:
        _close_listeners.append(weakref.WeakMethod(method))


def unregister_close_listener(method) -> None:
    with _close_listeners_mu:
        _close_listeners[:] = [
            wm for wm in _close_listeners if wm() not in (None, method)
        ]


def _notify_close(frag) -> None:
    with _close_listeners_mu:
        listeners = [wm() for wm in _close_listeners]
        if None in listeners:  # drop collected entries opportunistically
            _close_listeners[:] = [wm for wm in _close_listeners if wm() is not None]
    for fn in listeners:
        if fn is None:
            continue
        try:
            fn(frag)
        except Exception:  # noqa: BLE001 — listeners must not break close
            pass


# Fragment WRITE listeners: called with (fragment, set_rows, set_cols,
# clear_rows, clear_cols, exact) — absolute column ids — after every
# successful content change (point writes, bulk imports, sync merges).
# ``exact`` is True only when every reported bit provably CHANGED state
# (the point-write paths, which skip notification on no-ops); bulk
# imports report the requested lists, which may include already-set
# bits, so incremental consumers (the subscribe delta engine) must
# treat exact=False entries as dirtiness, not arithmetic.  The
# rebalance delta log rides this hook to capture the write stream of a
# migrating slice; when nothing is registered the cost is one
# list-truthiness check per write.  Listeners register module-wide
# (every fragment) or per-fragment (Fragment.add_write_listener);
# per-fragment listeners are dropped automatically when the fragment
# leaves service (close/retire) so churning subscribers cannot leak
# callbacks on rebalanced-away slices.
_write_listeners: list = []
_write_listeners_mu = threading.Lock()


def register_write_listener(fn) -> None:
    with _write_listeners_mu:
        if fn not in _write_listeners:
            _write_listeners.append(fn)


def unregister_write_listener(fn) -> None:
    with _write_listeners_mu:
        _write_listeners[:] = [f for f in _write_listeners if f is not fn]


def _notify_write(
    frag, set_rows, set_cols, clear_rows, clear_cols, exact=False
) -> None:
    if frag._wal_replaying:
        # WAL recovery re-applies writes the listeners (replication,
        # rebalance delta log, subscriptions) already saw acked before
        # the crash — fanning them out again would double-count.
        return
    for fn in list(_write_listeners) + list(frag._frag_write_listeners):
        try:
            fn(frag, set_rows, set_cols, clear_rows, clear_cols, exact)
        except Exception:  # noqa: BLE001 — listeners must not break writes
            pass


def _apply_pending(dev, pending):
    """Fold queued point writes into one device scatter.

    Sequential semantics per bit compose to last-wins: each (slot, word)
    accumulates a set-mask and clear-mask where a later opposite op on
    the same bit cancels the earlier one, then a single gather/modify/
    scatter applies ``(v & ~clear) | set`` — unique keys, so the scatter
    never races."""
    acc: dict[tuple[int, int], list[int]] = {}
    for slot, word, mask, op in pending:
        masks = acc.setdefault((slot, word), [0, 0])
        if op:
            masks[0] |= mask
            masks[1] &= ~mask
        else:
            masks[1] |= mask
            masks[0] &= ~mask
    keys = list(acc)
    slots = np.asarray([k[0] for k in keys], dtype=np.int32)
    words = np.asarray([k[1] for k in keys], dtype=np.int32)
    set_m = np.asarray([acc[k][0] for k in keys], dtype=np.uint32)
    keep_m = np.asarray(
        [(~acc[k][1]) & 0xFFFFFFFF for k in keys], dtype=np.uint32
    )
    cur = dev[slots, words]
    return dev.at[slots, words].set((cur & keep_m) | set_m)


class FragmentError(RuntimeError):
    pass


class FragmentRetiredError(FragmentError):
    """A write landed on a fragment that left service (demoted to the
    cold tier or released after migration).  Raised instead of
    mutating the orphaned in-memory plane — the caller (View.set_bit)
    retries through the view, which revives the fragment by
    hydration; a second failure propagates loudly.  Bits are never
    silently dropped."""


class ArchiveChecksumError(FragmentError):
    """A fragment tar's payload does not match its embedded per-entry
    checksum — the named error restore paths reject on instead of
    silently installing torn bytes (the tar self-verifies since the
    tiered-storage PR; rebalance's out-of-band checksums remain)."""


@dataclass
class PairSet:
    """Parallel row/column id lists for block sync (reference:
    fragment.go:1509-1512)."""

    row_ids: list[int] = field(default_factory=list)
    column_ids: list[int] = field(default_factory=list)


@dataclass
class TopOptions:
    """reference: fragment.go:675-691"""

    n: int = 0
    src: RowBitmap | None = None
    row_ids: list[int] | None = None
    min_threshold: int = 0
    filter_field: str = ""
    filter_values: list[Any] | None = None
    tanimoto_threshold: int = 0


@dataclass
class TopState:
    """In-flight TopN work on one fragment, between top_prepare (async
    kernel dispatch) and top_finish (fetch + selection) — array-native:
    candidate ids / cached counts are int64 ndarrays in candidate
    (count-descending) order, and the dense/sparse score tiers are
    POSITIONS into that order.  ``done_ids``/``done_cnts`` short-circuit
    the src-less / empty cases with a final (filtered, sorted, trimmed)
    result; otherwise ``dev_counts`` holds the un-fetched device score
    vector (the executor may bulk-fetch many fragments' vectors in one
    round trip and hand the result back via ``counts``)."""

    done_ids: np.ndarray | None = None
    done_cnts: np.ndarray | None = None
    cand_ids: np.ndarray | None = None
    cand_cached: np.ndarray | None = None
    dense_pos: np.ndarray | None = None
    sparse_pos: np.ndarray | None = None
    sparse_cnt: np.ndarray | None = None
    n: int = 0
    tanimoto: int = 0
    src_count: int = 0
    min_threshold: int = 0
    dev_counts: object = None
    counts: object = None


@dataclass
class SubRef:
    """One fragment's TopN scoring inputs: the executor feeds ``plane``
    (the HBM-resident mirror) and ``slots`` (padded candidate slot
    indices) straight into one fused cross-fragment program
    (bp.score_planes) — no gathered candidate copy ever exists on
    device (an eager per-fragment/stacked copy once tripped OOM at 100
    slices x 256 candidates).  ``plane`` is the mirror ARRAY captured
    under the fragment lock at prepare time: jax arrays are immutable
    and mirror refreshes create new objects, so the captured reference
    is a free content snapshot — dense scoring stays consistent with
    the sparse-tier probes even if a writer lands before the program
    runs."""

    plane: object  # device plane mirror (immutable array snapshot)
    slots: np.ndarray  # int32[padded_rows] candidate slot indices
    shape: tuple  # (padded_rows, words)
    plane_rows: int  # mirror row count (program-shape grouping)
    device: object


class Fragment:
    """One frame-view x slice bit-plane with caches and sync hooks."""

    def __init__(
        self,
        path: str,
        index: str,
        frame: str,
        view: str,
        slice_i: int,
        cache_type: str = cache_mod.TYPE_RANKED,
        cache_size: int = cache_mod.DEFAULT_CACHE_SIZE,
        max_op_n: int = DEFAULT_FRAGMENT_MAX_OP_N,
        dense_row_budget: int = DENSE_ROW_BUDGET,
    ):
        self.path = path
        self.index = index
        self.frame = frame
        self.view = view
        self.slice = slice_i
        self.cache_type = cache_type
        self.cache_size = cache_size
        self.max_op_n = max_op_n
        self.dense_row_budget = dense_row_budget

        self.row_attr_store = None  # wired by Frame
        self.stats = NopStatsClient()  # re-tagged by View._new_fragment
        # Injectable like Handler's (net/handler.py): embedders route or
        # silence repair notices; default matches the CLI server.
        self.logger = lambda msg: print(msg, file=sys.stderr)
        # Process-unique identity for cache version vectors: unlike
        # id(), a serial is never reused by a recreated fragment.
        self._serial = next(_fragment_serials)
        # Residency-pool identities (device/pool.py): the dense-plane
        # HBM mirror and the paged-sparse-row cache account separately.
        self._pool_key = ("frag", self._serial, "mirror")
        self._sparse_pool_key = ("frag", self._serial, "sparse")

        self._mu = threading.RLock()
        # Two-tier row storage.  DENSE: plane row *slots* hold up to
        # dense_row_budget touched rows (device-mirrored fast path);
        # _slot_of maps logical row id -> slot.  SPARSE: every further
        # row is a sorted uint32 array of in-slice bit offsets — memory
        # scales with set bits, so fragments are row-unbounded.
        self._plane = bp.empty_plane(bp.ROW_BLOCK)
        self._slot_of: dict[int, int] = {}
        self._sparse: dict[int, np.ndarray] = {}
        # Sparse rows paged to the home device for query leaves (LRU).
        # Each entry holds the row's COMPRESSED container payload —
        # (fmt, device_payload, encoded_nbytes) per ops/bitplane
        # encode_row — so HBM residency scales with cardinality, not
        # with the 128 KiB dense geometry; _sparse_dev_nbytes tracks
        # the resident total for pool accounting.
        self._sparse_dev: "OrderedDict[int, tuple]" = OrderedDict()
        self._sparse_dev_nbytes = 0
        # Host-side encoded payloads (write-time format selection),
        # invalidated per row by _after_write like _row_cache; bytes
        # are the compressed size, so the cache is cheap even for the
        # row-unbounded sparse tier.
        self._payload_cache: dict[int, tuple] = {}
        # TopN candidate-row gathers cached per (version, candidate set):
        # Sorted tier-key arrays for vectorized dense/sparse candidate
        # splits (see _tier_key_arrays_locked), cached per version.
        self._tier_arrays = None
        self._tier_arrays_version = -1
        self._max_row_id = 0
        self._op_n = 0
        self._version = 0
        # Per-fragment write listeners (add_write_listener): cleared on
        # close/retire so a fragment leaving service holds zero
        # registered callbacks (no leak across rebalance or tier churn).
        self._frag_write_listeners: list = []
        # Incremental per-row popcounts (reference keeps cached counts,
        # bitmap.go:184-217); avoids an O(row) recount on every SetBit.
        self._count_of: dict[int, int] = {}
        self._device = None
        self._device_version = -1
        # Point writes queue here while a device mirror exists; the next
        # read folds them into ONE batched scatter instead of re-uploading
        # the whole plane (SURVEY.md §7 "mutation rate vs immutable device
        # buffers").  (slot, word, mask, op) with op 1=OR / 0=ANDNOT.
        self._device_pending: list[tuple[int, int, int, int]] = []
        # Slots with queued deltas — lets device_row() serve a row the
        # pending writes DON'T touch straight from the resident mirror
        # (byte-exact: every plane change since the last sync is in the
        # queue).  Maintained strictly alongside _device_pending.
        self._pending_slots: set[int] = set()
        self._file = None
        # Group-commit op-log buffer: point writes append 13-byte op
        # records here and fsync-free flush happens at boundaries
        # (threshold / snapshot / close / holder flush loop) instead of
        # per bit.  The reference gets the same effect from writing ops
        # into an mmap'd file and letting the page cache carry them
        # (reference: fragment.go:379-418, roaring/roaring.go:649-660);
        # durability is identical-in-kind: a crash can lose ops since
        # the last flush boundary, never committed state.  Reads never
        # consult the file while open, so read-your-writes holds.
        self._op_buf = bytearray()
        # Durable-ingest hooks (pilosa_tpu/ingest): a WAL writer is
        # attached at open when an IngestManager owns this path; while
        # attached, every changed op ALSO appends to the WAL and acks
        # can wait on its group-commit fsync.  _wal_replaying marks
        # crash-recovery replay (suppresses listener fanout, WAL
        # re-logging, and mid-replay auto-snapshots).
        self._wal = None
        self._wal_replaying = False
        self._row_cache: dict[int, np.ndarray] = {}
        self.cache = cache_mod.new_cache(cache_type, cache_size)
        # Block checksum cache: blocks() re-hashes only blocks written
        # since the last call (the reference likewise caches block
        # checksums and invalidates per-write, fragment.go:717-796).
        # A None digest records "materialized but empty" (skipped).
        self._block_sums: dict[int, bytes | None] = {}
        self._dirty_blocks: set[int] = set()
        self._opened = False
        # Set by retire(): the fragment left service (tier demotion,
        # post-migration release) and writes must raise rather than
        # mutate the orphaned plane.  Reads stay valid — the host
        # tiers still hold the content as of retirement.
        self._retired = False

    # ------------------------------------------------------------------
    # lifecycle (reference: fragment.go:154-338)
    # ------------------------------------------------------------------

    def open(self) -> None:
        with self._mu:
            if self._opened:
                return
            os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
            self._file = open(self.path, "a+b")
            try:
                fcntl.flock(self._file.fileno(), fcntl.LOCK_EX | fcntl.LOCK_NB)
            except OSError as e:
                self._file.close()
                self._file = None
                raise FragmentError(f"fragment file locked: {self.path}") from e
            try:
                self._open_storage()
                self._open_cache()
            except BaseException:
                # A failed open must not leave the file locked — the
                # flock would block every retry (and any other Fragment
                # on the path) until process exit.
                fcntl.flock(self._file.fileno(), fcntl.LOCK_UN)
                self._file.close()
                self._file = None
                raise
            self._version += 1
            self._opened = True
            # Durable ingest: replay any WAL tail newer than the
            # snapshot+op-log state just loaded, then attach a writer
            # (no-op when no IngestManager owns this path).  Inside
            # _mu: lock order is frag._mu -> wal locks.
            ingest_wal.attach_fragment(self)

    def _open_storage(self) -> None:
        size = os.fstat(self._file.fileno()).st_size
        if size == 0:
            # Seed an empty roaring header so subsequent op-log appends
            # produce a parseable file (reference: fragment.go:187-242
            # unmarshals the file before attaching the op writer).
            self._file.write(roaring.encode({}))
            self._file.flush()
            return
        # Streaming load straight out of an mmap of the file
        # (_load_direct): containers fill the two tiers in place, no
        # whole-file intermediate, so peak RSS on open is the TIER
        # size, not 2x the file (reference mmaps and zero-copies
        # containers, fragment.go:154-242, roaring/roaring.go:567-620).
        # Array containers stay as value arrays, so a tall-sparse
        # file loads in O(set bits).
        mm = mmap.mmap(self._file.fileno(), 0, access=mmap.ACCESS_READ)
        err = None
        try:
            op_n = self._load_direct(mm)
        except roaring.CorruptError as e:
            # A decode failure's traceback frames hold buffer
            # views of the mmap; closing it here would raise
            # BufferError and mask the corruption diagnosis.
            # Capture the message, let the except block drop the
            # traceback (and with it the views), then close and
            # re-raise cleanly.
            err = str(e)
        if err is not None:
            # WAL recovery: a crash mid-append (group commit makes the
            # torn window up to the flush buffer, not one record) leaves
            # a tail that fails its FNV checks.  Truncate to the last
            # valid record and serve the committed prefix; anything that
            # is NOT pure-tail damage still refuses to load (reference
            # replays ops on open, roaring/roaring.go:622-646 — its
            # single-record appends make torn tails near-impossible, so
            # it has no repair; ours must).
            torn = None
            try:
                # The bound follows THIS fragment's group-commit flush
                # threshold (a subclass/test may tune it): crash residue
                # can never exceed one flush buffer + the record that
                # tripped it.
                torn = roaring.scan_torn_tail(
                    mm, max_tail=self._OP_FLUSH_BYTES + 2 * roaring.OP_SIZE
                )
            except roaring.CorruptError:
                torn = None
            op_n = None
            if torn is not None:
                # Prove the committed prefix actually loads BEFORE
                # mutating the file — damage outside the op tail (e.g. a
                # corrupt container payload alongside tail garbage) must
                # leave the file bytes untouched for forensics, not get
                # half-"repaired" and still refuse to open.  _load_direct
                # only commits to self on success and copies everything
                # it keeps, so the view/mmap can close right after.
                view = memoryview(mm)[: torn[0]]
                try:
                    op_n = self._load_direct(view)
                except roaring.CorruptError:
                    op_n = None
                finally:
                    del view
            mm.close()
            if op_n is None:
                raise roaring.CorruptError(err)
            valid_end, reason = torn
            dropped = size - valid_end
            self._file.truncate(valid_end)
            self._file.flush()
            os.fsync(self._file.fileno())
            self.stats.count("oplogRepair")
            self.logger(
                f"fragment {self.path}: repaired torn op-log tail "
                f"({reason}); dropped {dropped} uncommitted bytes"
            )
        else:
            mm.close()
        # replayed-op count feeds snapshot bookkeeping
        self._op_n = op_n

    def add_write_listener(self, fn) -> None:
        """Register a write listener on THIS fragment only (same call
        signature as the module-wide hook).  Dropped automatically when
        the fragment leaves service — close, retire, tier demotion —
        so callers need no unhook path for slices that churn away."""
        with self._mu:
            if fn not in self._frag_write_listeners:
                self._frag_write_listeners.append(fn)

    def remove_write_listener(self, fn) -> None:
        with self._mu:
            self._frag_write_listeners[:] = [
                f for f in self._frag_write_listeners if f is not fn
            ]

    def write_listener_count(self) -> int:
        with self._mu:
            return len(self._frag_write_listeners)

    def close(self) -> None:
        with self._mu:
            if self._wal is not None:
                # Final group commit + file close; pending waiters
                # resolve durable (or WalClosed if the commit fails).
                writer, self._wal = self._wal, None
                writer._manager.detach(writer)
            if self._file is not None:
                self._flush_ops_locked()
                self.flush_cache()
                fcntl.flock(self._file.fileno(), fcntl.LOCK_UN)
                self._file.close()
                self._file = None
            # Explicit HBM release: drop the mirror AND the paged sparse
            # rows, and deregister both from the residency pool — a
            # deleted frame or an in-process restart returns its device
            # bytes now, not whenever GC reaches self._device.
            self._invalidate_device()
            self._sparse_dev.clear()
            self._sparse_dev_nbytes = 0
            self._payload_cache.clear()
            device_mod.pool().remove(self._sparse_pool_key)
            self._opened = False
            # A fragment leaving service (shutdown OR frame/index/view
            # deletion) must invalidate epoch-validated read caches —
            # deletes would otherwise serve stale batches until some
            # unrelated write moved the epoch.
            _bump_write_epoch()
            # A closed fragment must hold zero registered listeners —
            # per-fragment callbacks die with the fragment's service
            # life, never with its garbage collection.
            self._frag_write_listeners.clear()
        # Outside the lock: listeners may take their own locks.
        _notify_close(self)

    def retire(self) -> None:
        """Take the fragment out of service permanently: block further
        writes (they raise :class:`FragmentRetiredError` so the caller
        revives through the view instead of losing bits), then close.
        The tier manager's demotion path calls this AFTER the tar
        upload verified, so retirement never strands unuploaded
        state."""
        self.mark_retired()
        self.close()

    def mark_retired(self) -> None:
        with self._mu:
            self._retired = True
            # Retirement blocks writes permanently, so per-fragment
            # write listeners can never fire again — drop them now.
            self._frag_write_listeners.clear()

    def mark_retired_if_version(self, version: int) -> bool:
        """Atomically retire ONLY if no write landed since ``version``
        was read — the optimistic token the tier demotion path uses:
        the uploaded tar snapshot is provably current when this
        succeeds, and any write racing the demotion either bumped the
        version first (demotion aborts) or arrives after retirement
        (raises, and the view-level retry revives by hydration)."""
        with self._mu:
            if self._version != version:
                return False
            self._retired = True
            return True

    def _check_writable_locked(self) -> None:
        if self._retired:
            raise FragmentRetiredError(
                f"fragment {self.index}/{self.frame}/{self.view}/"
                f"{self.slice} is retired (demoted or released); "
                "re-resolve it through the view"
            )

    @property
    def cache_path(self) -> str:
        """reference: fragment.go:147-149"""
        return self.path + ".cache"

    def _open_cache(self) -> None:
        """Load persisted TopN candidate ids and re-count their rows
        (reference: fragment.go:244-282)."""
        try:
            with open(self.cache_path, "rb") as fh:
                payload = fh.read()
        except FileNotFoundError:
            return
        except OSError:
            return  # corrupt cache is rebuilt lazily, like the reference
        ids = self._decode_cache_ids(payload)
        if ids is None:
            return
        for row_id in ids:
            if isinstance(row_id, int) and (
                row_id in self._slot_of or row_id in self._sparse
            ):
                self.cache.bulk_add(row_id, self._count_of.get(row_id, 0))
        self.cache.invalidate()

    @staticmethod
    def _encode_cache_ids(ids: list[int]) -> bytes:
        """The reference's protobuf ``Cache`` message (same name + field
        number as internal/private.proto, reference: fragment.go:
        1083-1110) — .cache files and backup-tar "cache" entries are
        interchangeable with a real Pilosa's."""
        from pilosa_tpu.net import wire_pb2 as wire

        return wire.Cache(IDs=ids).SerializeToString()

    @staticmethod
    def _decode_cache_ids(payload: bytes) -> list[int] | None:
        """Cache-file payload -> row ids.  Protobuf ``Cache`` is the
        format; a leading '[' means a JSON list from r01-r04 files
        (kept readable for upgrades).  None = unreadable (the cache
        rebuilds lazily, like the reference)."""
        if payload[:1] == b"[":
            try:
                ids = json.loads(payload)
            except json.JSONDecodeError:
                return None
            return ids if isinstance(ids, list) else None
        from pilosa_tpu.net import wire_pb2 as wire

        msg = wire.Cache()
        try:
            msg.ParseFromString(payload)
        except Exception:
            return None
        return list(msg.IDs)

    def flush_cache(self) -> None:
        """Persist TopN candidate row ids (reference: fragment.go:1083-1110)."""
        with self._mu:
            tmp = self.cache_path + ".tmp"
            with open(tmp, "wb") as fh:
                fh.write(self._encode_cache_ids(self.cache.ids()))
            os.replace(tmp, self.cache_path)

    # ------------------------------------------------------------------
    # geometry
    # ------------------------------------------------------------------

    def pos(self, row_id: int, column_id: int) -> int:
        """Bit position within the plane (reference: fragment.go:476-484,
        1529-1531)."""
        min_col = self.slice * SLICE_WIDTH
        if not (min_col <= column_id < min_col + SLICE_WIDTH):
            raise FragmentError(
                f"column out of bounds: {column_id} not in slice {self.slice}"
            )
        return row_id * SLICE_WIDTH + (column_id % SLICE_WIDTH)

    @property
    def max_row_id(self) -> int:
        return self._max_row_id

    def _ensure_slot(self, row_id: int) -> int | None:
        """Dense-tier slot for a row, or None when the row lives in (or
        a first touch lands in) the SPARSE tier.  Dense capacity is
        allocated compactly up to ``dense_row_budget``; beyond it new
        rows start sparse — memory scales with set bits, never with
        distinct-row count (the roaring pay-per-container analog)."""
        slot = self._slot_of.get(row_id)
        if slot is not None:
            return slot
        if row_id in self._sparse:
            return None
        # Bit positions are u64 in the op-log (pos = row*2^20 + offset),
        # so row ids must stay below 2^44; reject before mutating state
        # (PQL rowID=-1 wraps to 2^64-1 at the executor boundary).
        if row_id >= MAX_ROW_ID:
            raise FragmentError(f"row id out of range: {row_id}")
        self._max_row_id = max(self._max_row_id, row_id)
        if len(self._slot_of) >= self.dense_row_budget:
            self._sparse[row_id] = np.empty(0, dtype=np.uint32)
            self._count_of[row_id] = 0
            return None
        slot = self._alloc_dense_slot(row_id)
        self._count_of[row_id] = 0
        return slot

    def _alloc_dense_slot(self, row_id: int) -> int:
        slot = len(self._slot_of)
        self._slot_of[row_id] = slot
        needed = bp.pad_rows(slot + 1)
        if needed > self._plane.shape[0]:
            self._reserve_dense(
                max(needed, min(2 * self._plane.shape[0], self.dense_row_budget))
            )
        return slot

    def _reserve_dense(self, n_slots: int) -> None:
        """Grow the dense plane to hold ``n_slots`` rows in ONE
        allocation.  Bulk imports pre-size for all their new rows up
        front — growing through the doubling path copies the whole
        plane O(log n) times."""
        needed = bp.pad_rows(max(n_slots, 1))
        if needed > self._plane.shape[0]:
            extra = np.zeros(
                (needed - self._plane.shape[0], bp.WORDS_PER_SLICE), np.uint32
            )
            self._plane = np.vstack([self._plane, extra])
            # the device mirror no longer matches the plane's shape —
            # a structural change the delta-scatter cannot express
            if self._device is not None:
                ingest_scatter.note_fallback()
            self._invalidate_device()

    def _maybe_promote(self, row_id: int) -> None:
        """Sparse rows past PROMOTE_BITS move to the dense tier while
        budget remains (beyond it, offset arrays cost more than the
        plane row); correctness never depends on promotion."""
        offs = self._sparse.get(row_id)
        if (
            offs is None
            or len(offs) <= PROMOTE_BITS
            or len(self._slot_of) >= self.dense_row_budget
        ):
            return
        del self._sparse[row_id]
        self._payload_cache.pop(row_id, None)
        if self._sparse_dev.pop(row_id, None) is not None:
            self._sync_sparse_pool_locked()
        slot = self._alloc_dense_slot(row_id)
        self._plane[slot] = bp.np_columns_to_row(offs)
        # Tier promotion rewrites a whole plane row — structural, not a
        # per-bit delta the scatter path can carry.
        if self._device is not None:
            ingest_scatter.note_fallback()
        self._invalidate_device()

    def _load_direct(self, mm) -> int:
        """Stream containers from the mmap'd file STRAIGHT into the two
        storage tiers and replay the op-log; returns the op count.

        Unlike decode_tiered + _load_tiered (kept for restore payloads),
        no whole-file container dict ever materializes, so open's peak
        heap is the tier size itself (plane + sparse offsets ≈ file
        bytes), not 2x — the closest Python analog of the reference's
        zero-copy mmap container attach (roaring/roaring.go:567-620):
        file bytes stay in the page cache, the heap holds exactly the
        tiers.  Everything builds into locals and commits to ``self`` at
        the end, so a CorruptError mid-parse leaves the fragment's state
        untouched (the torn-tail repair path retries after truncating).
        """
        keys, ns, offs, plens, ops_base = roaring.parse_header_tables(mm)
        size = len(mm)
        cps = bp.CONTAINERS_PER_SLICE
        cbits = roaring.CONTAINER_BITS
        wpc = bp.WORDS_PER_CONTAINER
        n_cont = len(keys)

        if n_cont:
            ends = offs + plens
            if (offs >= size).any() or (ends > size).any():
                raise roaring.CorruptError("container payload out of bounds")
            if (offs % 4).any():
                raise roaring.CorruptError("misaligned container payload")
            ops_offset = int(max(ops_base, ends.max()))
        else:
            ops_offset = ops_base

        rows_of = (keys // cps).astype(np.int64)
        # Header n fields drive the density RANKING only; exact counts
        # are recomputed from the actual payloads after the tiers are
        # built (a corrupt n must never poison Count/TopN — the check
        # CLI reports such files, but open stays payload-truthful).
        uniq_rows, starts = np.unique(rows_of, return_index=True)
        row_counts = (
            np.add.reduceat(ns, starts) if n_cont else np.zeros(0, np.int64)
        )
        order = np.argsort(-row_counts, kind="stable")
        dense_rows = sorted(
            int(uniq_rows[i]) for i in order[: self.dense_row_budget]
        )
        slot_of = {r: i for i, r in enumerate(dense_rows)}
        plane = bp.empty_plane(bp.pad_rows(len(dense_rows)))
        sparse: dict[int, np.ndarray] = {}

        # Per-container slot (-1 = sparse tier), via the uniq_rows table.
        slot_table = np.asarray(
            [slot_of.get(int(r), -1) for r in uniq_rows], dtype=np.int64
        )
        cont_slots = (
            slot_table[np.searchsorted(uniq_rows, rows_of)]
            if n_cont
            else np.zeros(0, np.int64)
        )

        # One u32 view over the payload region (no copy; op-log records
        # after ops_offset are 13-byte and break 4-alignment, so the
        # view stops there).
        u32 = np.frombuffer(mm, dtype="<u4", count=ops_offset // 4)

        amask = ns <= roaring.ARRAY_MAX_SIZE if n_cont else np.zeros(0, bool)
        bmask = ~amask if n_cont else amask

        # Sparse rows holding any BITMAP container are rebuilt
        # per-row below (two payload forms must interleave in key
        # order); exclude them from the vectorized grouping.
        special_rows = (
            set(int(r) for r in rows_of[bmask & (cont_slots < 0)])
            if n_cont
            else set()
        )

        # ---- array containers: vectorized gather in bounded CHUNKS so
        # the transient index/value arrays never rival the tier itself
        # (an all-array 180 MB file would otherwise gather ~45M values
        # with int64 scratch — hundreds of MB of peak for nothing).
        _CHUNK_VALUES = self._LOAD_CHUNK_VALUES
        if n_cont and amask.any():
            a_idx = np.nonzero(amask)[0]
            csum = np.cumsum(ns[a_idx])
            special_arr = (
                np.asarray(sorted(special_rows)) if special_rows else None
            )
            sp_rows_parts: list[np.ndarray] = []
            sp_offs_parts: list[np.ndarray] = []
            start = 0
            while start < len(a_idx):
                floor = int(csum[start - 1]) if start else 0
                end = int(
                    np.searchsorted(csum, floor + _CHUNK_VALUES, side="right")
                )
                end = max(end, start + 1)
                blk = a_idx[start:end]
                ns_blk = ns[blk]
                offs32 = (offs[blk] // 4).astype(np.int64)
                total = int(ns_blk.sum())
                base_idx = np.repeat(
                    offs32 - np.insert(np.cumsum(ns_blk), 0, 0)[:-1], ns_blk
                )
                vals = u32[base_idx + np.arange(total)]
                del base_idx
                if total and int(vals.max()) >= cbits:
                    raise roaring.CorruptError("array value out of range")
                if total > 1:
                    d = np.diff(vals.astype(np.int64))
                    ok = d > 0
                    # container-boundary diffs are exempt (bnd-1 indexes
                    # d, and bnd <= total-1 always since every n >= 1);
                    # chunk edges are container boundaries too.
                    bnd = np.cumsum(ns_blk)[:-1]
                    ok[bnd - 1] = True
                    if not ok.all():
                        raise roaring.CorruptError(
                            "array container is not sorted/unique"
                        )
                    del d, ok
                # offsets within a slice fit int32 (< 2^20)
                cidx_rep = np.repeat(
                    (keys[blk] % cps).astype(np.int32), ns_blk
                )
                slots_rep = np.repeat(cont_slots[blk].astype(np.int32), ns_blk)
                off_in_slice = cidx_rep * np.int32(cbits) + vals.astype(
                    np.int32
                )
                del vals, cidx_rep

                dm = slots_rep >= 0
                if dm.any():
                    sel = off_in_slice[dm]
                    word = sel // np.int32(bp.WORD_BITS)
                    bits = (
                        np.uint32(1) << (sel % np.int32(bp.WORD_BITS)).astype(np.uint32)
                    ).astype(np.uint32)
                    np.bitwise_or.at(plane, (slots_rep[dm], word), bits)
                    del sel, word, bits
                sm = ~dm
                if sm.any():
                    rows_rep = np.repeat(rows_of[blk], ns_blk)
                    if special_arr is not None:
                        sm &= ~np.isin(rows_rep, special_arr)
                    if sm.any():
                        # boolean-mask indexing COPIES: compact buffers
                        # holding exactly the sparse values.
                        sp_rows_parts.append(rows_rep[sm])
                        sp_offs_parts.append(
                            off_in_slice[sm].astype(np.uint32)
                        )
                start = end
            if sp_rows_parts:
                # chunks ascend in container-key order, so the
                # concatenation is globally sorted by (row, offset);
                # per-row slices are views of ONE compact buffer.
                s_rows = np.concatenate(sp_rows_parts)
                s_offs = np.concatenate(sp_offs_parts)
                del sp_rows_parts, sp_offs_parts
                u_s, st = np.unique(s_rows, return_index=True)
                bounds = np.append(st, len(s_rows))
                for j, r in enumerate(u_s):
                    sparse[int(r)] = s_offs[bounds[j] : bounds[j + 1]]

        # ---- bitmap containers of dense rows: slice-assign payloads.
        if n_cont and bmask.any():
            for i in np.nonzero(bmask)[0]:
                slot = int(cont_slots[i])
                if slot < 0:
                    continue
                s32 = int(offs[i]) // 4
                cidx = int(keys[i]) % cps
                # wpc is u32 words per container (2048)
                plane[slot, cidx * wpc : (cidx + 1) * wpc] = u32[
                    s32 : s32 + wpc
                ]

        # ---- mixed-form sparse rows (rare): rebuild in key order.
        for r in sorted(special_rows):
            lo = int(np.searchsorted(rows_of, r, side="left"))
            hi = int(np.searchsorted(rows_of, r, side="right"))
            segs = []
            for i in range(lo, hi):
                cidx = int(keys[i]) % cps
                s32 = int(offs[i]) // 4
                if amask[i]:
                    vals_i = u32[s32 : s32 + int(ns[i])]
                else:
                    w = np.ascontiguousarray(
                        u32[s32 : s32 + wpc]
                    ).view(np.uint64)
                    vals_i = roaring.words_to_values(w)
                segs.append(
                    vals_i.astype(np.uint32) + np.uint32(cidx * cbits)
                )
            sparse[r] = (
                np.concatenate(segs) if segs else np.empty(0, np.uint32)
            )

        # ---- exact counts from the built tiers (payload-truthful,
        # like the replaced decode path's np_count sweep).  Row-block
        # sweeps keep the popcount temp out of the open peak.
        counts: dict[int, int] = {}
        if dense_rows:
            cnts = np.concatenate(
                [
                    bp.np_row_counts(plane[b : b + 256])
                    for b in range(0, len(dense_rows), 256)
                ]
            )
            counts.update(
                (r, int(cnts[slot])) for r, slot in slot_of.items()
            )
        counts.update((r, len(offs_r)) for r, offs_r in sparse.items())

        # ---- op-log replay over the freshly-built tiers.
        op_n = 0
        max_row = int(uniq_rows.max()) if n_cont else 0
        for typ, value in roaring._iter_ops(mm, ops_offset):
            op_n += 1
            row, offset = divmod(value, SLICE_WIDTH)
            slot = slot_of.get(row)
            if slot is None and row not in sparse:
                if len(slot_of) < self.dense_row_budget:
                    slot = slot_of[row] = len(slot_of)
                    if slot >= plane.shape[0]:
                        extra = np.zeros(
                            (bp.pad_rows(slot + 1) - plane.shape[0],
                             bp.WORDS_PER_SLICE),
                            np.uint32,
                        )
                        plane = np.vstack([plane, extra])
                else:
                    sparse[row] = np.empty(0, np.uint32)
                counts.setdefault(row, 0)
            if slot is not None:
                if typ == roaring.OP_ADD:
                    changed = bp.np_set_bit(plane, slot * SLICE_WIDTH + offset)
                else:
                    changed = bp.np_clear_bit(plane, slot * SLICE_WIDTH + offset)
            else:
                offs_row = sparse[row]
                i = int(np.searchsorted(offs_row, offset))
                present = i < len(offs_row) and int(offs_row[i]) == offset
                if typ == roaring.OP_ADD and not present:
                    sparse[row] = np.insert(offs_row, i, np.uint32(offset))
                    changed = True
                elif typ == roaring.OP_REMOVE and present:
                    sparse[row] = np.delete(offs_row, i)
                    changed = True
                else:
                    changed = False
            if changed:
                counts[row] = counts.get(row, 0) + (
                    1 if typ == roaring.OP_ADD else -1
                )
                max_row = max(max_row, row)

        # ---- commit (everything above was local).
        self._slot_of = slot_of
        self._plane = plane
        self._sparse = sparse
        self._sparse_dev.clear()
        self._payload_cache.clear()
        self._sync_sparse_pool_locked()
        self._max_row_id = max_row
        self._count_of = counts
        self._block_sums.clear()
        self._dirty_blocks.clear()
        self._row_cache.clear()
        self._invalidate_device()
        _bump_write_epoch()
        return op_n

    def _load_tiered(
        self, words: dict[int, np.ndarray], arrays: dict[int, np.ndarray]
    ) -> None:
        """Replace storage from tiered containers (open/restore): the
        densest rows fill the dense tier first; the long sparse tail
        stays as offset arrays."""
        per_row: dict[int, list[tuple[int, np.ndarray, bool]]] = {}
        counts: dict[int, int] = {}
        for key, w in words.items():
            row, cidx = divmod(int(key), bp.CONTAINERS_PER_SLICE)
            per_row.setdefault(row, []).append((cidx, w, False))
            counts[row] = counts.get(row, 0) + bp.np_count(w)
        for key, vals in arrays.items():
            row, cidx = divmod(int(key), bp.CONTAINERS_PER_SLICE)
            per_row.setdefault(row, []).append((cidx, vals, True))
            counts[row] = counts.get(row, 0) + len(vals)

        by_density = sorted(per_row, key=lambda r: (-counts[r], r))
        dense_rows = sorted(by_density[: self.dense_row_budget])
        sparse_rows = by_density[self.dense_row_budget :]

        self._slot_of = {r: i for i, r in enumerate(dense_rows)}
        plane = bp.empty_plane(bp.pad_rows(len(dense_rows)))
        wpc = bp.WORDS_PER_CONTAINER
        for i, r in enumerate(dense_rows):
            for cidx, payload, is_vals in per_row[r]:
                w = roaring.values_to_words(payload) if is_vals else payload
                plane[i, cidx * wpc : (cidx + 1) * wpc] = (
                    w.view("<u4").astype(np.uint32)
                )
        self._plane = plane

        self._sparse = {}
        for r in sparse_rows:
            segs = []
            for cidx, payload, is_vals in sorted(per_row[r]):
                vals = payload if is_vals else roaring.words_to_values(payload)
                segs.append(
                    vals.astype(np.uint32) + np.uint32(cidx * roaring.CONTAINER_BITS)
                )
            self._sparse[r] = (
                np.concatenate(segs) if segs else np.empty(0, np.uint32)
            )
        self._sparse_dev.clear()
        self._payload_cache.clear()
        self._sync_sparse_pool_locked()

        self._max_row_id = max(per_row) if per_row else 0
        self._count_of = counts
        self._block_sums.clear()
        self._dirty_blocks.clear()
        self._invalidate_device()
        _bump_write_epoch()

    def _containers_packed(
        self,
    ) -> tuple[np.ndarray, np.ndarray, dict[int, np.ndarray]]:
        """Current storage as (dense keys, dense payloads, sparse value
        arrays) for serialization — the dense tier packs into two
        contiguous buffers with no per-container Python, and sparse rows
        convert offsets->values directly, never materializing a plane
        row.  Returns ``(keys u64 ascending, words2d u64[n, 1024],
        arrays)`` for roaring.encode_packed."""
        wpc = bp.WORDS_PER_CONTAINER
        cps = bp.CONTAINERS_PER_SLICE
        cbits = roaring.CONTAINER_BITS
        arrays: dict[int, np.ndarray] = {}
        # Dense tier, fully vectorized (in row blocks to bound the
        # transient gather copy): nonzero mask -> boolean-select both
        # the keys and the payload rows; no per-container Python.
        key_blocks: list[np.ndarray] = []
        payload_blocks: list[np.ndarray] = []
        if self._slot_of:
            items = list(self._slot_of.items())
            BLOCK = 256  # rows per sweep: 32 MiB transient
            for b in range(0, len(items), BLOCK):
                chunk_items = items[b : b + BLOCK]
                rows_arr = np.asarray([r for r, _ in chunk_items], np.int64)
                slots = np.asarray([s for _, s in chunk_items], dtype=np.intp)
                # Bulk-import fragments allocate slots sequentially, so
                # the common case is a contiguous ascending run — slice
                # a VIEW instead of gather-copying 32 MiB per block.
                if len(slots) and (
                    slots[-1] - slots[0] == len(slots) - 1
                    and (np.diff(slots) == 1).all()
                ):
                    sub = self._plane[slots[0] : slots[-1] + 1]
                else:
                    sub = np.ascontiguousarray(self._plane[slots])
                sub = sub.reshape(len(chunk_items), cps, wpc)
                # Nonzero test on the u64 view: half the elements.
                nonzero = sub.view(np.uint64).any(axis=2)
                if not nonzero.any():
                    continue
                key_blocks.append(
                    (rows_arr[:, None] * cps + np.arange(cps)[None, :])[
                        nonzero
                    ].astype(np.uint64)
                )
                payload_blocks.append(sub[nonzero])
        if key_blocks:
            keys = np.concatenate(key_blocks)
            payloads = np.concatenate(payload_blocks)  # (n, wpc) uint32
            if len(keys) > 1 and not (np.diff(keys.view(np.int64)) > 0).all():
                order = np.argsort(keys, kind="stable")
                keys = keys[order]
                payloads = payloads[order]
            words2d = (
                np.ascontiguousarray(payloads)
                .view(np.uint64)
                .reshape(len(keys), wpc // 2)
            )
        else:
            keys = np.zeros(0, np.uint64)
            words2d = np.zeros((0, wpc // 2), np.uint64)
        # Sparse tier, vectorized across ALL rows at once: rows visit in
        # ascending order and offsets ascend within a row, so the global
        # key stream is non-decreasing — one unique() groups it.
        sp_rows = sorted(r for r in self._sparse if len(self._sparse[r]))
        if sp_rows:
            lens = np.asarray([len(self._sparse[r]) for r in sp_rows])
            rows_rep = np.repeat(np.asarray(sp_rows, dtype=np.int64), lens)
            offs_all = np.concatenate([self._sparse[r] for r in sp_rows])
            keys_all = rows_rep * bp.CONTAINERS_PER_SLICE + offs_all // cbits
            vals_all = (offs_all % cbits).astype(np.uint32)
            uniq_keys, starts = np.unique(keys_all, return_index=True)
            for j, k in enumerate(uniq_keys):
                hi = starts[j + 1] if j + 1 < len(starts) else len(vals_all)
                arrays[int(k)] = vals_all[starts[j] : hi]
        return keys, words2d, arrays

    def _row_words_host(self, row_id: int) -> np.ndarray | None:
        """One row's words on host (copy), whichever tier holds it.
        Takes the fragment lock itself (reentrant) — callers like the
        executor's host batch assembly read concurrently with writers
        that replace the plane or migrate rows between tiers."""
        with self._mu:
            slot = self._slot_of.get(row_id)
            if slot is not None:
                return self._plane[slot].copy()
            offs = self._sparse.get(row_id)
            if offs is None:
                return None
            return bp.np_columns_to_row(offs)

    # ------------------------------------------------------------------
    # reads
    # ------------------------------------------------------------------

    def row(self, row_id: int) -> RowBitmap:
        """Extract one row as a RowBitmap segment (reference:
        fragment.go:340-375 row via roaring.OffsetRange).

        Only dense-tier rows are cached: caching a materialized sparse
        row would cost 128 KiB per entry in an unbounded dict —
        reintroducing the rows x 128 KiB footprint the sparse tier
        removes."""
        with self._mu:
            seg = self._row_cache.get(row_id)
            if seg is None:
                seg = self._row_words_host(row_id)
                if seg is None:
                    seg = bp.empty_row()
                if row_id not in self._sparse:
                    self._row_cache[row_id] = seg
            return RowBitmap.from_segment(self.slice, seg.copy())

    def contains(self, row_id: int, column_id: int) -> bool:
        with self._mu:
            offset = self.pos(row_id, column_id) % SLICE_WIDTH
            slot = self._slot_of.get(row_id)
            if slot is not None:
                return bp.np_contains(self._plane, slot * SLICE_WIDTH + offset)
            offs = self._sparse.get(row_id)
            if offs is None:
                return False
            i = int(np.searchsorted(offs, offset))
            return i < len(offs) and int(offs[i]) == offset

    def count(self) -> int:
        """Total set bits — from the incrementally-maintained per-row
        counts: no plane scan and no device round-trip (the counts are
        exact under set/clear/import, like the reference's cached
        bitmap.n bookkeeping, bitmap.go:184-217)."""
        with self._mu:
            return sum(self._count_of.values())

    def row_counts(self) -> dict[int, int]:
        """{row_id: popcount} for every touched row (host-side, O(rows))."""
        with self._mu:
            return dict(self._count_of)

    # Above this many queued point writes, a full re-upload is cheaper
    # than the scatter program.
    _MAX_DEVICE_PENDING = 8192

    # Array-container values gathered per sweep in _load_direct (~1M
    # values -> ~25 MB scratch); tests shrink it to force multi-chunk
    # loads on small fixtures.
    _LOAD_CHUNK_VALUES = 1 << 20

    def _invalidate_device(self) -> None:
        """Bulk plane changes (import, restore, load) force a full
        re-upload; queued point updates would be stale.  The residency
        pool drops the mirror's accounting with it."""
        self._device = None
        self._device_version = -1
        self._device_pending.clear()
        self._pending_slots.clear()
        device_mod.pool().remove(self._pool_key)

    def _pool_info(self) -> dict:
        return {
            "fragment": f"{self.index}/{self.frame}/{self.view}/{self.slice}",
            "slice": self.slice,
        }

    def _evict_mirror(self) -> bool:
        """Residency-pool eviction hook: drop the HBM mirror.  The host
        plane is authoritative, so the next ``device_plane()`` rebuilds
        it — but ``_device_pending`` must clear COHERENTLY under the
        fragment lock: queued point writes describe deltas against the
        dropped mirror, and replaying them onto a freshly-uploaded
        (already current) plane would be wrong.  Non-blocking acquire:
        the pool may pick this fragment while another thread is inside
        ``device_plane()``; skipping an actively-used mirror is always
        safe, dropping it mid-upload is not."""
        if not self._mu.acquire(blocking=False):
            return False
        try:
            self._device = None
            self._device_version = -1
            self._device_pending.clear()
            self._pending_slots.clear()
            return True
        finally:
            self._mu.release()

    def _evict_sparse_rows(self) -> bool:
        """Residency-pool eviction hook for the paged-sparse-row cache:
        page everything out (rebuilt on demand from the host offset
        arrays)."""
        if not self._mu.acquire(blocking=False):
            return False
        try:
            self._sparse_dev.clear()
            self._sparse_dev_nbytes = 0
            return True
        finally:
            self._mu.release()

    def _sync_sparse_pool_locked(self) -> None:
        """Re-account the paged-sparse-row cache after it changed
        (page-in, write invalidation, promotion, bulk load).  Resident
        bytes are the COMPRESSED payload sizes; the pool entry's info
        carries the logical dense equivalent (rows x 128 KiB) and the
        container-format mix so /debug/hbm can report compressed vs
        logical.  Callers hold ``_mu``."""
        ents = self._sparse_dev.values()
        self._sparse_dev_nbytes = sum(e[2] for e in ents)
        n = len(self._sparse_dev)
        if n == 0:
            device_mod.pool().remove(self._sparse_pool_key)
        else:
            mix: dict[str, int] = {}
            for fmt, _dev, _nb in ents:
                name = bp.FMT_NAMES.get(fmt, str(fmt))
                mix[name] = mix.get(name, 0) + 1
            info = dict(self._pool_info())
            info["logical_bytes"] = n * ROW_NBYTES
            info["formats"] = mix
            device_mod.pool().resize(
                self._sparse_pool_key,
                {bp.home_device(self.slice): self._sparse_dev_nbytes},
                info=info,
            )

    @property
    def plane_nbytes(self) -> int:
        """Host dense-plane byte size — what a staged device mirror
        costs in HBM (pad_rows keeps the plane in pow2 row classes, so
        this is also the mirror's compile-shape bucket x 128 KiB).
        The staging/warming paths order and account by it."""
        return int(self._plane.nbytes)

    def device_plane(self):
        """The HBM mirror of the plane, pinned to the slice's home device
        (slice mod n_devices) so multi-device query batches assemble
        shard-local (parallel/mesh.home_device).  Point writes since the
        last read apply as one batched on-device scatter; bulk changes
        re-upload.  Every (re)upload admits through the residency pool
        FIRST, so LRU mirrors are evicted to make room and accounted
        residency never exceeds the HBM budget."""
        import jax

        with self._mu:
            pool = device_mod.pool()
            if self._device is not None and self._device_version != self._version:
                if self._device_pending:
                    # Incremental mirror maintenance: ONE fused scatter
                    # launch applies the queued deltas (ingest/scatter:
                    # pow2-bucketed update axis, no donation).  The pin
                    # lease keeps the pool from evicting the mirror
                    # between gather and scatter; publication is the
                    # plain attribute swap below, so a concurrent
                    # reader holding the OLD array sees a consistent
                    # (old) plane — version-fenced atomicity.
                    with pool.pinned(self._pool_key):
                        self._device = ingest_scatter.apply(
                            self._device, self._device_pending
                        )
                    self._device_pending.clear()
                    self._pending_slots.clear()
                    self._device_version = self._version
                else:
                    self._device = None
            if self._device is None or self._device_version != self._version:
                dev = bp.home_device(self.slice)
                pool.admit(
                    self._pool_key,
                    {dev: int(self._plane.nbytes)},
                    self._evict_mirror,
                    category="mirror",
                    info=self._pool_info(),
                )
                try:
                    self._device = jax.device_put(self._plane, dev)
                except BaseException:
                    pool.remove(self._pool_key)
                    raise
                pool.count_restage(int(self._plane.nbytes))
                self._device_pending.clear()
                self._pending_slots.clear()
                self._device_version = self._version
            else:
                pool.touch(self._pool_key)
            return self._device

    def has_row(self, row_id: int) -> bool:
        """Whether either tier holds the row (no device work)."""
        with self._mu:
            return row_id in self._slot_of or row_id in self._sparse

    def device_row(self, row_id: int):
        """One row as a device leaf for query plans (exec/plan.py).

        Dense rows gather from the HBM plane mirror (no host copy);
        sparse rows PAGE on demand — materialized host-side and
        device_put to the slice's home device, kept in a small LRU so
        repeated queries over the same sparse rows (e.g. inverse-view
        Bitmap calls) hit HBM (SURVEY.md §7 "row-block paging HBM<->host
        for sparse-tall frames")."""
        import jax

        with self._mu:
            slot = self._slot_of.get(row_id)
            if slot is not None:
                dev = self._device
                if (
                    dev is not None
                    and self._device_version != self._version
                    and slot not in self._pending_slots
                ):
                    # Row-level freshness: the mirror is stale only
                    # where queued deltas touch, and this row isn't
                    # among them (a change the queue can't express
                    # drops the mirror entirely), so the resident
                    # plane's row is byte-exact as-is.  Serving it
                    # directly keeps an ingest storm on OTHER rows from
                    # forcing a whole-plane sync onto every read.
                    device_mod.pool().touch(self._pool_key)
                    return dev[slot]
                return self.device_plane()[slot]
            ent = self._sparse_dev_entry_locked(row_id)
            if ent is None:
                return None
            fmt, dev, _nb = ent
            # Transient dense expansion for the stacking caller; the
            # resident cache keeps only the compressed payload, so HBM
            # never holds a decompressed staging copy.
            return bp.expand_payload(fmt, dev)

    def _sparse_dev_entry_locked(self, row_id: int):
        """The paged compressed-container entry ``(fmt, device_payload,
        encoded_nbytes)`` for a sparse-tier row, paging it in (pool
        admission first, at COMPRESSED bytes) on miss.  Callers hold
        ``_mu``; returns None when the row is absent."""
        import jax

        offs = self._sparse.get(row_id)
        if offs is None:
            return None
        ent = self._sparse_dev.get(row_id)
        if ent is not None:
            self._sparse_dev.move_to_end(row_id)
            device_mod.pool().touch(self._sparse_pool_key)
            return ent
        fmt, payload, nbytes = self._host_payload_locked(row_id, offs)
        home = bp.home_device(self.slice)
        device_mod.pool().admit(
            self._sparse_pool_key,
            {home: self._sparse_dev_nbytes + nbytes},
            self._evict_sparse_rows,
            category="sparse",
            info=self._pool_info(),
        )
        dev = jax.device_put(payload, home)
        ent = self._sparse_dev[row_id] = (fmt, dev, nbytes)
        while len(self._sparse_dev) > SPARSE_DEVICE_CACHE:
            self._sparse_dev.popitem(last=False)
        self._sync_sparse_pool_locked()
        return ent

    def _host_payload_locked(self, row_id: int, offs) -> tuple:
        """Write-time-selected container encoding of one sparse-tier
        row — ``(fmt, payload, encoded_nbytes)``, memoized until the
        row mutates (_after_write pops it, which is also how a write
        triggers format RE-selection: the next encode sees the new
        density)."""
        ent = self._payload_cache.get(row_id)
        if ent is None:
            ent = self._payload_cache[row_id] = bp.encode_row(offs)
        return ent

    def host_payload(self, row_id: int):
        """Host-side container view of any present row: ``(fmt,
        payload, encoded_nbytes, cardinality)``.  Dense-tier rows are
        FMT_DENSE views of the authoritative plane (callers copy into
        batches, never mutate); sparse-tier rows return the memoized
        compressed encoding.  None when the row is absent — the
        executor's anchored count assembles its format-dispatched leaf
        batches from this."""
        with self._mu:
            slot = self._slot_of.get(row_id)
            if slot is not None:
                return (
                    bp.FMT_DENSE,
                    self._plane[slot],
                    ROW_NBYTES,
                    self._count_of.get(row_id, 0),
                )
            offs = self._sparse.get(row_id)
            if offs is None:
                return None
            fmt, payload, nbytes = self._host_payload_locked(row_id, offs)
            return (fmt, payload, nbytes, len(offs))

    def row_positions(self, row_id: int):
        """Sorted uint32 in-slice positions of one present row (the
        anchored count's anchor vector), or None.  O(cardinality) for
        sparse-tier rows; dense-tier rows pay one 128 KiB plane-row
        scan."""
        with self._mu:
            slot = self._slot_of.get(row_id)
            if slot is not None:
                return bp.np_row_to_columns(self._plane[slot]).astype(
                    np.uint32
                )
            offs = self._sparse.get(row_id)
            if offs is None:
                return None
            return np.asarray(offs, dtype=np.uint32)

    def row_count(self, row_id: int) -> int:
        """Cached popcount of one row (0 when absent) — the anchored
        count's anchor-selection key, no plane scan."""
        with self._mu:
            return self._count_of.get(row_id, 0)

    # ------------------------------------------------------------------
    # writes (reference: fragment.go:379-473)
    # ------------------------------------------------------------------

    def set_bit(self, row_id: int, column_id: int) -> bool:
        with self._mu:
            self._check_writable_locked()
            pos = self.pos(row_id, column_id)
            offset = pos % SLICE_WIDTH
            grew = row_id > self._max_row_id
            slot = self._ensure_slot(row_id)
            if slot is not None:
                changed = bp.np_set_bit(self._plane, slot * SLICE_WIDTH + offset)
                if changed:
                    self._queue_device_update(slot, offset, 1)
            else:
                changed = self._sparse_insert(row_id, offset)
            if changed:
                self._append_op(roaring.OP_ADD, pos)
                self._after_write(row_id, +1)
                self.stats.count("setBit")  # reference: fragment.go:418
                if grew:
                    # reference: fragment.go:421-423
                    self.stats.gauge("rows", float(self._max_row_id))
                self._maybe_promote(row_id)
                if _write_listeners or self._frag_write_listeners:
                    _notify_write(
                        self, (row_id,), (column_id,), (), (), exact=True
                    )
            return changed

    def clear_bit(self, row_id: int, column_id: int) -> bool:
        with self._mu:
            self._check_writable_locked()
            pos = self.pos(row_id, column_id)
            offset = pos % SLICE_WIDTH
            slot = self._slot_of.get(row_id)
            if slot is not None:
                changed = bp.np_clear_bit(self._plane, slot * SLICE_WIDTH + offset)
                if changed:
                    self._queue_device_update(slot, offset, 0)
            elif row_id in self._sparse:
                changed = self._sparse_remove(row_id, offset)
            else:
                return False
            if changed:
                self._append_op(roaring.OP_REMOVE, pos)
                self._after_write(row_id, -1)
                self.stats.count("clearBit")  # reference: fragment.go:470
                if _write_listeners or self._frag_write_listeners:
                    _notify_write(
                        self, (), (), (row_id,), (column_id,), exact=True
                    )
            return changed

    def _sparse_insert(self, row_id: int, offset: int) -> bool:
        offs = self._sparse[row_id]
        i = int(np.searchsorted(offs, offset))
        if i < len(offs) and int(offs[i]) == offset:
            return False
        self._sparse[row_id] = np.insert(offs, i, np.uint32(offset))
        return True

    def _sparse_remove(self, row_id: int, offset: int) -> bool:
        offs = self._sparse[row_id]
        i = int(np.searchsorted(offs, offset))
        if i >= len(offs) or int(offs[i]) != offset:
            return False
        self._sparse[row_id] = np.delete(offs, i)
        return True

    def _queue_device_update(self, slot: int, offset: int, op: int) -> None:
        """Record a point write for the device mirror; overflow (or
        scatter disabled by config) degrades to a full re-upload on
        next read."""
        if self._device is None:
            return
        if not ingest_scatter.ENABLED:
            # Historical behavior: every point write invalidates the
            # mirror (and the next read re-stages the whole plane) —
            # kept as the config-off arm and the bench contrast.
            ingest_scatter.note_fallback()
            self._invalidate_device()
            return
        if len(self._device_pending) >= self._MAX_DEVICE_PENDING:
            ingest_scatter.note_fallback()
            self._invalidate_device()
            return
        word, shift = divmod(offset, bp.WORD_BITS)
        self._device_pending.append((slot, word, 1 << shift, op))
        self._pending_slots.add(slot)

    def apply_pending_scatter(self) -> bool:
        """Fold queued point-write deltas into the device mirror NOW
        (one fused scatter launch) instead of at the next read.  The
        ingest committer calls this on its group-commit tick, so a read
        storm usually finds the mirror already clean and pays nothing.
        No-op unless a mirror is resident with queued deltas; returns
        True when a launch was dispatched."""
        with self._mu:
            if (
                self._device is None
                or self._device_version == self._version
                or not self._device_pending
            ):
                return False
            pool = device_mod.pool()
            with pool.pinned(self._pool_key):
                self._device = ingest_scatter.apply(
                    self._device, self._device_pending
                )
            self._device_pending.clear()
            self._pending_slots.clear()
            self._device_version = self._version
            pool.touch(self._pool_key)
            return True

    def _queue_import_updates_locked(
        self, set_slots, set_offs, clr_slots, clr_offs
    ) -> None:
        """Queue a bulk import's dense-plane bits as scatter deltas when
        the import is small enough; otherwise fall back to full mirror
        invalidation (one re-upload beats thousands of folded updates,
        and sparse-tier bits never touch the mirror anyway)."""
        n = (0 if set_slots is None else len(set_slots)) + (
            0 if clr_slots is None else len(clr_slots)
        )
        if (
            self._device is None
            or not ingest_scatter.ENABLED
            or n == 0
            or n > ingest_scatter.IMPORT_SCATTER_MAX
            or len(self._device_pending) + n > self._MAX_DEVICE_PENDING
        ):
            if self._device is not None:
                ingest_scatter.note_fallback()
            self._invalidate_device()
            return
        for slots, offs_a, op in (
            (set_slots, set_offs, 1),
            (clr_slots, clr_offs, 0),
        ):
            if slots is None:
                continue
            words, shifts = np.divmod(
                np.asarray(offs_a, dtype=np.int64), bp.WORD_BITS
            )
            for slot, word, shift in zip(slots, words, shifts):
                self._device_pending.append(
                    (int(slot), int(word), 1 << int(shift), op)
                )
                self._pending_slots.add(int(slot))

    def _after_write(self, row_id: int, delta: int) -> None:
        self._version += 1
        _bump_write_epoch()
        self._row_cache.pop(row_id, None)
        # Dropping the encoded payload IS the format re-selection hook:
        # the next read re-encodes at the row's new density (a sparse
        # row crossing a threshold lands in a different container).
        self._payload_cache.pop(row_id, None)
        if self._sparse_dev.pop(row_id, None) is not None:
            self._sync_sparse_pool_locked()
        self._dirty_blocks.add(row_id // HASH_BLOCK_SIZE)
        n = self._count_of[row_id] = self._count_of.get(row_id, 0) + delta
        self.cache.add(row_id, n)
        self._op_n += 1
        if self._op_n >= self.max_op_n and not self._wal_replaying:
            # Mid-replay snapshots would truncate the WAL segment being
            # replayed; recovery checkpoints once, after the replay.
            self.snapshot()

    # Flush the op buffer once it holds this many bytes (~5k ops) even
    # between boundaries, bounding worst-case loss and memory.
    _OP_FLUSH_BYTES = roaring.OP_FLUSH_BYTES

    def _append_op(self, typ: int, pos: int) -> None:
        if self._file is not None:
            self._op_buf += roaring.encode_op(typ, pos)
            if len(self._op_buf) >= self._OP_FLUSH_BYTES:
                self._flush_ops_locked()
        if self._wal is not None and not self._wal_replaying:
            # Log-before-ack: the same changed-op record goes to the
            # WAL; the ack path waits on its group-commit fsync
            # (executor wait_durable).  During recovery replay the op
            # is already IN the WAL.  A shutdown race (writer closed
            # under us) degrades to the historical op-buf durability.
            try:
                self._wal.log(typ, pos)
            except ingest_wal.WalClosed:
                pass

    def _flush_ops_locked(self) -> None:
        if self._op_buf and self._file is not None:
            self._file.seek(0, os.SEEK_END)
            self._file.write(self._op_buf)
            self._file.flush()
        self._op_buf.clear()

    def flush_ops(self) -> None:
        """Group-commit boundary: persist buffered op-log records."""
        with self._mu:
            self._flush_ops_locked()

    def import_bulk(
        self,
        row_ids: Sequence[int],
        column_ids: Sequence[int],
        clear_row_ids: Sequence[int] | None = None,
        clear_column_ids: Sequence[int] | None = None,
    ) -> None:
        """Bulk load: op-log off, vectorized scatter, cache recount per
        touched row, snapshot (reference: fragment.go:936-1004).

        ``clear_row_ids``/``clear_column_ids`` optionally clear bits in
        the same pass (one snapshot, one recount) — the overwrite half
        of a BSI value import.  Clears never create rows; a clear on an
        absent row is a no-op.  A bit must not appear in both lists."""
        clear_row_ids = clear_row_ids if clear_row_ids is not None else []
        clear_column_ids = (
            clear_column_ids if clear_column_ids is not None else []
        )
        if len(row_ids) != len(column_ids) or len(clear_row_ids) != len(
            clear_column_ids
        ):
            raise FragmentError("mismatch of row/column len")
        if len(row_ids) == 0 and len(clear_row_ids) == 0:
            return
        with self._mu:
            self._check_writable_locked()
            rows = np.asarray(row_ids, dtype=np.int64)
            cols = np.asarray(column_ids, dtype=np.int64)
            min_col = self.slice * SLICE_WIDTH
            if ((cols < min_col) | (cols >= min_col + SLICE_WIDTH)).any():
                raise FragmentError("column out of bounds for slice")
            offs = cols % SLICE_WIDTH
            uniq = np.unique(rows)
            # Pre-size the dense plane once for every row this import
            # can add (one allocation, not O(log n) doubling copies).
            n_new = sum(
                1 for r in uniq
                if int(r) not in self._slot_of and int(r) not in self._sparse
            )
            self._reserve_dense(
                min(len(self._slot_of) + n_new, self.dense_row_budget)
            )
            slot_of = {int(r): self._ensure_slot(int(r)) for r in uniq}

            # Per-row slot resolution through a per-UNIQUE-row table:
            # O(unique) Python work + one vectorized gather, instead of
            # a per-bit comprehension.
            slot_table = np.asarray(
                [-1 if slot_of[int(r)] is None else slot_of[int(r)] for r in uniq],
                dtype=np.int64,
            )
            slots_all = slot_table[np.searchsorted(uniq, rows)]
            dense_mask = slots_all >= 0
            imp_set_slots = imp_set_offs = None
            imp_clr_slots = imp_clr_offs = None
            if dense_mask.any():
                imp_set_slots = slots_all[dense_mask]
                imp_set_offs = offs[dense_mask]
                bp.np_set_bulk(self._plane, imp_set_slots, imp_set_offs)
            if not dense_mask.all():
                s_rows = rows[~dense_mask]
                s_offs = offs[~dense_mask].astype(np.uint32)
                order = np.lexsort((s_offs, s_rows))
                s_rows, s_offs = s_rows[order], s_offs[order]
                uniq_s = np.unique(s_rows)
                starts = np.searchsorted(s_rows, uniq_s)
                for i, r in enumerate(uniq_s):
                    hi = starts[i + 1] if i + 1 < len(starts) else len(s_rows)
                    seg = s_offs[starts[i] : hi]
                    cur = self._sparse[int(r)]
                    if len(cur) == 0:
                        # brand-new row (the tall-import common case):
                        # the sorted segment IS the row, minus dups
                        merged = seg[
                            np.insert(np.diff(seg) != 0, 0, True)
                        ] if len(seg) > 1 else seg
                    else:
                        merged = np.union1d(cur, seg).astype(np.uint32)
                    self._sparse[int(r)] = merged

            # ---- clears (the BSI overwrite path): clears only touch
            # rows that EXIST; dense rows take one vectorized andnot
            # scatter, sparse rows a per-row sorted difference.
            if len(clear_row_ids):
                c_rows = np.asarray(clear_row_ids, dtype=np.int64)
                c_cols = np.asarray(clear_column_ids, dtype=np.int64)
                if ((c_cols < min_col) | (c_cols >= min_col + SLICE_WIDTH)).any():
                    raise FragmentError("column out of bounds for slice")
                c_offs = c_cols % SLICE_WIDTH
                for r in np.unique(c_rows):
                    r = int(r)
                    if r in slot_of:
                        continue
                    slot = self._slot_of.get(r)
                    if slot is None and r not in self._sparse:
                        continue  # clears never create rows
                    slot_of[r] = slot
                c_keep = np.asarray(
                    [int(r) in slot_of for r in c_rows], dtype=bool
                )
                c_rows, c_offs = c_rows[c_keep], c_offs[c_keep]
                c_slots = np.asarray(
                    [
                        -1 if slot_of[int(r)] is None else slot_of[int(r)]
                        for r in c_rows
                    ],
                    dtype=np.int64,
                )
                dm = c_slots >= 0
                if dm.any():
                    imp_clr_slots = c_slots[dm]
                    imp_clr_offs = c_offs[dm]
                    bp.np_clear_bulk(self._plane, imp_clr_slots, imp_clr_offs)
                if (~dm).any():
                    s_rows = c_rows[~dm]
                    s_offs = c_offs[~dm].astype(np.uint32)
                    for r in np.unique(s_rows):
                        self._sparse[int(r)] = np.setdiff1d(
                            self._sparse[int(r)], s_offs[s_rows == r]
                        ).astype(np.uint32)
                uniq = np.union1d(uniq, np.unique(c_rows)).astype(np.int64)

            self._version += 1
            _bump_write_epoch()
            self._queue_import_updates_locked(
                imp_set_slots, imp_set_offs, imp_clr_slots, imp_clr_offs
            )
            self._sparse_dev.clear()
            self._payload_cache.clear()
            self._sync_sparse_pool_locked()
            self._row_cache.clear()
            self._dirty_blocks.update(int(r) // HASH_BLOCK_SIZE for r in uniq)
            d_items = [(r, s) for r, s in slot_of.items() if s is not None]
            if d_items:
                cnts = bp.np_row_counts(
                    self._plane[np.asarray([s for _, s in d_items])]
                )
            for i, (r, _) in enumerate(d_items):
                self._count_of[r] = int(cnts[i])
                self.cache.bulk_add(r, int(cnts[i]))
            for r, s in slot_of.items():
                if s is None:
                    n = len(self._sparse[r])
                    self._count_of[r] = n
                    self.cache.bulk_add(r, n)
            for r in uniq:
                self._maybe_promote(int(r))
            self.cache.invalidate()
            self.cache.recalculate()
            self.stats.count("ImportBit", len(row_ids))  # ref: fragment.go:969
            if _write_listeners or self._frag_write_listeners:
                _notify_write(
                    self, row_ids, column_ids, clear_row_ids, clear_column_ids
                )
            self.snapshot()

    def snapshot(self) -> None:
        """Full roaring serialization atomically renamed over the data
        file; resets the op count (reference: fragment.go:1032-1074)."""
        with self._mu:
            t0 = time.perf_counter()
            # Buffered ops are subsumed by the serialized state below.
            self._op_buf.clear()
            data = roaring.encode_packed(*self._containers_packed())
            tmp = self.path + ".snapshotting"
            with open(tmp, "wb") as fh:
                fh.write(data)
                fh.flush()
                os.fsync(fh.fileno())
            if self._file is not None:
                fcntl.flock(self._file.fileno(), fcntl.LOCK_UN)
                self._file.close()
            os.replace(tmp, self.path)
            # The rename is durable only once the DIRECTORY entry is
            # synced — without this, a crash after the replace can
            # resurrect the pre-snapshot file (with its now-truncated
            # WAL gone), silently losing the snapshot.
            ingest_wal._fsync_dir(self.path)
            self._file = open(self.path, "a+b")
            fcntl.flock(self._file.fileno(), fcntl.LOCK_EX | fcntl.LOCK_NB)
            self._op_n = 0
            if self._wal is not None:
                # Every op the WAL covers is captured by the (now
                # durable) snapshot: restart the segment at the new
                # base version.  len(data) is the fresh file's op
                # region offset, identifying WHICH snapshot this
                # segment was truncated against.
                self._wal.truncate_segment(len(data))
            # reference: fragment.go:1026-1030
            self.stats.histogram("snapshot", time.perf_counter() - t0)

    # ------------------------------------------------------------------
    # TopN engine (reference: fragment.go:505-673)
    # ------------------------------------------------------------------

    def top(self, opt: TopOptions | None = None) -> list[Pair]:
        """Concurrent-read safe: the candidate listing and the plane
        gather each take the fragment lock briefly, but the device score
        fetch runs OUTSIDE it (the gathered submatrix is an immutable
        device snapshot) — so parallel TopN queries overlap their device
        round trips instead of serializing on the fragment, matching the
        reference's RWMutex read-side concurrency (fragment.go:507)."""
        return self.top_finish(self.top_prepare(opt))

    def top_prepare(self, opt: TopOptions | None = None) -> "TopState":
        """Phase 1 of TopN on this fragment: candidate selection, sparse
        scoring, and the ASYNC dispatch of the dense score kernel —
        everything except the device->host fetch.  The executor prepares
        every local slice first and fetches ALL their score vectors in
        one device round trip (mapperLocal's TPU shape: one transfer per
        node per phase, not one per slice)."""
        opt = opt or TopOptions()
        with self._mu:
            ids, cnts = self._top_candidates_arrays(opt.row_ids)
        return self._top_score_prepare(ids, cnts, opt, bool(opt.row_ids))

    def top_prepare_parts(self, opt: TopOptions | None = None):
        """top_prepare WITHOUT the dense-kernel dispatch: returns
        ``(TopState, sub, src_words)`` so the executor can batch many
        fragments' score kernels into one program (see
        bp.score_planes)."""
        opt = opt or TopOptions()
        with self._mu:
            ids, cnts = self._top_candidates_arrays(opt.row_ids)
        return self._top_score_parts(ids, cnts, opt, bool(opt.row_ids))

    def top_finish(self, st: "TopState") -> list[Pair]:
        """Phase 2: resolve the dense score fetch (or accept one already
        fetched in bulk via ``st.counts``) and apply the final
        threshold/tanimoto selection.  Expressed over
        ``top_score_arrays`` so the scoring arithmetic has exactly one
        implementation."""
        ids, cnts, keep, short = self.top_score_arrays(st)
        if not short:
            ids, cnts = ids[keep], cnts[keep]
            order = np.lexsort((ids, -cnts))  # sort_pairs' (-count, id)
            if st.n:
                order = order[: st.n]
            ids, cnts = ids[order], cnts[order]
        return [Pair(int(i), int(c)) for i, c in zip(ids, cnts)]

    def top_candidates_arrays(
        self, opt: TopOptions | None = None
    ) -> tuple[np.ndarray, np.ndarray]:
        """(ids, cached counts) of the filtered candidate listing phase-1
        scoring would use (cache ranking + threshold/tanimoto-window/attr
        filters) — host-only, no device work, array-native.  The
        executor's folded TopN uses this to form the cross-slice
        candidate union before any scoring dispatch."""
        opt = opt or TopOptions()
        with self._mu:
            ids, cnts = self._top_candidates_arrays(opt.row_ids)
        ids, cnts, _, _ = self._filter_arrays(ids, cnts, opt)
        return ids, cnts

    def _filter_arrays(
        self, ids: np.ndarray, cnts: np.ndarray, opt: TopOptions
    ) -> tuple[np.ndarray, np.ndarray, int, int]:
        """Candidate filtering on cached counts, vectorized (reference:
        fragment.go:535-594 candidate loop).  Returns
        ``(ids, cnts, tanimoto, src_count)`` with the filters applied;
        attr filters fall back to a per-survivor dict probe (they need
        the attr store either way)."""
        tanimoto = 0
        src_count = 0
        mask = cnts > 0
        if opt.tanimoto_threshold > 0 and opt.src is not None:
            tanimoto = opt.tanimoto_threshold
            src_count = opt.src.count()
            min_tan = float(src_count * tanimoto) / 100
            max_tan = float(src_count * 100) / float(tanimoto)
            mask &= (cnts > min_tan) & (cnts < max_tan)
        elif opt.min_threshold:
            mask &= cnts >= opt.min_threshold
        if opt.filter_field and opt.filter_values:
            filters = set()
            for v in opt.filter_values:
                try:
                    filters.add(v)
                except TypeError:
                    pass
            store = self.row_attr_store
            if store is None:
                mask[:] = False
            else:
                for k in np.flatnonzero(mask):
                    attrs = store.attrs(int(ids[k]))
                    if not attrs or attrs.get(opt.filter_field) not in filters:
                        mask[k] = False
        return ids[mask], cnts[mask], tanimoto, src_count

    @staticmethod
    def select_winners(
        ids: np.ndarray,
        cnts: np.ndarray,
        keep: np.ndarray,
        cand_ids: np.ndarray,
        n: int,
        cand_mask: np.ndarray | None = None,
    ) -> tuple[np.ndarray, np.ndarray]:
        """Phase-1 winner selection over a scored union restricted to
        ``cand_ids``: filter mask, (-count, id) sort (sort_pairs'
        canonical order), trim to ``n``.  The ONE implementation of the
        phase-1 selection rule (consumed by the executor's folded
        TopN).  ``cand_mask`` optionally pre-resolves the
        ``isin(ids, cand_ids)`` membership (the executor's prep cache
        computes it once per query shape)."""
        m = keep & (
            cand_mask if cand_mask is not None else np.isin(ids, cand_ids)
        )
        sel_ids, sel_cnts = ids[m], cnts[m]
        order = np.lexsort((sel_ids, -sel_cnts))
        if n:
            order = order[:n]
        return sel_ids[order], sel_cnts[order]

    _EMPTY_I64 = np.empty(0, np.int64)

    def _top_score_prepare(
        self,
        ids: np.ndarray,
        cached: np.ndarray,
        opt: TopOptions,
        row_ids_mode: bool,
    ) -> "TopState":
        st, sub_ref, src_words = self._top_score_parts(
            ids, cached, opt, row_ids_mode
        )
        if sub_ref is not None:
            # ASYNC dispatch — the fetch happens in top_finish.  The
            # gather reads sub_ref.plane (the snapshot captured under
            # the lock), never the live mirror: a concurrent write
            # could reorder the slot layout out from under the
            # prepared slot indices.
            st.dev_counts = bp.top_counts(
                sub_ref.plane[sub_ref.slots], src_words
            )
        return st

    def _top_score_parts(
        self,
        ids: np.ndarray,
        cached: np.ndarray,
        opt: TopOptions,
        row_ids_mode: bool,
    ):
        """Everything in a scoring pass EXCEPT the dense-kernel
        dispatch: returns ``(TopState, sub, src_words)`` where ``sub``
        (the gathered device submatrix, or None) and ``src_words`` let
        the executor score MANY fragments in one batched program
        (bp.score_planes) instead of one dispatch per slice.

        ``ids``/``cached`` are the (unfiltered) candidate arrays in
        count-descending order; ``row_ids_mode`` mirrors the reference's
        explicit-ids behavior of returning every scored row (n applies
        only to ranked-cache candidates, reference: fragment.go:516)."""
        n = 0 if row_ids_mode else opt.n
        ids, cached, tanimoto, src_count = self._filter_arrays(ids, cached, opt)

        if opt.src is None:
            # No intersection: cached counts are final.  Candidates are
            # already count-descending; take the first n.
            if n and n < len(ids):
                ids, cached = ids[:n], cached[:n]
            return TopState(done_ids=ids, done_cnts=cached), None, None

        # Batched intersection scoring: one fused kernel over all
        # candidate rows at once (replaces the reference's sequential
        # threshold-pruned loop, fragment.go:601-627).
        if not len(ids):
            return (
                TopState(done_ids=self._EMPTY_I64, done_cnts=self._EMPTY_I64),
                None,
                None,
            )
        src_seg = opt.src.segments.get(self.slice)
        if src_seg is None:
            return (
                TopState(done_ids=self._EMPTY_I64, done_cnts=self._EMPTY_I64),
                None,
                None,
            )
        src_words = np.asarray(src_seg, dtype=np.uint32)
        with self._mu:
            slot_ids, slot_vals, sparse_sorted = self._tier_key_arrays_locked()
            dense_pos = np.flatnonzero(np.isin(ids, slot_ids))
            sparse_pos = np.flatnonzero(np.isin(ids, sparse_sorted))
            if not len(dense_pos) and not len(sparse_pos):
                return (
                    TopState(
                        done_ids=self._EMPTY_I64, done_cnts=self._EMPTY_I64
                    ),
                    None,
                    None,
                )
            sub_ref = None
            if len(dense_pos):
                # Candidate rows gather from the HBM-resident plane —
                # only the src row and slot indices travel host->device.
                # The gather itself is LAZY (SubRef): the executor's
                # stacked-batch cache usually already holds the rows.
                slots = slot_vals[
                    np.searchsorted(slot_ids, ids[dense_pos])
                ].astype(np.int32)
                # Pad to a full row block (repeating the last slot) so
                # the scorer's row count stays on the tile-aligned
                # kernel path; surplus scores are discarded on read.
                padded = bp.pad_rows(len(slots))
                if padded != len(slots):
                    slots = np.pad(slots, (0, padded - len(slots)), mode="edge")
                sub_ref = SubRef(
                    plane=self.device_plane(),
                    slots=slots,
                    shape=(padded, bp.WORDS_PER_SLICE),
                    plane_rows=int(self._plane.shape[0]),
                    device=bp.home_device(self.slice),
                )
            # Sparse candidates (the low-count tail) score host-side in
            # O(set bits): probe src's words at each offset.
            sparse_cnt = np.empty(len(sparse_pos), np.int64)
            for j, k in enumerate(sparse_pos):
                offs = self._sparse[int(ids[k])]
                sparse_cnt[j] = int(
                    ((src_words[offs >> 5] >> (offs & np.uint32(31)))
                     & np.uint32(1)).sum()
                )
        st = TopState(
            cand_ids=ids,
            cand_cached=cached,
            dense_pos=dense_pos,
            sparse_pos=sparse_pos,
            sparse_cnt=sparse_cnt,
            n=n,
            tanimoto=tanimoto,
            src_count=src_count,
            min_threshold=opt.min_threshold,
        )
        return st, sub_ref, src_words

    def _tier_key_arrays_locked(self):
        """Sorted key arrays of the two row tiers, cached per fragment
        version: ``(slot_ids_sorted, slot_vals_aligned, sparse_ids_
        sorted)`` — turns the per-candidate dict membership walk into
        three vector ops.  Callers hold ``_mu``."""
        if self._tier_arrays is None or self._tier_arrays_version != self._version:
            sids = np.fromiter(self._slot_of.keys(), np.int64, len(self._slot_of))
            svals = np.fromiter(
                self._slot_of.values(), np.int64, len(self._slot_of)
            )
            order = np.argsort(sids)
            spids = np.sort(
                np.fromiter(self._sparse.keys(), np.int64, len(self._sparse))
            )
            self._tier_arrays = (sids[order], svals[order], spids)
            self._tier_arrays_version = self._version
        return self._tier_arrays

    def top_score_arrays(
        self, st: "TopState"
    ) -> tuple[np.ndarray, np.ndarray, np.ndarray, bool]:
        """Vectorized view of a scoring pass: ``(ids, counts, keep,
        done)`` over the candidates in candidate order, where ``keep``
        is the threshold/tanimoto filter mask ``top_finish`` would apply
        element-wise.  ``done=True`` means the pass short-circuited and
        ``ids/counts`` are that final, already-filtered list with
        ``keep`` all-true.

        The folded executor TopN consumes this instead of ``top_finish``:
        at 2k candidates x several calls per query, building and merging
        Pair objects in Python dominated warm TopN host time; the numpy
        formulation does the identical arithmetic in a few vector ops.
        """
        if st.done_ids is not None:
            return (
                st.done_ids,
                st.done_cnts,
                np.ones(len(st.done_ids), dtype=bool),
                True,
            )
        ids, cached = st.cand_ids, st.cand_cached
        cnts = np.zeros(len(ids), np.int64)
        if st.dense_pos is not None and len(st.dense_pos):
            if st.counts is None:
                st.counts = np.asarray(st.dev_counts)
            cnts[st.dense_pos] = np.asarray(
                st.counts[: len(st.dense_pos)], dtype=np.int64
            )
        if st.sparse_pos is not None and len(st.sparse_pos):
            cnts[st.sparse_pos] = st.sparse_cnt
        if st.tanimoto > 0:
            denom = cached + st.src_count - cnts
            with np.errstate(divide="ignore", invalid="ignore"):
                score = np.ceil(cnts * 100.0 / denom)
            keep = (cnts > 0) & (score > st.tanimoto)
        else:
            keep = (cnts > 0) & (cnts >= st.min_threshold)
        return ids, cnts, keep, False

    def _top_candidates_arrays(
        self, row_ids: list[int] | None
    ) -> tuple[np.ndarray, np.ndarray]:
        """reference: fragment.go:641-673 topBitmapPairs"""
        if not row_ids:
            # invalidate() is throttle-aware: the re-sort happens at most
            # every RECALCULATE_INTERVAL_S (reference: cache.go:236-241).
            self.cache.invalidate()
            return self.cache.top_arrays()
        ids, cnts = [], []
        # Dedupe explicit ids: a duplicated id would be scored twice and
        # its counts SUMMED by the cross-slice merge (and break the
        # assume_unique contract of top_prepare_union's setdiff).
        for row_id in dict.fromkeys(row_ids):
            c = self._row_count_locked(row_id)
            if c > 0:
                ids.append(row_id)
                cnts.append(c)
        ids = np.asarray(ids, np.int64)
        cnts = np.asarray(cnts, np.int64)
        order = np.lexsort((ids, -cnts))
        return ids[order], cnts[order]

    def _row_count_locked(self, row_id: int) -> int:
        """Count resolution for candidate listing (callers hold _mu):
        cached ranking first, then the O(1) maintained count, with
        full-row materialization (128 KiB unpack) only as a consistency
        safety net."""
        n = self.cache.get(row_id)
        if n <= 0 and (row_id in self._slot_of or row_id in self._sparse):
            n = self._count_of.get(row_id, 0)
            if n <= 0:
                n = self.row(row_id).count()
        return n

    def top_prepare_union_parts(
        self,
        union_ids: np.ndarray,
        cand_ids: np.ndarray,
        cand_cnts: np.ndarray,
        opt: TopOptions,
    ):
        """The folded executor TopN's union scoring pass WITHOUT the
        dense-kernel dispatch (see top_prepare_parts): equivalent to
        ``top_prepare(replace(opt, row_ids=union))`` but reuses the
        already-listed candidate arrays, resolving counts only for
        union ids this slice's own cache walk didn't produce (foreign
        winners) — O(missing) host work instead of O(union).
        ``union_ids`` must be unique (np.unique output)."""
        with self._mu:
            foreign = np.setdiff1d(union_ids, cand_ids, assume_unique=True)
            f_cnts = np.fromiter(
                (self._row_count_locked(int(r)) for r in foreign),
                np.int64,
                len(foreign),
            )
        fm = f_cnts > 0
        all_ids = np.concatenate([cand_ids, foreign[fm]])
        all_cnts = np.concatenate([cand_cnts, f_cnts[fm]])
        order = np.lexsort((all_ids, -all_cnts))
        return self._top_score_parts(
            all_ids[order], all_cnts[order], opt, row_ids_mode=True
        )

    # ------------------------------------------------------------------
    # block checksums + sync (reference: fragment.go:694-934)
    # ------------------------------------------------------------------

    def checksum(self) -> bytes:
        """SHA1 over the block checksums (reference: fragment.go:694-701)."""
        h = hashlib.sha1()
        for _, chk in self.blocks():
            h.update(chk)
        return h.digest()

    def blocks(self) -> list[tuple[int, bytes]]:
        """[(block_id, sha1)] per HASH_BLOCK_SIZE rows; empty blocks are
        skipped (reference: fragment.go:717-796).  Checksums hash the
        sorted (row, offset) BIT POSITIONS of the block — like the
        reference, which hashes positions rather than raw storage — so
        they depend only on logical content, identical across tiers and
        replicas."""
        with self._mu:
            by_block: dict[int, list[int]] = {}
            for r in self._slot_of:
                by_block.setdefault(r // HASH_BLOCK_SIZE, []).append(r)
            for r in self._sparse:
                by_block.setdefault(r // HASH_BLOCK_SIZE, []).append(r)
            out = []
            for block_id in sorted(by_block):
                if (
                    block_id in self._block_sums
                    and block_id not in self._dirty_blocks
                ):
                    chk = self._block_sums[block_id]
                else:
                    rws, cls = self._block_positions(
                        block_id, by_block[block_id]
                    )
                    chk = (
                        hashlib.sha1(
                            rws.astype("<u8").tobytes()
                            + cls.astype("<u8").tobytes()
                        ).digest()
                        if len(rws)
                        else None
                    )
                    self._block_sums[block_id] = chk
                    self._dirty_blocks.discard(block_id)
                if chk is not None:
                    out.append((block_id, chk))
            return out

    def _block_positions(
        self, block_id: int, rows: list[int] | None = None
    ) -> tuple[np.ndarray, np.ndarray]:
        """Sorted (rows, col-offsets) of every set bit in a block, from
        both tiers.  ``rows`` (any order) skips the full-dict scan when
        the caller already grouped rows by block — blocks() would
        otherwise rescan every row per block."""
        lo = block_id * HASH_BLOCK_SIZE
        hi = lo + HASH_BLOCK_SIZE
        if rows is None:
            rows = [r for r in self._slot_of if lo <= r < hi] + [
                r for r in self._sparse if lo <= r < hi
            ]
        rows = sorted(rows)
        segs: list[np.ndarray] = []
        seg_rows: list[int] = []
        for r in rows:
            slot = self._slot_of.get(r)
            if slot is not None:
                offs = bp.np_row_to_columns(self._plane[slot]).astype(np.int64)
            else:
                offs = self._sparse[r].astype(np.int64)
            if len(offs):
                segs.append(offs)
                seg_rows.append(r)
        if not segs:
            return np.empty(0, np.int64), np.empty(0, np.int64)
        lens = np.asarray([len(s) for s in segs])
        rws = np.repeat(np.asarray(seg_rows, dtype=np.int64), lens)
        return rws, np.concatenate(segs)

    def block_data(self, block_id: int) -> PairSet:
        """All (row, col-offset) bits in a block (reference:
        fragment.go:798-808)."""
        with self._mu:
            rws, cls = self._block_positions(block_id)
            # .tolist() materializes Python ints in C, not per-element
            # Python-loop conversion.
            return PairSet(row_ids=rws.tolist(), column_ids=cls.tolist())

    def merge_block(
        self, block_id: int, data: list[PairSet]
    ) -> tuple[list[PairSet], list[PairSet]]:
        """Majority-consensus merge of replicas' block data (reference:
        fragment.go:810-934): a bit is set iff >= (n+1+1)//2 of the n+1
        participants have it (ties -> set).  Applies the local diff and
        returns (sets, clears) per *remote* participant.

        Note: the reference has a bookkeeping slip in its clears-diff
        construction (clears[i].RowIDs appended from sets[i].RowIDs,
        fragment.go:913); this implementation computes the clears
        correctly rather than reproducing the bug.
        """
        for i, ps in enumerate(data):
            if len(ps.row_ids) != len(ps.column_ids):
                raise FragmentError(
                    f"pair set mismatch(idx={i}): "
                    f"{len(ps.row_ids)} != {len(ps.column_ids)}"
                )
        with self._mu:
            lo_row = block_id * HASH_BLOCK_SIZE
            hi_row = (block_id + 1) * HASH_BLOCK_SIZE

            local = self.block_data(block_id)
            participants = [local] + list(data)

            def to_pos(ps: PairSet) -> np.ndarray:
                if not ps.row_ids:
                    return np.empty(0, dtype=np.int64)
                r = np.asarray(ps.row_ids, dtype=np.int64)
                c = np.asarray(ps.column_ids, dtype=np.int64)
                keep = (r >= lo_row) & (r < hi_row) & (c >= 0) & (c < SLICE_WIDTH)
                return np.unique(r[keep] * SLICE_WIDTH + c[keep])

            pos_sets = [to_pos(ps) for ps in participants]
            all_pos = np.concatenate(pos_sets) if pos_sets else np.empty(0, np.int64)
            if all_pos.size == 0:
                return ([PairSet() for _ in data], [PairSet() for _ in data])
            uniq, votes = np.unique(all_pos, return_counts=True)
            majority_n = (len(participants) + 1) // 2
            consensus = votes >= majority_n

            sets_out: list[PairSet] = []
            clears_out: list[PairSet] = []
            for pos in pos_sets:
                has = np.isin(uniq, pos)
                to_set = uniq[consensus & ~has]
                to_clear = uniq[~consensus & has]
                sets_out.append(
                    PairSet(
                        row_ids=[int(p) // SLICE_WIDTH for p in to_set],
                        column_ids=[int(p) % SLICE_WIDTH for p in to_set],
                    )
                )
                clears_out.append(
                    PairSet(
                        row_ids=[int(p) // SLICE_WIDTH for p in to_clear],
                        column_ids=[int(p) % SLICE_WIDTH for p in to_clear],
                    )
                )

            base = self.slice * SLICE_WIDTH
            for r, c in zip(sets_out[0].row_ids, sets_out[0].column_ids):
                self.set_bit(r, base + c)
            for r, c in zip(clears_out[0].row_ids, clears_out[0].column_ids):
                self.clear_bit(r, base + c)

            return sets_out[1:], clears_out[1:]

    # ------------------------------------------------------------------
    # archive backup/restore (reference: fragment.go:1112-1283)
    # ------------------------------------------------------------------

    def _archive_payloads(self) -> list[tuple[str, bytes]]:
        """Consistent snapshot of the archive entries, taken under the
        lock; serialization to tar happens lock-free so a slow consumer
        never stalls writers.

        The archive SELF-VERIFIES: a leading "checksum" entry carries
        the sha256 of every payload entry, so restore (and the tier
        store's get) rejects torn bytes with
        :class:`ArchiveChecksumError` instead of installing them —
        previously only ``rebalance/`` checksummed, out-of-band."""
        with self._mu:
            data = roaring.encode_packed(*self._containers_packed())
            cache_data = self._encode_cache_ids(self.cache.ids())
        sums = json.dumps(
            {
                "algo": "sha256",
                "entries": {
                    "data": hashlib.sha256(data).hexdigest(),
                    "cache": hashlib.sha256(cache_data).hexdigest(),
                },
            },
            separators=(",", ":"),
        ).encode()
        return [("checksum", sums), ("data", data), ("cache", cache_data)]

    @staticmethod
    def _write_archive(entries: list[tuple[str, bytes]], w) -> None:
        tw = tarfile.open(fileobj=w, mode="w|")
        for name, payload in entries:
            info = tarfile.TarInfo(name)
            info.size = len(payload)
            info.mtime = int(time.time())
            tw.addfile(info, io.BytesIO(payload))
        tw.close()

    def write_to(self, w) -> None:
        """Stream a tar with "data" (roaring file) and "cache" entries."""
        self._write_archive(self._archive_payloads(), w)

    def tar_chunks(self, chunk_bytes: int = 0) -> Iterable[bytes]:
        """The archive as a bounded-chunk generator: the tar writer
        runs against a ChunkPipe on a producer thread, so the HTTP
        layer pulls constant-size chunks with backpressure instead of
        materializing the tar (reference: handler.go:1102-1123 +
        fragment.go:1112-1176 stream WriteTo into the ResponseWriter)."""
        from pilosa_tpu import stream as stream_mod

        entries = self._archive_payloads()
        return stream_mod.generate_from_writer(
            lambda w: self._write_archive(entries, w), chunk_bytes=chunk_bytes
        )

    @staticmethod
    def _verify_archive_payloads(payloads: dict[str, bytes]) -> None:
        """Check every payload entry against the tar's embedded
        "checksum" entry (when present — archives from before the
        tiered-storage PR have none and install unverified, like the
        reference's).  Raises :class:`ArchiveChecksumError` BEFORE any
        payload is applied, so a torn transfer never half-installs."""
        chk = payloads.pop("checksum", None)
        if chk is None:
            return
        try:
            entries = json.loads(chk).get("entries", {})
        except (ValueError, AttributeError) as e:
            raise ArchiveChecksumError(
                f"fragment archive has an unreadable checksum entry: {e}"
            ) from e
        for name, want in entries.items():
            payload = payloads.get(name)
            if payload is None:
                continue  # entry legitimately absent from this archive
            got = hashlib.sha256(payload).hexdigest()
            if got != want:
                raise ArchiveChecksumError(
                    f"fragment archive entry {name!r} is torn: sha256 "
                    f"{got[:12]}… != recorded {str(want)[:12]}…"
                )

    def read_from(self, r) -> None:
        """Restore from a tar produced by write_to.  Payloads are
        collected and CHECKSUM-VERIFIED first (see
        :meth:`_verify_archive_payloads`), then applied data-then-cache
        — a rejected archive leaves the fragment untouched."""
        with self._mu:
            tr = tarfile.open(fileobj=r, mode="r|")
            payloads: dict[str, bytes] = {}
            for member in tr:
                payloads[member.name] = tr.extractfile(member).read()
            tr.close()
            self._verify_archive_payloads(payloads)
            payload = payloads.get("data")
            if payload is not None:
                words, arrays, _ = roaring.decode_tiered(payload)
                self._load_tiered(words, arrays)
                self._version += 1
                self._row_cache.clear()
                self._op_n = 0
                self._op_buf.clear()  # replaced wholesale below
                # persist (same durability discipline as snapshot():
                # file fsync before the atomic rename, directory fsync
                # after — a crash must never resurrect the pre-restore
                # file once the restore was acked)
                with open(self.path + ".snapshotting", "wb") as fh:
                    fh.write(payload)
                    fh.flush()
                    os.fsync(fh.fileno())
                if self._file is not None:
                    fcntl.flock(self._file.fileno(), fcntl.LOCK_UN)
                    self._file.close()
                os.replace(self.path + ".snapshotting", self.path)
                ingest_wal._fsync_dir(self.path)
                self._file = open(self.path, "a+b")
                fcntl.flock(self._file.fileno(), fcntl.LOCK_EX | fcntl.LOCK_NB)
                if self._wal is not None:
                    # Restored content replaces everything the segment
                    # described: restart it against the new snapshot.
                    self._wal.truncate_segment(len(payload))
            cache_payload = payloads.get("cache")
            if cache_payload is not None:
                ids = self._decode_cache_ids(cache_payload)
                if ids is not None:
                    self.cache = cache_mod.new_cache(
                        self.cache_type, self.cache_size
                    )
                    self.cache.stats = self.stats
                    for row_id in ids:
                        if isinstance(row_id, int) and (
                            row_id in self._slot_of or row_id in self._sparse
                        ):
                            self.cache.bulk_add(
                                row_id, self._count_of.get(row_id, 0)
                            )
                    self.cache.invalidate()
                    # A replaced cache changes TopN candidates without
                    # any fragment write: epoch-validated prep caches
                    # must notice even for a cache-only tar (the data
                    # branch bumps via _load_tiered).
                    _bump_write_epoch()

    # ------------------------------------------------------------------

    def _iter_row_offsets(self) -> Iterable[tuple[int, np.ndarray]]:
        """Yield (rowID, sorted uint64 offsets-within-slice) per non-empty
        row, ascending, taking the lock per row (reference:
        fragment.go:487-502 over the container iterators).  The single
        iteration protocol under both for_each_bit and csv_chunks.

        Peak extra memory is ONE unpacked row (~1 MiB), not the fully
        unpacked plane — exports and sync walks of big fragments stay
        under 2x plane memory."""
        with self._mu:
            rows = sorted(set(self._slot_of) | set(self._sparse))
        for r in rows:
            with self._mu:
                slot = self._slot_of.get(r)
                if slot is not None:
                    offs = bp.np_row_to_columns(self._plane[slot])
                else:
                    sp = self._sparse.get(r)
                    if sp is None:
                        continue
                    offs = sp
            if len(offs):
                yield r, offs

    def for_each_bit(self) -> Iterable[tuple[int, int]]:
        """Yield (rowID, absolute columnID) for every set bit."""
        base = self.slice * SLICE_WIDTH
        for r, offs in self._iter_row_offsets():
            for c in offs:
                yield r, base + int(c)

    def csv_chunks(self, chunk_pairs: int = 1 << 20) -> Iterable[bytes]:
        """Vectorized CSV export: yield "row,col\\n" byte chunks of up to
        ``chunk_pairs`` records, rows ascending (reference: the
        fragment.go:487-502 iterator feeding ctl/export.go — but
        formatted a row-block at a time through the native formatter
        instead of one Python tuple per bit)."""
        base = self.slice * SLICE_WIDTH
        pend_r: list[np.ndarray] = []
        pend_c: list[np.ndarray] = []
        pending = 0
        for r, offs in self._iter_row_offsets():
            pend_r.append(np.full(len(offs), r, dtype=np.uint64))
            pend_c.append(offs.astype(np.uint64) + np.uint64(base))
            pending += len(offs)
            if pending >= chunk_pairs:
                yield self._format_pairs(np.concatenate(pend_r), np.concatenate(pend_c))
                pend_r, pend_c, pending = [], [], 0
        if pending:
            yield self._format_pairs(np.concatenate(pend_r), np.concatenate(pend_c))

    @staticmethod
    def _format_pairs(rws: np.ndarray, cls: np.ndarray) -> bytes:
        from pilosa_tpu import native

        blob = native.format_csv(rws, cls)
        if blob is not None:
            return blob
        # numpy fallback: C-loop string conversion, still no per-bit
        # Python iteration.
        out = np.char.add(
            np.char.add(rws.astype("S20"), b","),
            np.char.add(cls.astype("S20"), b"\n"),
        )
        return b"".join(out.tolist())

    def __repr__(self) -> str:
        return (
            f"Fragment(index={self.index!r}, frame={self.frame!r}, "
            f"view={self.view!r}, slice={self.slice})"
        )
