"""HolderSyncer / FragmentSyncer — active anti-entropy.

The holder syncer walks the entire local schema and, for every index,
frame, view, and owned fragment, converges state with the other
replicas (reference: holder.go:357-556):

  1. column attrs  — exchange SHA1 block checksums, pull differing
     blocks from each peer, merge locally (last-writer-merge at the
     attribute-map level, reference: holder.go:432-475);
  2. row attrs     — same per frame (reference: holder.go:477-522);
  3. fragments     — per owned (frame, view, slice): compare per-block
     checksums across replicas, fetch differing blocks' bit dumps,
     majority-consensus merge, apply local diffs, and push each
     remote's diff back as generated SetBit/ClearBit PQL
     (reference: fragment.go:1317-1498).

Checksum computation is the only data-plane-heavy step; the fragment's
``blocks()`` walks device-resident planes (ops/bitplane kernels dump
set positions) and hashes on host.
"""

from __future__ import annotations

import threading

from pilosa_tpu import stream as stream_mod
from pilosa_tpu.core.fragment import PairSet
from pilosa_tpu.core.view import VIEW_STANDARD, is_inverse_view
from pilosa_tpu.net.client import ClientError, InternalClient
from pilosa_tpu.ops.bitplane import SLICE_WIDTH

# Repair writes pushed per request: far enough under the server's
# max-writes-per-request default (5000) to leave headroom, and keeps a
# badly diverged block from assembling one huge PQL string in memory.
REPAIR_BATCH = 1000


class HolderSyncer:
    """reference: holder.go:357-556

    With ``replication`` wired (pilosa_tpu/replicate), anti-entropy is
    the BACKSTOP rather than the mechanism: a slice whose per-slice
    write versions already agree across every replica skips the block
    checksum walk entirely (``sync.skippedInSync``), and repairs that
    do run are attributed by cause — ``cause:missed-hint`` when the
    versions disagreed (writes a replica provably missed, i.e. hints
    that overflowed or never replayed) vs ``cause:drift`` when versions
    agreed but content diverged anyway.  ``full=True`` disables the
    skip (the server forces it every Nth tick against the equal-but-
    wrong version edge cases)."""

    def __init__(
        self, holder, host: str, cluster, closing=None, client_factory=None,
        replication=None, full: bool = False,
    ):
        self.holder = holder
        self.host = host
        self.cluster = cluster
        self.closing = closing or threading.Event()
        self.client_factory = client_factory or (lambda h: InternalClient(h, timeout=30.0))
        self.replication = replication
        self.full = full
        # host -> {slice: version} fetched once per (peer, index).
        self._peer_versions: dict[tuple[str, str], dict[int, int] | None] = {}

    def is_closing(self) -> bool:
        return self.closing.is_set()

    def _peers(self):
        return [n for n in self.cluster.nodes if n.host != self.host]

    def _versions_of(self, host: str, index: str, max_slice: int):
        """One peer's slice versions for an index, fetched once per
        sweep; None = unreachable (treat as disagreeing)."""
        key = (host, index)
        if key not in self._peer_versions:
            try:
                self._peer_versions[key] = self.client_factory(
                    host
                ).replicate_versions(index, range(max_slice + 1))
            except Exception:  # noqa: BLE001 — peer may be down/old
                self._peer_versions[key] = None
        return self._peer_versions[key]

    def slice_cause(self, index: str, slice_i: int, max_slice: int) -> str | None:
        """The sync decision for one slice: None = versions agree on
        every replica (skip the checksum walk), ``"missed-hint"`` =
        some replica's version lags (it provably missed writes),
        ``"drift"`` = versions unavailable/equal-but-unproven (full
        sweep, no replication, unreachable peer)."""
        if self.replication is None or self.full:
            return "drift"
        local = self.replication.versions.get(index, slice_i)
        if local <= 0:
            return "drift"  # nothing observed yet: not provably in sync
        for node in self.cluster.fragment_nodes(index, slice_i):
            if node.host == self.host:
                continue
            versions = self._versions_of(node.host, index, max_slice)
            if versions is None:
                return "drift"
            if versions.get(slice_i, 0) != local:
                return "missed-hint"
        return None

    def sync_holder(self) -> None:
        """reference: holder.go:379-430"""
        for index_name, idx in sorted(self.holder.indexes().items()):
            if self.is_closing():
                return
            # Per-(index, slice) sync decision, shared by every view of
            # the slice: versions-agree slices skip their checksum walk.
            causes: dict[int, str | None] = {}
            index_max = max(idx.max_slice(), idx.max_inverse_slice())
            self.sync_index(index_name)
            for frame_name, frame in sorted(idx.frames().items()):
                if self.is_closing():
                    return
                self.sync_frame(index_name, frame_name)
                for view_name, view in sorted(frame.views().items()):
                    # Every view's fragments sync, like the reference's
                    # holder walk (reference: holder.go:403-425).  The
                    # standard view repairs remotes via PQL push (which
                    # fans out to derived views); inverse/time views
                    # exchange and repair their OWN block data through
                    # the view-scoped import path, so divergence
                    # introduced directly in a derived view converges
                    # too (the reference only ever merges standard
                    # data, fragment.go:1443).
                    max_slice = (
                        idx.max_inverse_slice()
                        if is_inverse_view(view_name)
                        else idx.max_slice()
                    )
                    for slice_i in range(max_slice + 1):
                        if self.is_closing():
                            return
                        if not self.cluster.owns_fragment(
                            self.host, index_name, slice_i
                        ):
                            continue
                        if slice_i not in causes:
                            causes[slice_i] = self.slice_cause(
                                index_name, slice_i, index_max
                            )
                            if causes[slice_i] is None:
                                self.holder.stats.count("sync.skippedInSync")
                        if causes[slice_i] is None:
                            continue  # replica versions agree: backstop only
                        # Create locally-absent fragments so data that
                        # exists only on peers is pulled (reference:
                        # holder.go:533-546 CreateFragmentIfNotExists).
                        view.create_fragment_if_not_exists(slice_i)
                        self.sync_fragment(
                            index_name, frame_name, view_name, slice_i,
                            cause=causes[slice_i],
                        )

    def sync_index(self, index: str) -> None:
        """Column-attr convergence (reference: holder.go:432-475)."""
        idx = self.holder.index(index)
        if idx is None:
            return
        blocks = idx.column_attr_store.blocks()
        for node in self._peers():
            try:
                m = self.client_factory(node.host).column_attr_diff(index, blocks)
            except ClientError:
                continue
            if not m:
                continue
            idx.column_attr_store.set_bulk_attrs(m)
            blocks = idx.column_attr_store.blocks()

    def sync_frame(self, index: str, name: str) -> None:
        """Row-attr convergence (reference: holder.go:477-522)."""
        f = self.holder.frame(index, name)
        if f is None:
            return
        blocks = f.row_attr_store.blocks()
        for node in self._peers():
            try:
                m = self.client_factory(node.host).row_attr_diff(index, name, blocks)
            except ClientError as e:
                if e.status == 404:
                    continue  # frame not created remotely yet
                continue
            if not m:
                continue
            f.row_attr_store.set_bulk_attrs(m)
            blocks = f.row_attr_store.blocks()

    def sync_fragment(
        self, index: str, frame: str, view: str, slice_i: int,
        cause: str = "drift",
    ) -> None:
        f = self.holder.fragment(index, frame, view, slice_i)
        if f is None:
            return
        FragmentSyncer(
            fragment=f,
            host=self.host,
            cluster=self.cluster,
            closing=self.closing,
            client_factory=self.client_factory,
            cause=cause,
            holder_stats=self.holder.stats,
        ).sync_fragment()


class FragmentSyncer:
    """reference: fragment.go:1317-1498

    ``cause`` attributes this sync's repairs: "missed-hint" = the
    replica versions disagreed before the walk (writes a replica
    provably missed — overflowed or never-replayed hints), "drift" =
    versions agreed/unknown but checksums diverged anyway.  Rendered as
    ``sync.repairBits[cause:*]`` on the holder stats."""

    def __init__(
        self, fragment, host: str, cluster, closing=None, client_factory=None,
        cause: str = "drift", holder_stats=None,
    ):
        self.fragment = fragment
        self.host = host
        self.cluster = cluster
        self.closing = closing or threading.Event()
        self.client_factory = client_factory or (lambda h: InternalClient(h, timeout=30.0))
        self.cause = cause
        self.holder_stats = holder_stats

    def _count_repair_bits(self, n: int) -> None:
        self.fragment.stats.count("repairBits", n)
        if self.holder_stats is not None:
            self.holder_stats.count_with_custom_tags(
                "sync.repairBits", n, [f"cause:{self.cause}"]
            )

    def is_closing(self) -> bool:
        return self.closing.is_set()

    def sync_fragment(self) -> None:
        """reference: fragment.go:1339-1418"""
        f = self.fragment
        nodes = self.cluster.fragment_nodes(f.index, f.slice)
        if len(nodes) == 1:
            return
        if not any(n.host == self.host for n in nodes):
            return

        # Collect per-replica block checksums (local + each peer).
        blocks_sets: list[dict[int, bytes]] = [dict(f.blocks())]
        for node in nodes:
            if node.host == self.host:
                continue
            if self.is_closing():
                return
            try:
                remote = self.client_factory(node.host).fragment_blocks(
                    f.index, f.frame, f.view, f.slice
                )
            except ClientError as e:
                if e.status == 404:
                    remote = []  # fragment not created remotely yet
                else:
                    raise
            blocks_sets.append(dict(remote))

        # A block needs syncing when any replica's checksum differs.
        block_ids = sorted(set().union(*[set(b) for b in blocks_sets]))
        for block_id in block_ids:
            checksums = {b.get(block_id) for b in blocks_sets}
            if len(checksums) <= 1:
                continue
            if self.is_closing():
                return
            self.sync_block(block_id)
            f.stats.count("BlockRepair")  # reference: fragment.go:1412

    def sync_block(self, block_id: int) -> None:
        """reference: fragment.go:1420-1498"""
        f = self.fragment
        pair_sets: list[PairSet] = []
        hosts: list[str] = []
        for node in self.cluster.fragment_nodes(f.index, f.slice):
            if node.host == self.host:
                continue
            if self.is_closing():
                return
            client = self.client_factory(node.host)
            # Each view exchanges its OWN block data (a 404 means the
            # peer hasn't materialized this derived view yet — treat as
            # empty so the consensus can still pull/push).
            try:
                row_ids, column_ids = client.block_data(
                    f.index, f.frame, f.view, f.slice, block_id
                )
            except ClientError as e:
                if e.status != 404:
                    raise
                row_ids, column_ids = [], []
            pair_sets.append(PairSet(row_ids=row_ids, column_ids=column_ids))
            hosts.append(node.host)

        if self.is_closing():
            return
        sets, clears = f.merge_block(block_id, pair_sets)

        base = f.slice * SLICE_WIDTH
        for host, set_ps, clear_ps in zip(hosts, sets, clears):
            if not set_ps.column_ids and not clear_ps.column_ids:
                continue
            if self.is_closing():
                return
            if f.view == VIEW_STANDARD:
                # Standard diffs push back as generated PQL, which fans
                # out through the remote's whole write path (all views,
                # caches, op-log) — reference: fragment.go:1465-1492.
                # Batched so a badly diverged block never assembles one
                # huge request (or trips max-writes-per-request).
                def _lines(set_ps=set_ps, clear_ps=clear_ps):
                    for r, c in zip(set_ps.row_ids, set_ps.column_ids):
                        yield (
                            f'SetBit(frame="{f.frame}", rowID={r},'
                            f" columnID={base + c})"
                        )
                    for r, c in zip(clear_ps.row_ids, clear_ps.column_ids):
                        yield (
                            f'ClearBit(frame="{f.frame}", rowID={r},'
                            f" columnID={base + c})"
                        )

                client = self.client_factory(host)
                for batch in stream_mod.batched(_lines(), REPAIR_BATCH):
                    if self.is_closing():
                        return
                    client.execute_query(f.index, "\n".join(batch), remote=False)
                    # reference: fragment.go:1412 counts repairs; per
                    # batch here so dashboards see push progress.
                    f.stats.count("repairBatch")
                    self._count_repair_bits(len(batch))
            else:
                # Derived views repair via the view-scoped raw write
                # path: PQL cannot target an individual inverse/time
                # view.
                self.client_factory(host).import_view_bits(
                    f.index,
                    f.frame,
                    f.view,
                    f.slice,
                    (set_ps.row_ids, [base + c for c in set_ps.column_ids]),
                    (clear_ps.row_ids, [base + c for c in clear_ps.column_ids]),
                )
                f.stats.count("repairBatch")
                self._count_repair_bits(
                    len(set_ps.column_ids) + len(clear_ps.column_ids)
                )
