"""pilosa_tpu.device — HBM residency management.

The process has ONE set of devices, so it gets ONE residency manager:
``pool()`` returns the process-global :class:`PlanePool` every device
allocation registers with (fragment mirrors, paged sparse rows, the
executor's batch/TopN cache entries), and ``prefetcher()`` the shared
async mirror :class:`Prefetcher`.  The server configures the pool from
``[device]`` config at open; bare library use (tests, bench) gets an
unconfigured pool, whose budget resolves from the
``PILOSA_DEVICE_HBM_BUDGET_BYTES`` env or device detection — unbounded
on the CPU backend, so nothing changes for code that never asked for a
budget.
"""

from __future__ import annotations

import threading

from pilosa_tpu.device.pool import PlanePool  # noqa: F401 — re-export
from pilosa_tpu.device.prefetch import Prefetcher  # noqa: F401 — re-export

_mu = threading.Lock()
_pool: PlanePool | None = None
_prefetcher: Prefetcher | None = None


def pool() -> PlanePool:
    """The process-global residency manager."""
    global _pool
    if _pool is None:
        with _mu:
            if _pool is None:
                _pool = PlanePool()
    return _pool


def prefetcher() -> Prefetcher:
    """The shared prefetcher, bound to the global pool."""
    global _prefetcher
    if _prefetcher is None:
        with _mu:
            if _prefetcher is None:
                _prefetcher = Prefetcher()
    return _prefetcher


def _set_pool(p: PlanePool | None) -> PlanePool | None:
    """Swap the global pool (tests only); returns the previous one."""
    global _pool
    with _mu:
        prev = _pool
        _pool = p
        return prev


def bytes_by_device(arr) -> dict:
    """{device: bytes} attribution for a jax array.

    A mesh-sharded array charges each device exactly ITS shard's bytes
    (``addressable_shards`` — the authoritative per-device footprint):
    attributing the global size to one device would evict that shard's
    neighbors for capacity the device never spends, and an even split
    is wrong for uneven layouts and for replicated arrays (every device
    holds a full copy).  A committed array lands whole on its one
    device.  Fallback (arrays without shard introspection): even split
    over ``devices()`` / the legacy ``.device`` attribute."""
    if arr is None:
        return {}
    nbytes = int(getattr(arr, "nbytes", 0) or 0)
    if not nbytes:
        return {}
    try:
        shards = arr.addressable_shards
    except Exception:  # noqa: BLE001 — non-jax stand-ins / old arrays
        shards = None
    if shards:
        out: dict = {}
        for sh in shards:
            n = int(getattr(sh.data, "nbytes", 0) or 0)
            if n:
                out[sh.device] = out.get(sh.device, 0) + n
        if out:
            return out
    devs = None
    try:
        devs = list(arr.devices())
    except Exception:  # noqa: BLE001 — older arrays expose .device
        d = getattr(arr, "device", None)
        devs = [d] if d is not None else None
    if not devs:
        return {}
    share = nbytes // len(devs)
    return {d: share for d in devs}
