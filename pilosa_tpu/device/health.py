"""Device health — the accelerator's own fault domain.

Every other fault domain already has local handling (op-log repair,
host breakers, admission shedding, quorum hints); the device had none:
a launch failure surfaced as a generic XLA runtime error and a hung ICI
all-reduce wedged the process behind the collective-launch mutex
forever.  This module gives the node a per-device breaker-style state
machine plus a hung-collective watchdog, so a misbehaving accelerator
degrades the node to the host (numpy) evaluator (exec/hosteval.py)
instead of bricking it:

* **Classification.**  :func:`classify` maps a launch exception to a
  failure kind — ``oom`` (RESOURCE_EXHAUSTED / allocator text),
  ``hang`` (a watchdog trip), ``error`` (an XLA/injected runtime
  error) — or None for exceptions that are not device faults at all
  (semantic errors, deadlines), which the launch sites re-raise.

* **State machine.**  Each path — ``device:<ordinal>`` per
  participating device, plus ``collective`` for the mesh-psum launch
  path — moves healthy → suspect (first failure) → quarantined
  (``quarantine_threshold`` consecutive failures, or ONE hang).  A
  quarantined path denies launches (callers answer from the host
  planes, byte-identically) until ``open_ms`` elapses, then admits
  exactly one half-open PROBE launch; ``probe_successes`` successful
  probes heal it (and fire ``on_heal`` — the server re-materializes
  HBM mirrors through the staging lane), a failed probe re-arms the
  quarantine clock.

* **Watchdog.**  :meth:`DeviceHealth.run_collective` runs a
  collective-bearing dispatch+fetch on a dedicated runner thread and
  waits at most ``[device] launch-watchdog-ms``: a hung all-reduce
  trips :class:`LaunchWatchdogTimeout` (counted as
  ``device.watchdogTrips``), quarantines the ``collective`` path, and
  the caller falls back to the per-slice (non-collective) launch or
  the host evaluator — the process never wedges.  The hung runner
  thread is abandoned (its eventual completion is discarded and
  counted) and a fresh runner serves the next collective.

Surfaced at ``GET /debug/health`` (``device`` section), ``/metrics``
(``device.health.*`` gauges, ``device.watchdogTrips``), and — via the
server's gossip piggyback — to peers, whose coordinators deprioritize
degraded replicas (executor._slices_by_node).
"""

from __future__ import annotations

import queue
import threading
import time

STATE_HEALTHY = "healthy"
STATE_SUSPECT = "suspect"
STATE_QUARANTINED = "quarantined"

KIND_OOM = "oom"
KIND_ERROR = "error"
KIND_HANG = "hang"

MODE_OK = "ok"
MODE_PROBE = "probe"
MODE_DENY = "deny"

# The mesh-collective launch path (psum over ICI) is tracked as its own
# breaker path: a hang there indicts the collective rendezvous, not the
# devices — single-device and host execution keep working.
COLLECTIVE = "collective"

DEFAULT_QUARANTINE_THRESHOLD = 3
DEFAULT_OPEN_MS = 10_000.0
DEFAULT_PROBE_SUCCESSES = 1
DEFAULT_WATCHDOG_MS = 60_000.0

_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "out of memory", "OUT_OF_MEMORY")


class LaunchWatchdogTimeout(RuntimeError):
    """A device launch exceeded the watchdog deadline — the shape of a
    hung collective rendezvous or a wedged device runtime."""


class CollectiveUnavailable(RuntimeError):
    """The collective launch path is quarantined; callers fall back to
    the per-slice (non-collective) launch or the host evaluator."""


def classify(exc: BaseException) -> str | None:
    """Failure kind of a device-launch exception, or None when the
    exception is NOT a device fault (semantic errors, deadlines,
    scheduler shutdowns) and must propagate unchanged.

    The allowlist is deliberately narrow: only the watchdog's own
    timeout, the chaos layer's injected device faults, and the JAX/XLA
    runtime's error types (by module, plus the RESOURCE_EXHAUSTED /
    out-of-memory text real allocator failures carry) count — an
    unrecognized exception fails the query loudly rather than silently
    rerouting a logic bug through the host path."""
    if isinstance(exc, LaunchWatchdogTimeout):
        return KIND_HANG
    from pilosa_tpu.testing import faults

    if isinstance(exc, faults.FaultOOM):
        return KIND_OOM
    if isinstance(exc, faults.FaultError):
        return KIND_ERROR
    mod = type(exc).__module__ or ""
    name = type(exc).__name__
    if (
        mod.startswith("jaxlib")
        or mod.startswith("jax")
        or name == "XlaRuntimeError"
    ):
        msg = str(exc)
        if any(m in msg for m in _OOM_MARKERS):
            return KIND_OOM
        return KIND_ERROR
    if isinstance(exc, RuntimeError) and any(
        m in str(exc) for m in _OOM_MARKERS
    ):
        return KIND_OOM
    return None


class _PathState:
    __slots__ = (
        "state",
        "failures",
        "opens",
        "quarantined_at",
        "probing",
        "probe_ok",
        "last_kind",
        "kinds",
    )

    def __init__(self):
        self.state = STATE_HEALTHY
        self.failures = 0  # consecutive
        self.opens = 0
        self.quarantined_at = 0.0
        self.probing = False
        self.probe_ok = 0
        self.last_kind = ""
        self.kinds: dict[str, int] = {}

    def snapshot(self, now: float) -> dict:
        out = {
            "state": self.state,
            "consecutiveFailures": self.failures,
            "quarantines": self.opens,
        }
        if self.last_kind:
            out["lastKind"] = self.last_kind
        if self.kinds:
            out["failures"] = dict(self.kinds)
        if self.state == STATE_QUARANTINED:
            out["sinceQuarantineMs"] = round(
                (now - self.quarantined_at) * 1000.0, 1
            )
            out["probing"] = self.probing
        return out


class _WatchdogRunner:
    """Runs collective launch bodies on a dedicated daemon thread with a
    wait deadline.  A timed-out body is ABANDONED: its generation goes
    stale, its eventual completion (or error) is discarded and counted,
    and the next submission spawns a fresh runner — so one wedged
    collective can never hold the watchdog hostage.  (The abandoned
    thread may still hold the process collective-launch mutex until the
    wedged call returns; that is exactly the window the quarantine
    covers — no new collective launches are attempted until a probe,
    by which time a recovered backend has released it.)"""

    def __init__(self, stats=None, name: str = "device-watchdog"):
        from pilosa_tpu.obs.stats import NopStatsClient

        self.stats = stats or NopStatsClient()
        self._name = name
        self._mu = threading.Lock()
        self._gen = 0
        self._q: "queue.SimpleQueue | None" = None
        self._thread: threading.Thread | None = None

    def _ensure_worker_locked(self) -> "queue.SimpleQueue":
        if self._q is None or self._thread is None or not self._thread.is_alive():
            self._q = queue.SimpleQueue()
            self._thread = threading.Thread(
                target=self._worker, args=(self._q,), daemon=True,
                name=self._name,
            )
            self._thread.start()
        return self._q

    def _worker(self, q: "queue.SimpleQueue") -> None:
        while True:
            item = q.get()
            if item is None:
                return
            gen, fn, box = item
            try:
                res, err = fn(), None
            except BaseException as e:  # noqa: BLE001 — crosses threads
                res, err = None, e
            with self._mu:
                stale = gen != self._gen
            if stale:
                # Abandoned by a timeout: nobody is waiting.  Count it
                # so a recovered-but-late launch is visible, and never
                # let its error escape into a log-spam path.
                self.stats.count("device.watchdog.abandonedCompletions")
                continue
            box["result"], box["error"] = res, err
            box["done"].set()

    def run(self, fn, timeout_s: float):
        """``fn()`` with a deadline; raises :class:`LaunchWatchdogTimeout`
        (and abandons the in-flight call) when it does not return in
        ``timeout_s``."""
        box: dict = {"result": None, "error": None, "done": threading.Event()}
        with self._mu:
            q = self._ensure_worker_locked()
            gen = self._gen
        q.put((gen, fn, box))
        if not box["done"].wait(timeout=timeout_s):
            with self._mu:
                # Stale-mark the in-flight call and retire this runner:
                # the next submission gets a fresh thread.
                self._gen += 1
                self._q = None
                self._thread = None
            raise LaunchWatchdogTimeout(
                f"device launch exceeded watchdog deadline ({timeout_s:.3f}s)"
            )
        if box["error"] is not None:
            raise box["error"]
        return box["result"]

    def close(self) -> None:
        with self._mu:
            q, self._q, self._thread = self._q, None, None
        if q is not None:
            q.put(None)


class DeviceHealth:
    """Per-path device breaker + the collective launch watchdog.

    One instance per node (the Server wires a configured one into its
    executor and coalescer; bare library executors build a default),
    tracking ``device:<ordinal>`` paths for the participating devices
    and the ``collective`` mesh-psum path."""

    def __init__(
        self,
        quarantine_threshold: int = DEFAULT_QUARANTINE_THRESHOLD,
        open_ms: float = DEFAULT_OPEN_MS,
        probe_successes: int = DEFAULT_PROBE_SUCCESSES,
        watchdog_ms: float = DEFAULT_WATCHDOG_MS,
        stats=None,
        logger=None,
        on_state_change=None,
    ):
        from pilosa_tpu.obs.stats import NopStatsClient

        self.quarantine_threshold = max(1, int(quarantine_threshold))
        self.open_s = float(open_ms) / 1000.0
        self.probe_successes = max(1, int(probe_successes))
        self.watchdog_s = float(watchdog_ms) / 1000.0
        self.stats = stats or NopStatsClient()
        self.logger = logger or (lambda m: None)
        # on_state_change(path, state) fires OUTSIDE the health lock on
        # every quarantine and heal — the server hooks gossip
        # (degraded-replica deprioritization) and mirror
        # re-materialization here.
        self.on_state_change = on_state_change
        self._mu = threading.Lock()
        self._paths: dict[str, _PathState] = {}
        self.watchdog_trips = 0
        self._runner = _WatchdogRunner(stats=self.stats)

    # ------------------------------------------------------------------
    # paths
    # ------------------------------------------------------------------

    def device_paths(self) -> list[str]:
        """One path per participating device (placement is process-wide,
        ops/bitplane.participating_devices)."""
        from pilosa_tpu.ops import bitplane as bp

        try:
            n = max(1, int(bp.mesh_device_count()))
        except Exception:  # noqa: BLE001 — no backend in some unit tests
            n = 1
        return [f"device:{d}" for d in range(n)]

    def _path(self, name: str) -> _PathState:
        st = self._paths.get(name)
        if st is None:
            st = self._paths[name] = _PathState()
        return st

    # ------------------------------------------------------------------
    # the gate
    # ------------------------------------------------------------------

    def acquire(self, paths: list[str]) -> str:
        """Launch admission over ``paths``: ``ok`` (all healthy or
        suspect), ``probe`` (some quarantined path past its open window
        — this caller carries the half-open probe), or ``deny``.  A
        granted probe is exclusive until :meth:`success` /
        :meth:`failure` / :meth:`cancel_probe` resolves it."""
        now = time.monotonic()
        granted: list[_PathState] = []
        with self._mu:
            quarantined = [
                st
                for st in (self._path(p) for p in paths)
                if st.state == STATE_QUARANTINED
            ]
            if not quarantined:
                return MODE_OK
            for st in quarantined:
                if st.probing:
                    return MODE_DENY
                if now - st.quarantined_at < self.open_s:
                    return MODE_DENY
            for st in quarantined:
                st.probing = True
                granted.append(st)
        return MODE_PROBE

    def cancel_probe(self, paths: list[str]) -> None:
        """Release a granted probe that never launched (empty batch)."""
        with self._mu:
            for p in paths:
                st = self._paths.get(p)
                if st is not None:
                    st.probing = False

    def denied(self, paths: list[str] | None = None) -> bool:
        """Whether a launch over ``paths`` (default: every device path)
        would be denied right now — a peek that consumes no probe."""
        paths = paths if paths is not None else self.device_paths()
        now = time.monotonic()
        with self._mu:
            for p in paths:
                st = self._paths.get(p)
                if st is None or st.state != STATE_QUARANTINED:
                    continue
                if st.probing or now - st.quarantined_at < self.open_s:
                    return True
        return False

    def degraded(self) -> bool:
        """Any path quarantined — the node-level flag gossip announces
        so coordinators deprioritize this replica."""
        with self._mu:
            return any(
                st.state == STATE_QUARANTINED for st in self._paths.values()
            )

    # ------------------------------------------------------------------
    # outcomes
    # ------------------------------------------------------------------

    def success(self, paths: list[str], probe: bool = False) -> None:
        events: list[tuple[str, str]] = []
        with self._mu:
            for p in paths:
                st = self._path(p)
                if st.state == STATE_QUARANTINED and (probe or st.probing):
                    st.probing = False
                    st.probe_ok += 1
                    if st.probe_ok >= self.probe_successes:
                        st.state = STATE_HEALTHY
                        st.failures = 0
                        st.probe_ok = 0
                        events.append((p, STATE_HEALTHY))
                    # else: stay quarantined, but past the open window —
                    # the next acquire() probes again immediately.
                elif st.state != STATE_QUARANTINED:
                    st.failures = 0
                    st.state = STATE_HEALTHY
        for p, state in events:
            self.stats.count("device.health.heals")
            self.logger(
                f"device health: {p} healed (half-open probe succeeded)"
            )
            self._notify(p, state)

    def failure(
        self,
        paths: list[str],
        kind: str,
        probe: bool = False,
        device: int | None = None,
    ) -> None:
        """Record a classified launch failure.  ``device`` (when the
        fault named one — per-device chaos targeting) narrows the blame
        to that ordinal's path; a real launch error indicts every
        participating path."""
        if device is not None:
            narrowed = [p for p in paths if p == f"device:{device}"]
            if narrowed:
                paths = narrowed
        events: list[tuple[str, str]] = []
        with self._mu:
            for p in paths:
                st = self._path(p)
                st.failures += 1
                st.last_kind = kind
                st.kinds[kind] = st.kinds.get(kind, 0) + 1
                if st.state == STATE_QUARANTINED:
                    # A failed probe (or a straggler failure) re-arms
                    # the quarantine clock.
                    st.probing = False
                    st.probe_ok = 0
                    st.quarantined_at = time.monotonic()
                    continue
                if kind == KIND_HANG or st.failures >= self.quarantine_threshold:
                    st.state = STATE_QUARANTINED
                    st.opens += 1
                    st.probing = False
                    st.probe_ok = 0
                    st.quarantined_at = time.monotonic()
                    events.append((p, STATE_QUARANTINED))
                else:
                    st.state = STATE_SUSPECT
        self.stats.count_with_custom_tags(
            "device.health.failures", 1, [f"kind:{kind}"]
        )
        for p, state in events:
            self.stats.count("device.health.quarantines")
            self.logger(
                f"device health: {p} QUARANTINED after {kind!r} failure(s) "
                "— serving from host planes until a half-open probe heals it"
            )
            self._notify(p, state)

    def _notify(self, path: str, state: str) -> None:
        cb = self.on_state_change
        if cb is None:
            return
        try:
            cb(path, state)
        except Exception as e:  # noqa: BLE001 — advisory hook
            self.logger(f"device health callback error: {e}")

    # ------------------------------------------------------------------
    # the collective path (mesh psum) + watchdog
    # ------------------------------------------------------------------

    def collective_allowed(self) -> bool:
        """Peek: would a collective launch be admitted (possibly as a
        probe)?  Callers use this to pick the on-device "total" reduce
        vs the per-slice partials path before assembling a launch."""
        return not self.denied([COLLECTIVE])

    def _locked_body(self, fn):
        """The watched payload: the process collective-launch mutex is
        acquired ON THE RUNNER THREAD, so a hang observed by the
        watchdog leaves the lock with the abandoned runner — quarantine
        keeps new collectives away until a probe, by which time a
        recovered backend has released it."""
        from pilosa_tpu.exec import plan

        with plan.collective_launch():
            return self._dispatch_body(fn)

    def _dispatch_body(self, fn):
        """The caller's dispatch+fetch body, running UNDER the
        collective mutex.  A named method (not the bare ``fn()``) so
        analyze.toml can declare the dynamic call edges — program-cache
        lookups and the collective chaos checkpoint acquire their locks
        under the mutex, and the lock-order pass must see it."""
        return fn()

    def run_collective(self, fn):
        """Run a collective-bearing dispatch+fetch (``fn`` does NOT
        take the collective lock itself) under the collective path's
        breaker and the launch watchdog.  Raises
        :class:`CollectiveUnavailable` when quarantined and
        :class:`LaunchWatchdogTimeout` on a trip — callers fall back to
        the per-slice launch or the host evaluator.  Device-fault
        errors from ``fn`` count against the collective path too (the
        caller's guard additionally classifies them for the device
        paths); non-device exceptions propagate unrecorded."""
        mode = self.acquire([COLLECTIVE])
        if mode == MODE_DENY:
            raise CollectiveUnavailable("collective launch path quarantined")
        probe = mode == MODE_PROBE
        try:
            if self.watchdog_s > 0:
                res = self._runner.run(
                    lambda: self._locked_body(fn), self.watchdog_s
                )
            else:
                res = self._locked_body(fn)
        except LaunchWatchdogTimeout:
            with self._mu:
                self.watchdog_trips += 1
            self.stats.count("device.watchdogTrips")
            self.logger(
                "device health: collective launch watchdog TRIPPED "
                f"({self.watchdog_s:.3f}s) — quarantining the mesh path"
            )
            self.failure([COLLECTIVE], KIND_HANG, probe=probe)
            raise
        except Exception as e:
            if classify(e) is not None:
                self.failure([COLLECTIVE], classify(e), probe=probe)
            raise
        self.success([COLLECTIVE], probe=probe)
        return res

    def close(self) -> None:
        self._runner.close()

    # ------------------------------------------------------------------
    # surfaces
    # ------------------------------------------------------------------

    def snapshot(self) -> dict:
        now = time.monotonic()
        with self._mu:
            paths = {p: st.snapshot(now) for p, st in sorted(self._paths.items())}
            trips = self.watchdog_trips
        return {
            "degraded": any(
                p["state"] == STATE_QUARANTINED for p in paths.values()
            ),
            "paths": paths,
            "watchdogTrips": trips,
            "quarantineThreshold": self.quarantine_threshold,
            "openMs": round(self.open_s * 1000.0, 1),
            "probeSuccesses": self.probe_successes,
            "watchdogMs": round(self.watchdog_s * 1000.0, 1),
        }

    _STATE_GAUGE = {STATE_HEALTHY: 0.0, STATE_SUSPECT: 1.0, STATE_QUARANTINED: 2.0}

    def gauges(self) -> dict:
        """Scrape-time /metrics gauges (rendered even without a stats
        backend, like the program-cache and admission gauges)."""
        with self._mu:
            out = {
                f"device.health.state[path:{p}]": self._STATE_GAUGE[st.state]
                for p, st in sorted(self._paths.items())
            }
            out["device.health.degraded"] = float(
                any(
                    st.state == STATE_QUARANTINED
                    for st in self._paths.values()
                )
            )
            out["device.watchdogTrips"] = float(self.watchdog_trips)
            return out
