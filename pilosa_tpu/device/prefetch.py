"""Async mirror prefetcher — overlap host->device staging with plan work.

A planned query's leaf fragments are known before its batch assembles
(exec/plan.py `collect_leaf_calls` + the executor's resolution walk);
any of them whose HBM mirror is cold would otherwise re-upload
serially, one 2-8 MiB `device_put` at a time, inside the assembly loop.
The prefetcher re-materializes those cold mirrors CONCURRENTLY on their
home devices — transfers to distinct devices genuinely overlap, and
even same-device uploads overlap the executor's host-side planning.

Workers call the same ``Fragment.device_plane()`` the query path uses,
so admission, budget eviction, and coherence all ride the fragment lock
— a prefetch can never produce a stale mirror, and the assembly thread
that reaches a fragment mid-upload simply blocks on that fragment's
lock until its mirror is ready (the overlap is across fragments, not
within one).

Three priority lanes share the workers:

* **query lane** (:meth:`prefetch`) — the per-query cold-mirror warm;
  always drains first.
* **hydrate lane** (:meth:`run_hydration`) — cold-tier fragment
  hydration (pilosa_tpu/tier): store fetch + tar restore jobs run
  here so concurrent hydrations are bounded by the worker pool, and
  the query lane's HBM warms still jump them (query lane wins).
* **staging lane** (:meth:`stage`) — the post-restart background
  re-materialization of the whole residency set
  (core/holder.stage_device_mirrors).  A restarted node answers its
  first queries while this lane drains; a query prefetch arriving
  mid-staging jumps the entire backlog, so serving latency never
  queues behind bulk staging.  ``throttle_s`` rate-limits the lane
  (and is the knob the slowed-staging tests turn).

Threads are daemons for the same reason the executor's pool uses them:
a worker wedged inside a device call must degrade to a lost prefetch,
never a process that cannot exit.
"""

from __future__ import annotations

import threading
import time
from collections import deque

DEFAULT_WORKERS = 8


class StageJob:
    """Progress handle for one :meth:`Prefetcher.stage` call."""

    def __init__(self, total: int):
        self.total = total
        self.staged = 0
        self.skipped = 0  # already resident at upload time
        self.errors = 0
        self._mu = threading.Lock()
        self._done = threading.Event()
        if total == 0:
            self._done.set()

    def _finish_one(self, *, staged: bool = False, skipped: bool = False,
                    error: bool = False) -> None:
        with self._mu:
            self.staged += 1 if staged else 0
            self.skipped += 1 if skipped else 0
            self.errors += 1 if error else 0
            if self.staged + self.skipped + self.errors >= self.total:
                self._done.set()

    @property
    def remaining(self) -> int:
        with self._mu:
            return max(0, self.total - self.staged - self.skipped - self.errors)

    def done(self) -> bool:
        return self._done.is_set()

    def wait(self, timeout: float | None = None) -> bool:
        return self._done.wait(timeout)

    def snapshot(self) -> dict:
        with self._mu:
            return {
                "total": self.total,
                "staged": self.staged,
                "skipped": self.skipped,
                "errors": self.errors,
                "remaining": max(
                    0, self.total - self.staged - self.skipped - self.errors
                ),
            }


class Prefetcher:
    """Re-materialize cold fragment mirrors in background threads.

    ``pool`` supplies the hit/miss/stage counters and is usually the
    global ``pilosa_tpu.device.pool()`` (the default when None).
    """

    def __init__(self, pool=None, max_workers: int = DEFAULT_WORKERS):
        self._pool = pool
        self._max_workers = max_workers
        # Three-lane work queue: query prefetches (high) always pop
        # before hydration jobs (mid), which pop before background
        # staging (low).
        self._high: deque = deque()
        self._mid: deque = deque()
        self._low: deque = deque()
        self._cv = threading.Condition(threading.Lock())
        self._threads: list[threading.Thread] = []
        self._idle = 0

    def pool(self):
        if self._pool is not None:
            return self._pool
        from pilosa_tpu import device as device_mod

        return device_mod.pool()

    @staticmethod
    def _is_cold(f) -> bool:
        # Advisory peek (no lock): a racing writer only flips a
        # fragment cold, and the worker re-checks under the lock.
        return f._device is None or f._device_version != f._version

    def prefetch(self, frags, wait: bool = False) -> int:
        """Schedule QUERY-lane uploads for every COLD fragment in
        ``frags``; already-resident mirrors count as prefetch hits.
        Returns the number scheduled.  ``wait=True`` blocks until every
        scheduled upload finished (tests and the bench use it; the
        executor fires and forgets — per-fragment locks provide the
        synchronization)."""
        pool = self.pool()
        cold = []
        hits = 0
        for f in frags:
            if f is None:
                continue
            if self._is_cold(f):
                cold.append(f)
            else:
                hits += 1
        if hits:
            pool.count_prefetch(hit=hits)
        if not cold:
            return 0
        done = threading.Event()
        remaining = [len(cold)]
        rlock = threading.Lock()
        for f in cold:
            self._submit(
                ("prefetch", f, pool, remaining, rlock, done), low=False
            )
        if wait:
            done.wait()
        return len(cold)

    def stage(self, frags, throttle_s: float = 0.0) -> StageJob:
        """Schedule STAGING-lane uploads for every cold fragment in
        ``frags`` (order preserved — the holder submits them in
        priority order) and return the job's progress handle.  Query
        prefetches always jump this backlog.  ``throttle_s`` sleeps
        between uploads — an operator knob to keep bulk staging from
        saturating the host->device link while serving (and the hook
        the deliberately-slowed restart tests use)."""
        pool = self.pool()
        cold = [f for f in frags if f is not None and self._is_cold(f)]
        job = StageJob(len(cold))
        if cold:
            pool.count_stage(scheduled=len(cold))
            for f in cold:
                self._submit(("stage", f, pool, job, throttle_s), low=True)
        return job

    def run_hydration(self, fn):
        """Run ``fn()`` on the HYDRATE lane and block for its result
        (or re-raise its exception).  Cold-tier hydrations ride this so
        their store fetch + restore work is bounded by the worker pool
        while query-lane HBM warms still pop first.  The calling query
        thread blocks here — hydration IS its critical path."""
        done = threading.Event()
        box: dict = {}
        self._submit(("hydrate", fn, box, done), lane="mid")
        done.wait()
        if "exc" in box:
            raise box["exc"]
        return box.get("result")

    # ------------------------------------------------------------------

    def _submit(self, item: tuple, low: bool | None = None,
                lane: str | None = None) -> None:
        if lane is None:
            lane = "low" if low else "high"
        with self._cv:
            {"high": self._high, "mid": self._mid, "low": self._low}[
                lane
            ].append(item)
            if self._idle == 0 and len(self._threads) < self._max_workers:
                t = threading.Thread(
                    target=self._worker, daemon=True, name="hbm-prefetch"
                )
                self._threads.append(t)
                t.start()
            else:
                self._cv.notify()

    def _take(self) -> tuple:
        with self._cv:
            self._idle += 1
            while not self._high and not self._mid and not self._low:
                self._cv.wait()
            self._idle -= 1
            if self._high:
                return self._high.popleft()
            if self._mid:
                return self._mid.popleft()
            return self._low.popleft()

    def _worker(self) -> None:
        while True:
            item = self._take()
            if item[0] == "prefetch":
                self._run_prefetch(*item[1:])
            elif item[0] == "hydrate":
                self._run_hydrate(*item[1:])
            else:
                self._run_stage(*item[1:])

    @staticmethod
    def _run_hydrate(fn, box: dict, done: threading.Event) -> None:
        try:
            box["result"] = fn()
        except BaseException as e:  # noqa: BLE001 — relayed to the caller
            box["exc"] = e
        finally:
            done.set()

    def _run_prefetch(self, frag, pool, remaining, rlock, done) -> None:
        try:
            was_cold = self._is_cold(frag)
            frag.device_plane()
            pool.count_prefetch(
                hit=0 if was_cold else 1, miss=1 if was_cold else 0
            )
        except Exception:  # noqa: BLE001 — prefetch is best-effort;
            pass  # the query path re-raises any real failure itself
        finally:
            with rlock:
                remaining[0] -= 1
                if remaining[0] == 0:
                    done.set()

    def _run_stage(self, frag, pool, job: StageJob, throttle_s: float) -> None:
        try:
            if not self._is_cold(frag):
                # A query (or its prefetch) got here first — the whole
                # point of lazy staging.
                pool.count_stage(done=1)
                job._finish_one(skipped=True)
                return
            if throttle_s > 0:
                time.sleep(throttle_s)
            frag.device_plane()
            pool.count_stage(done=1, nbytes=frag.plane_nbytes)
            job._finish_one(staged=True)
        except Exception as e:  # noqa: BLE001 — staging is best-effort,
            # but never silent: the error counts and the last one
            # surfaces in /debug/hbm.
            pool.count_stage(errors=1, last_error=repr(e))
            job._finish_one(error=True)
