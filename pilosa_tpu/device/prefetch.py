"""Async mirror prefetcher — overlap host->device staging with plan work.

A planned query's leaf fragments are known before its batch assembles
(exec/plan.py `collect_leaf_calls` + the executor's resolution walk);
any of them whose HBM mirror is cold would otherwise re-upload
serially, one 2-8 MiB `device_put` at a time, inside the assembly loop.
The prefetcher re-materializes those cold mirrors CONCURRENTLY on their
home devices — transfers to distinct devices genuinely overlap, and
even same-device uploads overlap the executor's host-side planning.

Workers call the same ``Fragment.device_plane()`` the query path uses,
so admission, budget eviction, and coherence all ride the fragment lock
— a prefetch can never produce a stale mirror, and the assembly thread
that reaches a fragment mid-upload simply blocks on that fragment's
lock until its mirror is ready (the overlap is across fragments, not
within one).

Threads are daemons for the same reason the executor's pool uses them:
a worker wedged inside a device call must degrade to a lost prefetch,
never a process that cannot exit.
"""

from __future__ import annotations

import queue
import threading

DEFAULT_WORKERS = 8


class Prefetcher:
    """Re-materialize cold fragment mirrors in background threads.

    ``pool`` supplies the hit/miss counters and is usually the global
    ``pilosa_tpu.device.pool()`` (the default when None).
    """

    def __init__(self, pool=None, max_workers: int = DEFAULT_WORKERS):
        self._pool = pool
        self._max_workers = max_workers
        self._work: "queue.SimpleQueue" = queue.SimpleQueue()
        self._threads: list[threading.Thread] = []
        self._idle = 0
        self._mu = threading.Lock()

    def pool(self):
        if self._pool is not None:
            return self._pool
        from pilosa_tpu import device as device_mod

        return device_mod.pool()

    def prefetch(self, frags, wait: bool = False) -> int:
        """Schedule uploads for every COLD fragment in ``frags``;
        already-resident mirrors count as prefetch hits.  Returns the
        number scheduled.  ``wait=True`` blocks until every scheduled
        upload finished (tests and the bench use it; the executor fires
        and forgets — per-fragment locks provide the synchronization)."""
        pool = self.pool()
        cold = []
        hits = 0
        for f in frags:
            if f is None:
                continue
            # Advisory peek (no lock): a racing writer only flips a
            # fragment cold, and the worker re-checks under the lock.
            if f._device is not None and f._device_version == f._version:
                hits += 1
            else:
                cold.append(f)
        if hits:
            pool.count_prefetch(hit=hits)
        if not cold:
            return 0
        done = threading.Event()
        remaining = [len(cold)]
        rlock = threading.Lock()
        for f in cold:
            self._submit(f, pool, remaining, rlock, done)
        if wait:
            done.wait()
        return len(cold)

    # ------------------------------------------------------------------

    def _submit(self, frag, pool, remaining, rlock, done) -> None:
        with self._mu:
            self._work.put((frag, pool, remaining, rlock, done))
            if self._idle == 0 and len(self._threads) < self._max_workers:
                t = threading.Thread(
                    target=self._worker, daemon=True, name="hbm-prefetch"
                )
                self._threads.append(t)
                t.start()

    def _worker(self) -> None:
        while True:
            with self._mu:
                self._idle += 1
            item = self._work.get()
            with self._mu:
                self._idle -= 1
            frag, pool, remaining, rlock, done = item
            try:
                was_cold = (
                    frag._device is None
                    or frag._device_version != frag._version
                )
                frag.device_plane()
                pool.count_prefetch(
                    hit=0 if was_cold else 1, miss=1 if was_cold else 0
                )
            except Exception:  # noqa: BLE001 — prefetch is best-effort;
                pass  # the query path re-raises any real failure itself
            finally:
                with rlock:
                    remaining[0] -= 1
                    if remaining[0] == 0:
                        done.set()
