"""One-shot stream-floor probe.

Measures, per local device, the achievable memory-stream bandwidth for
the access pattern the bitmap kernels actually have — a jitted
read-everything reduction over a contiguous uint32 buffer (HBM → VMEM →
VPU, no MXU).  The mean across devices becomes the roofline denominator
(``device.streamFloorGbps``): ``exec.launch.floorPct[site:*]`` is
achieved GB/s over THIS number, which is the online version of the
``bench.py`` stream-floor measurement ROADMAP item 2 tracks (BENCH_r05:
390.5 GB/s achieved vs 602.8 GB/s floor = 64.8%).

The probe runs once per process per backend (in-memory cache) and is
additionally cached in the server's artifact dir (``floorprobe.json``)
so restarts skip the measurement.  It is deliberately small —
single-digit MiB per device on CPU, 32 MiB on accelerators
(``PILOSA_FLOORPROBE_BYTES`` overrides) — a floor probe that slows
server open would get turned off.
"""

from __future__ import annotations

import json
import os
import threading
import time

ENV_BYTES = "PILOSA_FLOORPROBE_BYTES"

DEFAULT_PROBE_BYTES = 32 << 20  # accelerator backends
CPU_PROBE_BYTES = 4 << 20  # CPU backend (incl. the virtual test mesh)
WARMUP_ITERS = 1
TIMED_ITERS = 4

CACHE_FILE = "floorprobe.json"

_mu = threading.Lock()
_cache: dict[str, dict] = {}  # backend key -> probe result (per process)


def _backend_key(jax) -> str:
    devs = jax.local_devices()
    kind = getattr(devs[0], "device_kind", "?") if devs else "?"
    return f"{jax.default_backend()}:{kind}:{len(devs)}"


def _probe_bytes(backend: str) -> int:
    env = os.environ.get(ENV_BYTES)
    if env:
        try:
            n = int(env)
            if n > 0:
                return n
        except ValueError:
            pass
    return CPU_PROBE_BYTES if backend == "cpu" else DEFAULT_PROBE_BYTES


def _load_disk(artifact_dir: str | None, key: str) -> dict | None:
    if not artifact_dir:
        return None
    path = os.path.join(artifact_dir, CACHE_FILE)
    try:
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
        ent = doc.get(key)
        if isinstance(ent, dict) and "mean_gbps" in ent:
            return ent
    except (OSError, ValueError):
        pass
    return None


def _store_disk(artifact_dir: str | None, key: str, result: dict) -> None:
    if not artifact_dir:
        return
    path = os.path.join(artifact_dir, CACHE_FILE)
    try:
        os.makedirs(artifact_dir, exist_ok=True)
        doc: dict = {}
        try:
            with open(path, encoding="utf-8") as f:
                doc = json.load(f)
            if not isinstance(doc, dict):
                doc = {}
        except (OSError, ValueError):
            pass
        doc[key] = result
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
        os.replace(tmp, path)
    except OSError:
        pass  # a cache miss next boot, not an error


def _measure(jax, key: str) -> dict:
    import jax.numpy as jnp
    import numpy as np

    backend = jax.default_backend()
    n_bytes = _probe_bytes(backend)
    words = max(1, n_bytes // 4)
    host = np.ones(words, dtype=np.uint32)

    # Read-everything reduction: every word streams HBM->compute once
    # per call.  int32 accumulate keeps the VPU on the integer path the
    # bitmap kernels use (no MXU, no dtype upcast traffic).
    fn = jax.jit(lambda a: jnp.sum(a.astype(jnp.int32)))

    gbps: dict[str, float] = {}
    for dev in jax.local_devices():
        x = jax.device_put(host, dev)
        for _ in range(WARMUP_ITERS):
            fn(x).block_until_ready()  # compile + warm
        t0 = time.monotonic()
        for _ in range(TIMED_ITERS):
            fn(x).block_until_ready()
        dt = time.monotonic() - t0
        g = (words * 4 * TIMED_ITERS / dt / 1e9) if dt > 0 else 0.0
        gbps[str(getattr(dev, "id", len(gbps)))] = round(g, 3)
        del x
    vals = list(gbps.values())
    mean = sum(vals) / len(vals) if vals else 0.0
    return {
        "key": key,
        "probe_bytes": words * 4,
        "iters": TIMED_ITERS,
        "gbps": gbps,
        "mean_gbps": round(mean, 3),
    }


def probe(
    artifact_dir: str | None = None,
    stats=None,
    logger=None,
    force: bool = False,
) -> dict | None:
    """Measure (or load cached) per-device stream GB/s.

    Returns ``{"key", "probe_bytes", "iters", "gbps": {dev_id: g},
    "mean_gbps"}`` or None when jax is unavailable.  Emits the
    ``device.streamFloorGbps`` gauge (aggregate + per-device) when a
    stats client is passed."""
    try:
        import jax
    except Exception:  # pragma: no cover - jax is baked into the image
        return None
    try:
        key = _backend_key(jax)
        with _mu:
            cached = None if force else _cache.get(key)
        result = cached
        source = "memory"
        if result is None and not force:
            result = _load_disk(artifact_dir, key)
            source = "disk"
        if result is None:
            result = _measure(jax, key)
            source = "probe"
            _store_disk(artifact_dir, key, result)
        with _mu:
            _cache[key] = result
    except Exception as e:  # noqa: BLE001 - probe must never block open
        if logger is not None:
            logger(f"stream floor probe failed: {e}")
        return None
    if stats is not None:
        stats.gauge("device.streamFloorGbps", result["mean_gbps"])
        for dev_id, g in result["gbps"].items():
            stats.with_tags(f"device:{dev_id}").gauge(
                "device.streamFloorGbps", g
            )
    if logger is not None and source == "probe":
        logger(
            f"stream floor probe: {key} -> {result['mean_gbps']:.1f} GB/s "
            f"mean over {len(result['gbps'])} device(s)"
        )
    return result


def reset_cache() -> None:
    """Tests only: forget in-process probe results."""
    with _mu:
        _cache.clear()
