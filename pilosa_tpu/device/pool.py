"""PlanePool — the HBM residency manager.

Every device allocation the system keeps alive across queries registers
here: fragment plane mirrors (core/fragment.py `device_plane`), paged
sparse rows, and the executor's batch / TopN-prep cache entries
(exec/executor.py).  The pool keeps per-device byte accounting against a
budget (`[device] hbm-budget-bytes`) and reclaims by LRU eviction of
unpinned entries whenever an admission would exceed it — correctness is
free because the host numpy plane is always authoritative: an evicted
mirror simply rebuilds on the next read.

Design points:

* **Admission-before-upload.**  Owners call :meth:`admit` BEFORE the
  ``device_put``, so accounted residency never exceeds budget (modulo
  pinned saturation, which is counted, not hidden).
* **Pin leases.**  The executor pins the entries a fused program reads
  for the duration of dispatch+fetch; pinned entries are never victims,
  so eviction can never drop a plane mid-query.
* **Non-blocking evict callbacks.**  An evict callback must clear the
  owner's device reference under the OWNER's lock — but owners call
  into the pool while holding that lock (e.g. ``device_plane`` admits
  under the fragment lock).  To stay deadlock-free, callbacks acquire
  the owner lock with ``blocking=False`` and return False when they
  lose the race; the pool skips that victim (it is being actively used)
  and moves to the next.  The pool's own lock is reentrant, so a
  callback that calls back into :meth:`remove` is also safe.
* **LRU order** is the entry insertion/touch order; :meth:`touch` on a
  cache hit moves an entry to the MRU end.

Budget resolution (per device): an explicit positive ``configure``
value wins, then the ``PILOSA_DEVICE_HBM_BUDGET_BYTES`` env override,
then a safe fraction of the detected device memory
(``memory_stats()['bytes_limit']``), else unbounded — which is what the
CPU backend reports, so tests and laptops never evict unless asked to.
"""

from __future__ import annotations

import os
import threading
from collections import OrderedDict
from dataclasses import dataclass, field
from collections.abc import Callable

from pilosa_tpu.obs import trace
from pilosa_tpu.obs.stats import NopStatsClient

# Auto-detected budget = this fraction of the device's reported
# bytes_limit: headroom for XLA scratch, collectives, and transient
# program outputs that never register with the pool.
DEFAULT_BUDGET_FRACTION = 0.8

ENV_BUDGET = "PILOSA_DEVICE_HBM_BUDGET_BYTES"


def _device_label(dev) -> str:
    """Stable printable identity for a device key (jax Device or any
    hashable stand-in the unit tests use)."""
    i = getattr(dev, "id", None)
    if i is not None:
        return f"{getattr(dev, 'platform', 'dev')}:{i}"
    return str(dev)


@dataclass
class _Entry:
    key: tuple
    bytes_by_device: dict
    evict: Callable[[], bool]
    category: str  # "mirror" | "sparse" | "cache"
    info: dict = field(default_factory=dict)
    pins: int = 0

    @property
    def nbytes(self) -> int:
        return sum(self.bytes_by_device.values())


class PlanePool:
    """Per-device byte accounting + LRU eviction for long-lived device
    arrays.  Thread-safe; one instance serves the whole process (see
    ``pilosa_tpu.device.pool()``)."""

    def __init__(self, budget_bytes: int = 0, stats=None, tracer=None):
        # Reentrant: evict callbacks may legally call remove()/resize()
        # back into the pool from under _mu.
        self._mu = threading.RLock()
        self._entries: "OrderedDict[tuple, _Entry]" = OrderedDict()
        self._resident: dict = {}  # device -> bytes
        self._pinned: dict = {}  # device -> bytes held by pinned entries
        self._max_resident: dict = {}  # device -> high-water bytes
        self._cat_bytes: dict[str, int] = {}  # category -> bytes
        self._evictions = 0
        self._evict_skipped = 0
        self._over_budget = 0
        self._prefetch_hits = 0
        self._prefetch_misses = 0
        # Cold-staging progress (core/holder.stage_device_mirrors +
        # device/prefetch.py): scheduled/done/error counts, total bytes
        # staged, and the LAST staging error — warm_device_mirrors once
        # swallowed failures with only a log line; now every failure
        # counts and the latest surfaces in /debug/hbm.
        self._stage_scheduled = 0
        self._stage_done = 0
        self._stage_errors = 0
        self._stage_bytes = 0
        self._stage_last_error: str | None = None
        # Full mirror (re)uploads through Fragment.device_plane — the
        # cost the ingest delta-scatter exists to avoid.  Bytes, not
        # counts: a write storm that invalidates per-bit shows up as
        # plane_nbytes x writes here, vs one upload with scatter on.
        self._restage_uploads = 0
        self._restage_bytes = 0
        # 0 = auto (env -> detect -> unbounded); > 0 = explicit bytes.
        self._budget = int(budget_bytes or 0)
        self._detected: int | None = None
        self.stats = stats or NopStatsClient()
        self.tracer = tracer or trace.NOP_TRACER
        self._dev_stats: dict = {}  # device -> tagged stats child

    # ------------------------------------------------------------------
    # configuration / budget
    # ------------------------------------------------------------------

    def configure(self, budget_bytes: int | None = None, stats=None, tracer=None) -> None:
        """Server wiring: budget from config (0 = auto), stats/tracer
        for gauges and evict/prefetch spans."""
        with self._mu:
            if budget_bytes is not None:
                self._budget = int(budget_bytes)
            if stats is not None:
                self.stats = stats
                self._dev_stats.clear()
            if tracer is not None:
                self.tracer = tracer

    def budget_bytes(self) -> int:
        """The effective PER-DEVICE budget; 0 means unbounded."""
        if self._budget > 0:
            return self._budget
        raw = os.environ.get(ENV_BUDGET, "")
        if raw:
            try:
                v = int(raw)
                if v > 0:
                    return v
            except ValueError:
                pass
        return self._detect_budget()

    def _detect_budget(self) -> int:
        detected = self._detected
        if detected is None:
            limit = 0
            try:
                import jax

                ms = getattr(jax.local_devices()[0], "memory_stats", None)
                mem = ms() if callable(ms) else None
                if mem and mem.get("bytes_limit"):
                    limit = int(mem["bytes_limit"] * DEFAULT_BUDGET_FRACTION)
            except Exception:  # noqa: BLE001 — detection is best-effort
                limit = 0
            self._detected = detected = limit
        return detected

    # ------------------------------------------------------------------
    # tenant lifecycle
    # ------------------------------------------------------------------

    def admit(
        self,
        key: tuple,
        bytes_by_device: dict,
        evict: Callable[[], bool],
        category: str = "cache",
        info: dict | None = None,
    ) -> None:
        """Register (or re-register with new bytes) an entry, evicting
        LRU unpinned entries first so every touched device stays within
        budget.  Call BEFORE the actual device allocation; on upload
        failure call :meth:`remove`.  Re-admission preserves pins."""
        budget = self.budget_bytes()
        need = {d: int(n) for d, n in bytes_by_device.items() if n}
        # Stats emission happens AFTER the critical section: a stats
        # backend (UDP sendto, tag formatting) must never extend the
        # pool lock's hold time — this is the hottest query-path lock.
        n_ev = n_skip = 0
        over_budget = False
        with self._mu:
            old = self._entries.pop(key, None)
            pins = 0
            if old is not None:
                pins = old.pins
                self._debit(old)
            if budget and need and any(
                self._resident.get(d, 0) + n > budget for d, n in need.items()
            ):
                with self.tracer.span("evict", trigger=category) as sp:
                    n_ev, n_skip = self._evict_for_locked(need, budget, key)
                    sp.annotate(evicted=n_ev)
                if n_ev:
                    self._evictions += n_ev
            ent = _Entry(
                key=key,
                bytes_by_device=need,
                evict=evict,
                category=category,
                info=dict(info or {}),
                pins=pins,
            )
            self._entries[key] = ent
            self._credit(ent)
            if budget and any(
                self._resident.get(d, 0) > budget for d in need
            ):
                # All remaining tenants on the device were pinned (or
                # their owners were busy): correctness beats the budget,
                # but the breach is counted, never silent.
                self._over_budget += 1
                over_budget = True
            gauges = self._gauges_locked(need)
        if n_ev:
            self.stats.count("device.evictions", n_ev)
        if n_skip:
            self.stats.count("device.evictSkipped", n_skip)
        if over_budget:
            self.stats.count("device.overBudget")
        self._publish(gauges)

    def touch(self, key: tuple) -> None:
        with self._mu:
            if key in self._entries:
                self._entries.move_to_end(key)

    def resize(
        self, key: tuple, bytes_by_device: dict, info: dict | None = None
    ) -> None:
        """Update an entry's bytes in place (e.g. the sparse-row cache
        shrinking) without changing its LRU position or running
        admission eviction.  ``info`` (when given) replaces the entry's
        snapshot annotations — the compressed-container cache keeps its
        logical-bytes/format-mix surface current this way."""
        gauges = []
        with self._mu:
            ent = self._entries.get(key)
            if ent is None:
                return
            self._debit(ent)
            ent.bytes_by_device = {
                d: int(n) for d, n in bytes_by_device.items() if n
            }
            if info is not None:
                ent.info = dict(info)
            self._credit(ent)
            gauges = self._gauges_locked(ent.bytes_by_device)
        self._publish(gauges)

    def remove(self, key: tuple) -> None:
        gauges = []
        with self._mu:
            ent = self._entries.pop(key, None)
            if ent is not None:
                self._debit(ent)
                gauges = self._gauges_locked(ent.bytes_by_device)
        self._publish(gauges)

    def contains(self, key: tuple) -> bool:
        with self._mu:
            return key in self._entries

    # ------------------------------------------------------------------
    # pin leases
    # ------------------------------------------------------------------

    def pin(self, key: tuple) -> bool:
        """Take a pin lease on an entry; False when it is not resident
        (the caller's snapshot reference still keeps its array alive —
        the lease only guards the POOL's eviction choices)."""
        with self._mu:
            ent = self._entries.get(key)
            if ent is None:
                return False
            ent.pins += 1
            if ent.pins == 1:
                for d, n in ent.bytes_by_device.items():
                    self._pinned[d] = self._pinned.get(d, 0) + n
            return True

    def unpin(self, key: tuple) -> None:
        with self._mu:
            ent = self._entries.get(key)
            if ent is None or ent.pins == 0:
                return
            ent.pins -= 1
            if ent.pins == 0:
                for d, n in ent.bytes_by_device.items():
                    self._pinned[d] = max(0, self._pinned.get(d, 0) - n)

    def pin_many(self, keys) -> list:
        """Pin every present key under ONE lock acquisition; returns
        the keys actually pinned (for the matching :meth:`unpin_many`).
        A fused multi-query launch pins the UNION plane set of its
        whole drained batch — per-key lock round trips would scale the
        pool's hottest lock with batch occupancy."""
        held = []
        with self._mu:
            for k in keys:
                if k is None:
                    continue
                ent = self._entries.get(k)
                if ent is None:
                    continue
                ent.pins += 1
                if ent.pins == 1:
                    for d, n in ent.bytes_by_device.items():
                        self._pinned[d] = self._pinned.get(d, 0) + n
                held.append(k)
        return held

    def unpin_many(self, keys) -> None:
        with self._mu:
            for k in keys:
                ent = self._entries.get(k)
                if ent is None or ent.pins == 0:
                    continue
                ent.pins -= 1
                if ent.pins == 0:
                    for d, n in ent.bytes_by_device.items():
                        self._pinned[d] = max(
                            0, self._pinned.get(d, 0) - n
                        )

    class _PinLease:
        def __init__(self, pool: "PlanePool", keys):
            self._pool = pool
            self._keys = keys
            self._held: list = []

        def __enter__(self):
            # One lock acquisition however many keys the launch pins.
            self._held = self._pool.pin_many(self._keys)
            return self

        def __exit__(self, *exc):
            self._pool.unpin_many(self._held)

    def pinned(self, *keys) -> "PlanePool._PinLease":
        """Context manager pinning every present key for the block —
        the executor's per-program lease.  None keys are skipped."""
        return PlanePool._PinLease(self, keys)

    # ------------------------------------------------------------------
    # eviction (callers hold _mu)
    # ------------------------------------------------------------------

    def _evict_for_locked(self, need: dict, budget: int, exclude_key) -> tuple:
        """Returns ``(evicted, skipped)`` counts; the caller emits the
        stats for both outside the lock."""
        evicted = 0
        skipped = 0
        for k in list(self._entries.keys()):
            if all(
                self._resident.get(d, 0) + n <= budget
                for d, n in need.items()
            ):
                break
            if k == exclude_key:
                continue
            ent = self._entries.get(k)
            if ent is None or ent.pins > 0:
                continue
            # Only evicting entries that share a device with the need
            # can make room.
            if not any(d in need for d in ent.bytes_by_device):
                continue
            try:
                ok = bool(ent.evict())
            except Exception:  # noqa: BLE001 — a broken owner must not
                ok = True  # wedge the pool; drop the accounting.
            if ok:
                # The callback may have re-entered remove() itself.
                ent2 = self._entries.pop(k, None)
                if ent2 is not None:
                    self._debit(ent2)
                evicted += 1
            else:
                self._evict_skipped += 1
                skipped += 1
        return evicted, skipped

    # ------------------------------------------------------------------
    # accounting (callers hold _mu)
    # ------------------------------------------------------------------

    def _credit(self, ent: _Entry) -> None:
        for d, n in ent.bytes_by_device.items():
            r = self._resident.get(d, 0) + n
            self._resident[d] = r
            if r > self._max_resident.get(d, 0):
                self._max_resident[d] = r
            if ent.pins > 0:
                self._pinned[d] = self._pinned.get(d, 0) + n
        self._cat_bytes[ent.category] = (
            self._cat_bytes.get(ent.category, 0) + ent.nbytes
        )

    def _debit(self, ent: _Entry) -> None:
        for d, n in ent.bytes_by_device.items():
            self._resident[d] = max(0, self._resident.get(d, 0) - n)
            if ent.pins > 0:
                self._pinned[d] = max(0, self._pinned.get(d, 0) - n)
        self._cat_bytes[ent.category] = max(
            0, self._cat_bytes.get(ent.category, 0) - ent.nbytes
        )

    def _dev_stat(self, dev):
        # Called outside _mu (stats must not extend the critical
        # section); a racing create stores two equivalent children and
        # the last write wins — benign.
        c = self._dev_stats.get(dev)
        if c is None:
            c = self.stats.with_tags(f"device:{_device_label(dev)}")
            self._dev_stats[dev] = c
        return c

    def _gauges_locked(self, devices) -> list:
        """Snapshot the gauge values for ``devices`` under ``_mu``; the
        caller publishes via :meth:`_publish` AFTER releasing it (a
        stats backend must never extend the pool's critical section)."""
        out = [
            (d, "device.residentBytes", float(self._resident.get(d, 0)))
            for d in devices
        ]
        out.append(
            (None, "device.cacheBytes", float(self._cat_bytes.get("cache", 0)))
        )
        return out

    def _publish(self, gauges) -> None:
        for dev, name, value in gauges:
            client = self.stats if dev is None else self._dev_stat(dev)
            client.gauge(name, value)

    # ------------------------------------------------------------------
    # prefetch bookkeeping (incremented by device/prefetch.py)
    # ------------------------------------------------------------------

    def count_prefetch(self, hit: int = 0, miss: int = 0) -> None:
        with self._mu:
            self._prefetch_hits += hit
            self._prefetch_misses += miss
        if hit:
            self.stats.count("device.prefetch.hit", hit)
        if miss:
            self.stats.count("device.prefetch.miss", miss)

    def count_stage(
        self,
        scheduled: int = 0,
        done: int = 0,
        errors: int = 0,
        nbytes: int = 0,
        last_error: str | None = None,
    ) -> None:
        """Cold-staging bookkeeping (``device.stage.*`` counters) — fed
        by the holder's background stager and warm_device_mirrors."""
        with self._mu:
            self._stage_scheduled += scheduled
            self._stage_done += done
            self._stage_errors += errors
            self._stage_bytes += nbytes
            if last_error is not None:
                self._stage_last_error = str(last_error)
        if scheduled:
            self.stats.count("device.stage.scheduled", scheduled)
        if done:
            self.stats.count("device.stage.done", done)
        if errors:
            self.stats.count("device.stage.errors", errors)
        if nbytes:
            self.stats.count("device.stage.bytes", nbytes)

    def count_restage(self, nbytes: int) -> None:
        """One full plane upload through ``Fragment.device_plane`` (the
        ``device.pool.restageBytes`` counter the ingest bench contrasts
        against scatter launches)."""
        with self._mu:
            self._restage_uploads += 1
            self._restage_bytes += int(nbytes)
        if nbytes:
            self.stats.count("device.pool.restageBytes", int(nbytes))

    def restage_bytes(self) -> int:
        with self._mu:
            return self._restage_bytes

    # ------------------------------------------------------------------
    # introspection
    # ------------------------------------------------------------------

    @property
    def evictions(self) -> int:
        return self._evictions

    def resident_bytes(self, dev=None) -> int:
        with self._mu:
            if dev is not None:
                return self._resident.get(dev, 0)
            return sum(self._resident.values())

    def max_resident_bytes(self, dev=None) -> int:
        with self._mu:
            if dev is not None:
                return self._max_resident.get(dev, 0)
            return max(self._max_resident.values(), default=0)

    def snapshot(self) -> dict:
        """JSON-ready state for ``GET /debug/hbm``: per-device budget /
        resident / pinned / high-water bytes with each device's entries
        (LRU -> MRU), a flat per-fragment residency table, and the
        eviction/prefetch counters."""
        budget = self.budget_bytes()
        with self._mu:
            per_dev: dict = {}
            fragments: list[dict] = []
            resident_total = 0
            logical_total = 0
            for ent in self._entries.values():  # LRU -> MRU order
                # Compressed-container entries annotate the dense bytes
                # they REPLACE (info["logical_bytes"]); everything else
                # is stored at its logical geometry.
                logical = int(ent.info.get("logical_bytes", ent.nbytes))
                resident_total += ent.nbytes
                logical_total += logical
                row = {
                    "kind": ent.category,
                    "bytes": ent.nbytes,
                    "logical_bytes": logical,
                    "pinned": ent.pins > 0,
                }
                if len(ent.bytes_by_device) > 1:
                    # Mesh-sharded entry: each device's row below shows
                    # only ITS shard's bytes; `bytes` is the global size.
                    row["sharded"] = True
                    row["shards"] = len(ent.bytes_by_device)
                row.update(ent.info)
                for d, n in ent.bytes_by_device.items():
                    dd = per_dev.setdefault(
                        d,
                        {
                            "device": _device_label(d),
                            "budget_bytes": budget,
                            "resident_bytes": self._resident.get(d, 0),
                            "pinned_bytes": self._pinned.get(d, 0),
                            "max_resident_bytes": self._max_resident.get(d, 0),
                            "entries": [],
                        },
                    )
                    dd["entries"].append(dict(row, bytes=n))
                if "fragment" in ent.info:
                    fragments.append(
                        dict(
                            row,
                            devices=[
                                _device_label(d) for d in ent.bytes_by_device
                            ],
                        )
                    )
            return {
                "budget_bytes": budget,
                "cache_bytes": self._cat_bytes.get("cache", 0),
                # Compressed-plane headline: resident HBM vs what the
                # same entries would cost at dense geometry.
                "resident_bytes": resident_total,
                "logical_bytes": logical_total,
                "compression_ratio": round(
                    logical_total / resident_total, 3
                )
                if resident_total
                else 1.0,
                "devices": sorted(
                    per_dev.values(), key=lambda d: d["device"]
                ),
                "fragments": fragments,
                "counters": {
                    "evictions": self._evictions,
                    "evictSkipped": self._evict_skipped,
                    "overBudget": self._over_budget,
                    "prefetchHit": self._prefetch_hits,
                    "prefetchMiss": self._prefetch_misses,
                    "restageUploads": self._restage_uploads,
                    "restageBytes": self._restage_bytes,
                },
                # Cold-staging progress for rolling restarts: a
                # restarted node serves while this drains toward
                # scheduled == done + errors.
                "staging": {
                    "scheduled": self._stage_scheduled,
                    "done": self._stage_done,
                    "errors": self._stage_errors,
                    "pending": max(
                        0,
                        self._stage_scheduled
                        - self._stage_done
                        - self._stage_errors,
                    ),
                    "bytes": self._stage_bytes,
                    "last_error": self._stage_last_error,
                },
            }
