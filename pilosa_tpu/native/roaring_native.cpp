// Native roaring codec + CSV parser — the host-IO hot path.
//
// The reference's only native code is the amd64 popcount assembly
// (reference: roaring/assembly_amd64.s); its compute role moves to
// XLA/Pallas kernels in this framework.  What stays hot on the *host*
// here is file IO around the device: decoding roaring snapshots on
// fragment open (reference format: roaring/roaring.go:507-660),
// re-encoding on snapshot, op-log replay with per-record FNV-1a
// checksums, and CSV bit parsing on bulk import (reference:
// ctl/import.go).  Those loops are this library; Python falls back to
// pilosa_tpu/ops/roaring.py when it is unavailable and the two are
// kept byte-identical by parity tests.
//
// Build: g++ -O3 -shared -fPIC (driven by pilosa_tpu/native/__init__.py).
// ABI: plain C functions over caller-owned buffers + one opaque handle
// for decode results (ctypes-friendly; no pybind11 dependency).

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <map>
#include <string>
#include <vector>

namespace {

constexpr uint32_t kCookie = 12346;
constexpr int64_t kHeaderSize = 8;
constexpr int64_t kArrayMaxSize = 4096;
constexpr int64_t kContainerBits = 1 << 16;
constexpr int64_t kContainerWords = kContainerBits / 64;  // 1024
constexpr int64_t kOpSize = 13;

uint32_t fnv1a32(const uint8_t* data, int64_t n) {
  uint32_t h = 0x811C9DC5u;
  for (int64_t i = 0; i < n; i++) {
    h ^= data[i];
    h *= 0x01000193u;
  }
  return h;
}

uint16_t rd16(const uint8_t* p) {
  uint16_t v;
  std::memcpy(&v, p, 2);
  return v;
}
uint32_t rd32(const uint8_t* p) {
  uint32_t v;
  std::memcpy(&v, p, 4);
  return v;
}
uint64_t rd64(const uint8_t* p) {
  uint64_t v;
  std::memcpy(&v, p, 8);
  return v;
}

void wr32(uint8_t* p, uint32_t v) { std::memcpy(p, &v, 4); }
void wr64(uint8_t* p, uint64_t v) { std::memcpy(p, &v, 8); }

struct Bitmap {
  // ordered: iteration yields sorted keys, matching the Python codec
  std::map<uint64_t, std::vector<uint64_t>> containers;
  int64_t ops = 0;
  std::string error;
};

void set_err(Bitmap* bm, const char* msg) { bm->error = msg; }

}  // namespace

extern "C" {

// ---------------------------------------------------------------------------
// decode
// ---------------------------------------------------------------------------

// Parse a roaring file (containers + op-log).  Returns a handle; check
// ptpu_error() before using it.  (reference: roaring/roaring.go:567-660)
void* ptpu_decode(const uint8_t* data, int64_t len) {
  auto* bm = new Bitmap();
  if (len < kHeaderSize) {
    set_err(bm, "data too small");
    return bm;
  }
  uint32_t cookie = rd32(data);
  uint32_t key_n = rd32(data + 4);
  if (cookie != kCookie) {
    set_err(bm, "invalid roaring file");
    return bm;
  }
  if (kHeaderSize + (int64_t)key_n * 16 > len) {
    bm->error = "header claims " + std::to_string(key_n) +
                " containers but file is " + std::to_string(len) + " bytes";
    return bm;
  }
  const uint8_t* headers = data + kHeaderSize;
  const uint8_t* offsets = headers + (int64_t)key_n * 12;
  int64_t ops_offset = kHeaderSize + (int64_t)key_n * 16;
  for (uint32_t i = 0; i < key_n; i++) {
    uint64_t key = rd64(headers + (int64_t)i * 12);
    int64_t n = (int64_t)rd32(headers + (int64_t)i * 12 + 8) + 1;
    uint32_t offset = rd32(offsets + (int64_t)i * 4);
    if ((int64_t)offset >= len) {
      set_err(bm, "offset out of bounds");
      return bm;
    }
    int64_t payload = (n <= kArrayMaxSize) ? n * 4 : kContainerWords * 8;
    if ((int64_t)offset + payload > len) {
      set_err(bm, "container payload out of bounds");
      return bm;
    }
    std::vector<uint64_t> words(kContainerWords, 0);
    if (n <= kArrayMaxSize) {
      const uint8_t* vals = data + offset;
      for (int64_t j = 0; j < n; j++) {
        uint32_t v = rd32(vals + j * 4);
        if (v >= kContainerBits) {
          set_err(bm, "array value out of range");
          return bm;
        }
        words[v >> 6] |= (uint64_t)1 << (v & 63);
      }
    } else {
      std::memcpy(words.data(), data + offset, kContainerWords * 8);
    }
    bm->containers[key] = std::move(words);
    int64_t end = (int64_t)offset + payload;
    if (end > ops_offset) ops_offset = end;
  }

  // op-log replay (reference: roaring/roaring.go:622-646)
  int64_t pos = ops_offset;
  while (pos < len) {
    if (len - pos < kOpSize) {
      set_err(bm, "op data out of bounds");
      return bm;
    }
    uint8_t typ = data[pos];
    uint64_t value = rd64(data + pos + 1);
    uint32_t chk = rd32(data + pos + 9);
    if (chk != fnv1a32(data + pos, 9)) {
      set_err(bm, "checksum mismatch");
      return bm;
    }
    uint64_t key = value >> 16;
    uint64_t off = value & 0xFFFF;
    auto it = bm->containers.find(key);
    if (it == bm->containers.end()) {
      it = bm->containers.emplace(key, std::vector<uint64_t>(kContainerWords, 0))
               .first;
    }
    uint64_t mask = (uint64_t)1 << (off & 63);
    if (typ == 0) {
      it->second[off >> 6] |= mask;
    } else if (typ == 1) {
      it->second[off >> 6] &= ~mask;
    } else {
      set_err(bm, "invalid op type");
      return bm;
    }
    pos += kOpSize;
    bm->ops++;
  }
  return bm;
}

const char* ptpu_error(void* h) {
  auto* bm = static_cast<Bitmap*>(h);
  return bm->error.empty() ? nullptr : bm->error.c_str();
}

// ---------------------------------------------------------------------------
// tiered decode: array containers stay as sorted value vectors (pay-per-bit;
// a tall-sparse file has one array container per row), bitmap containers as
// word vectors.  Mirrors ops/roaring.decode_tiered.
// ---------------------------------------------------------------------------

// Tiered decode result.  Containers UNTOUCHED by the op-log are kept as
// offsets into the caller's input buffer (typically an mmap of the data
// file) and only memcpy'd once, straight into the caller's numpy
// arrays at extract time; op-touched containers materialize
// copy-on-write.  Post-snapshot files carry at most a few thousand ops,
// so this keeps peak native heap at O(touched) instead of O(file).
// The input pointer must stay valid until ptpu_t_extract — the Python
// wrapper performs decode+extract in one call while holding the mmap.
struct Tiered {
  const uint8_t* input = nullptr;
  std::map<uint64_t, int64_t> word_offs;    // key -> input offset
  std::map<uint64_t, std::vector<uint64_t>> words;  // op-touched
  struct ArrRef { int64_t off; int64_t n; };
  std::map<uint64_t, ArrRef> array_offs;    // key -> input run
  std::map<uint64_t, std::vector<uint32_t>> arrays;  // op-touched
  int64_t ops = 0;
  int64_t total_vals = 0;
  std::string error;

  void materialize_words(uint64_t key) {
    auto it = word_offs.find(key);
    if (it == word_offs.end()) return;
    std::vector<uint64_t> w(kContainerWords);
    std::memcpy(w.data(), input + it->second, kContainerWords * 8);
    words[key] = std::move(w);
    word_offs.erase(it);
  }

  void materialize_array(uint64_t key) {
    auto it = array_offs.find(key);
    if (it == array_offs.end()) return;
    std::vector<uint32_t> vals((size_t)it->second.n);
    std::memcpy(vals.data(), input + it->second.off, (size_t)it->second.n * 4);
    arrays[key] = std::move(vals);
    array_offs.erase(it);
  }
};

void* ptpu_decode_tiered(const uint8_t* data, int64_t len) {
  auto* t = new Tiered();
  t->input = data;
  if (len < kHeaderSize) {
    t->error = "data too small";
    return t;
  }
  uint32_t cookie = rd32(data);
  uint32_t key_n = rd32(data + 4);
  if (cookie != kCookie) {
    t->error = "invalid roaring file";
    return t;
  }
  if (kHeaderSize + (int64_t)key_n * 16 > len) {
    t->error = "header claims " + std::to_string(key_n) +
               " containers but file is " + std::to_string(len) + " bytes";
    return t;
  }
  const uint8_t* headers = data + kHeaderSize;
  const uint8_t* offsets = headers + (int64_t)key_n * 12;
  int64_t ops_offset = kHeaderSize + (int64_t)key_n * 16;
  for (uint32_t i = 0; i < key_n; i++) {
    uint64_t key = rd64(headers + (int64_t)i * 12);
    int64_t n = (int64_t)rd32(headers + (int64_t)i * 12 + 8) + 1;
    uint32_t offset = rd32(offsets + (int64_t)i * 4);
    if ((int64_t)offset >= len) {
      t->error = "offset out of bounds";
      return t;
    }
    int64_t payload = (n <= kArrayMaxSize) ? n * 4 : kContainerWords * 8;
    if ((int64_t)offset + payload > len) {
      t->error = "container payload out of bounds";
      return t;
    }
    if (n <= kArrayMaxSize) {
      // Validate in place; store only the input run.
      uint32_t prev = 0;
      for (int64_t j = 0; j < n; j++) {
        uint32_t v = rd32(data + offset + j * 4);
        if (v >= kContainerBits) {
          t->error = "array value out of range";
          return t;
        }
        if (j > 0 && v <= prev) {
          t->error = "array container is not sorted/unique";
          return t;
        }
        prev = v;
      }
      t->total_vals += n;
      t->array_offs[key] = Tiered::ArrRef{(int64_t)offset, n};
    } else {
      t->word_offs[key] = (int64_t)offset;
    }
    int64_t end = (int64_t)offset + payload;
    if (end > ops_offset) ops_offset = end;
  }

  // op-log replay over tiered forms
  int64_t pos = ops_offset;
  while (pos < len) {
    if (len - pos < kOpSize) {
      t->error = "op data out of bounds";
      return t;
    }
    uint8_t typ = data[pos];
    uint64_t value = rd64(data + pos + 1);
    uint32_t chk = rd32(data + pos + 9);
    if (chk != fnv1a32(data + pos, 9)) {
      t->error = "checksum mismatch";
      return t;
    }
    if (typ > 1) {
      t->error = "invalid op type";
      return t;
    }
    uint64_t key = value >> 16;
    uint32_t low = (uint32_t)(value & 0xFFFF);
    // Copy-on-write: an op touching an offset-tier container
    // materializes it first.
    t->materialize_words(key);
    auto wit = t->words.find(key);
    if (wit != t->words.end()) {
      uint64_t mask = (uint64_t)1 << (low & 63);
      if (typ == 0)
        wit->second[low >> 6] |= mask;
      else
        wit->second[low >> 6] &= ~mask;
    } else {
      t->materialize_array(key);
      auto& vals = t->arrays[key];  // creates empty on first touch
      auto it = std::lower_bound(vals.begin(), vals.end(), low);
      bool present = it != vals.end() && *it == low;
      if (typ == 0 && !present) {
        vals.insert(it, low);
        t->total_vals++;
      } else if (typ == 1 && present) {
        vals.erase(it);
        t->total_vals--;
      }
    }
    pos += kOpSize;
    t->ops++;
  }
  return t;
}

const char* ptpu_t_error(void* h) {
  auto* t = static_cast<Tiered*>(h);
  return t->error.empty() ? nullptr : t->error.c_str();
}

int64_t ptpu_t_ops(void* h) { return static_cast<Tiered*>(h)->ops; }

void ptpu_t_counts(void* h, int64_t* n_words, int64_t* n_arrays,
                   int64_t* total_vals) {
  auto* t = static_cast<Tiered*>(h);
  *n_words = (int64_t)(t->words.size() + t->word_offs.size());
  *n_arrays = (int64_t)(t->arrays.size() + t->array_offs.size());
  *total_vals = t->total_vals;
}

// Fill wkeys[nw], wwords[nw*1024], akeys[na], alens[na], avals[total].
void ptpu_t_extract(void* h, uint64_t* wkeys, uint64_t* wwords, uint64_t* akeys,
                    int64_t* alens, uint32_t* avals) {
  // Two-way sorted merge of the offset tier (copied straight from the
  // caller's input buffer — its single copy) and the op-touched tier.
  auto* t = static_cast<Tiered*>(h);
  int64_t i = 0;
  auto wo = t->word_offs.begin();
  auto wm = t->words.begin();
  while (wo != t->word_offs.end() || wm != t->words.end()) {
    bool take_off =
        wm == t->words.end() ||
        (wo != t->word_offs.end() && wo->first < wm->first);
    if (take_off) {
      wkeys[i] = wo->first;
      std::memcpy(wwords + i * kContainerWords, t->input + wo->second,
                  kContainerWords * 8);
      ++wo;
    } else {
      wkeys[i] = wm->first;
      std::memcpy(wwords + i * kContainerWords, wm->second.data(),
                  kContainerWords * 8);
      ++wm;
    }
    i++;
  }
  int64_t j = 0, at = 0;
  auto ao = t->array_offs.begin();
  auto am = t->arrays.begin();
  while (ao != t->array_offs.end() || am != t->arrays.end()) {
    bool take_off =
        am == t->arrays.end() ||
        (ao != t->array_offs.end() && ao->first < am->first);
    if (take_off) {
      akeys[j] = ao->first;
      alens[j] = ao->second.n;
      std::memcpy(avals + at, t->input + ao->second.off, ao->second.n * 4);
      at += ao->second.n;
      ++ao;
    } else {
      akeys[j] = am->first;
      alens[j] = (int64_t)am->second.size();
      std::memcpy(avals + at, am->second.data(), am->second.size() * 4);
      at += (int64_t)am->second.size();
      ++am;
    }
    j++;
  }
}

void ptpu_t_free(void* h) { delete static_cast<Tiered*>(h); }

int64_t ptpu_nkeys(void* h) {
  return (int64_t)static_cast<Bitmap*>(h)->containers.size();
}

int64_t ptpu_ops(void* h) { return static_cast<Bitmap*>(h)->ops; }

// Fill keys[nkeys] and words[nkeys*1024] (sorted by key).
void ptpu_extract(void* h, uint64_t* keys, uint64_t* words) {
  auto* bm = static_cast<Bitmap*>(h);
  int64_t i = 0;
  for (const auto& [key, w] : bm->containers) {
    keys[i] = key;
    std::memcpy(words + i * kContainerWords, w.data(), kContainerWords * 8);
    i++;
  }
}

void ptpu_free(void* h) { delete static_cast<Bitmap*>(h); }

// ---------------------------------------------------------------------------
// encode
// ---------------------------------------------------------------------------

// keys must be sorted ascending; words is nkeys*1024 u64.  Empty
// containers are dropped, n<=4096 written as sorted u32 arrays
// (reference: roaring/roaring.go:507-565).  Two-phase: size, then fill.
int64_t ptpu_encode_size(const uint64_t* keys, const uint64_t* words,
                         int64_t nkeys) {
  (void)keys;
  int64_t n_used = 0, payload = 0;
  for (int64_t i = 0; i < nkeys; i++) {
    const uint64_t* w = words + i * kContainerWords;
    int64_t n = 0;
    for (int64_t j = 0; j < kContainerWords; j++) n += __builtin_popcountll(w[j]);
    if (n == 0) continue;
    n_used++;
    payload += (n <= kArrayMaxSize) ? n * 4 : kContainerWords * 8;
  }
  return kHeaderSize + n_used * 16 + payload;
}

int64_t ptpu_encode(const uint64_t* keys, const uint64_t* words, int64_t nkeys,
                    uint8_t* out, int64_t cap) {
  // first pass: counts
  std::vector<int64_t> ns;
  std::vector<int64_t> used;
  ns.reserve(nkeys);
  for (int64_t i = 0; i < nkeys; i++) {
    const uint64_t* w = words + i * kContainerWords;
    int64_t n = 0;
    for (int64_t j = 0; j < kContainerWords; j++) n += __builtin_popcountll(w[j]);
    if (n == 0) continue;
    used.push_back(i);
    ns.push_back(n);
  }
  int64_t n_used = (int64_t)used.size();
  int64_t header_len = kHeaderSize + n_used * 12;
  int64_t offsets_at = header_len;
  int64_t total = header_len + n_used * 4;
  for (int64_t n : ns) total += (n <= kArrayMaxSize) ? n * 4 : kContainerWords * 8;
  if (total > cap) return -1;

  wr32(out, kCookie);
  wr32(out + 4, (uint32_t)n_used);
  int64_t payload_at = offsets_at + n_used * 4;
  for (int64_t i = 0; i < n_used; i++) {
    wr64(out + kHeaderSize + i * 12, keys[used[i]]);
    wr32(out + kHeaderSize + i * 12 + 8, (uint32_t)(ns[i] - 1));
    wr32(out + offsets_at + i * 4, (uint32_t)payload_at);
    const uint64_t* w = words + used[i] * kContainerWords;
    if (ns[i] <= kArrayMaxSize) {
      uint8_t* p = out + payload_at;
      for (int64_t j = 0; j < kContainerWords; j++) {
        uint64_t word = w[j];
        while (word) {
          int bit = __builtin_ctzll(word);
          wr32(p, (uint32_t)(j * 64 + bit));
          p += 4;
          word &= word - 1;
        }
      }
      payload_at += ns[i] * 4;
    } else {
      std::memcpy(out + payload_at, w, kContainerWords * 8);
      payload_at += kContainerWords * 8;
    }
  }
  return total;
}

// One 13-byte op-log record (reference: roaring/roaring.go:1746-1762).
void ptpu_encode_op(uint8_t typ, uint64_t value, uint8_t* out13) {
  out13[0] = typ;
  wr64(out13 + 1, value);
  wr32(out13 + 9, fnv1a32(out13, 9));
}

// ---------------------------------------------------------------------------
// CSV bit parsing (import hot path; reference: ctl/import.go:95-175)
// ---------------------------------------------------------------------------

// Parse "row,col\n" records into rows[]/cols[].  Blank lines and \r\n
// tolerated.  Returns the record count, or:
//   -1  malformed number / structure (caller falls back to Python csv)
//   -2  a record has a third field (timestamps need Python's datetime)
//   -3  capacity exceeded
int64_t ptpu_parse_csv(const uint8_t* buf, int64_t len, uint64_t* rows,
                       uint64_t* cols, int64_t cap) {
  int64_t n = 0;
  int64_t i = 0;
  while (i < len) {
    // skip blank lines
    if (buf[i] == '\n' || buf[i] == '\r') {
      i++;
      continue;
    }
    uint64_t row = 0, col = 0;
    bool any = false;
    while (i < len && buf[i] >= '0' && buf[i] <= '9') {
      uint64_t d = buf[i] - '0';
      if (row > (UINT64_MAX - d) / 10) return -1;  // overflow: loud fallback
      row = row * 10 + d;
      i++;
      any = true;
    }
    if (!any || i >= len || buf[i] != ',') return -1;
    i++;
    any = false;
    while (i < len && buf[i] >= '0' && buf[i] <= '9') {
      uint64_t d = buf[i] - '0';
      if (col > (UINT64_MAX - d) / 10) return -1;
      col = col * 10 + d;
      i++;
      any = true;
    }
    if (!any) return -1;
    if (i < len && buf[i] == ',') return -2;  // timestamp column
    while (i < len && buf[i] == '\r') i++;
    if (i < len && buf[i] != '\n') return -1;
    if (n >= cap) return -3;
    rows[n] = row;
    cols[n] = col;
    n++;
    i++;  // consume '\n' (or past EOF)
  }
  return n;
}

// ---------------------------------------------------------------------------
// CSV bit formatting (export hot path; reference: fragment.go:487-502 feeds
// ctl/export.go via buffered container iterators)
// ---------------------------------------------------------------------------

static inline int64_t fmt_u64(uint64_t v, uint8_t* out) {
  uint8_t tmp[20];
  int64_t n = 0;
  do {
    tmp[n++] = '0' + (v % 10);
    v /= 10;
  } while (v);
  for (int64_t i = 0; i < n; i++) out[i] = tmp[n - 1 - i];
  return n;
}

// Format n records as "row,col\n" into out (capacity cap bytes).
// Returns bytes written, or -3 if out ran out of space.
int64_t ptpu_format_csv(const uint64_t* rows, const uint64_t* cols, int64_t n,
                        uint8_t* out, int64_t cap) {
  int64_t w = 0;
  for (int64_t i = 0; i < n; i++) {
    if (w + 43 > cap) return -3;  // 20 + ',' + 20 + '\n' worst case
    w += fmt_u64(rows[i], out + w);
    out[w++] = ',';
    w += fmt_u64(cols[i], out + w);
    out[w++] = '\n';
  }
  return w;
}

}  // extern "C"
