"""Native runtime components — build + ctypes bindings.

Compiles ``roaring_native.cpp`` into a shared library on first use
(g++ -O3 -march=native, rebuilt when the source is newer than the binary) and exposes
ctypes wrappers.  Everything here has a pure-Python fallback in
``pilosa_tpu/ops/roaring.py``; parity tests keep the two byte-identical.

``PILOSA_TPU_DISABLE_NATIVE=1`` forces the Python paths.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "roaring_native.cpp")
_SO = os.path.join(_DIR, "libpilosa_native.so")

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_failed = False


# -mpopcnt (not -march=native): the hot loops are
# __builtin_popcountll sweeps, and POPCNT has been universal on x86-64
# since ~2008 — host-tuned codegen would SIGILL when a built .so moves
# between machines (shared checkouts, copied images).
_CFLAGS = ["-O3", "-mpopcnt", "-std=c++17", "-shared", "-fPIC"]
_FLAGS_FILE = _SO + ".flags"


def _build() -> bool:
    # Per-process temp name: concurrent builders (server + ctl import on
    # a fresh checkout) must not interleave writes before the atomic
    # rename.
    tmp = f"{_SO}.{os.getpid()}.tmp"
    cmd = ["g++", *_CFLAGS, "-o", tmp, _SRC]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, _SO)
        with open(_FLAGS_FILE, "w") as fh:
            fh.write(" ".join(_CFLAGS))
        return True
    except (subprocess.SubprocessError, OSError):
        return False
    finally:
        if os.path.exists(tmp):
            try:
                os.unlink(tmp)
            except OSError:
                pass


def _built_flags() -> str | None:
    try:
        with open(_FLAGS_FILE) as fh:
            return fh.read()
    except OSError:
        return None


def lib() -> ctypes.CDLL | None:
    """The loaded native library, building it if needed; None when
    disabled or the toolchain is unavailable."""
    global _lib, _failed
    if _lib is not None:
        return _lib
    if _failed or os.environ.get("PILOSA_TPU_DISABLE_NATIVE"):
        return None
    with _lock:
        if _lib is not None or _failed:
            return _lib
        try:
            stale = (
                not os.path.exists(_SO)
                or os.path.getmtime(_SO) < os.path.getmtime(_SRC)
                # A flags change must rebuild even when the source
                # didn't move (mtime alone would silently keep a binary
                # compiled with the old flags).
                or _built_flags() != " ".join(_CFLAGS)
            )
            if stale and not _build():
                _failed = True
                return None
            l = ctypes.CDLL(_SO)
        except OSError:
            _failed = True
            return None
        l.ptpu_decode.restype = ctypes.c_void_p
        l.ptpu_decode.argtypes = [ctypes.c_char_p, ctypes.c_int64]
        l.ptpu_error.restype = ctypes.c_char_p
        l.ptpu_error.argtypes = [ctypes.c_void_p]
        l.ptpu_nkeys.restype = ctypes.c_int64
        l.ptpu_nkeys.argtypes = [ctypes.c_void_p]
        l.ptpu_ops.restype = ctypes.c_int64
        l.ptpu_ops.argtypes = [ctypes.c_void_p]
        l.ptpu_extract.restype = None
        l.ptpu_extract.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint64),
        ]
        l.ptpu_free.restype = None
        l.ptpu_free.argtypes = [ctypes.c_void_p]
        l.ptpu_encode_size.restype = ctypes.c_int64
        l.ptpu_encode_size.argtypes = [
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.c_int64,
        ]
        l.ptpu_encode.restype = ctypes.c_int64
        l.ptpu_encode.argtypes = [
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.c_int64,
            ctypes.c_char_p,
            ctypes.c_int64,
        ]
        l.ptpu_encode_op.restype = None
        l.ptpu_encode_op.argtypes = [
            ctypes.c_uint8,
            ctypes.c_uint64,
            ctypes.c_char_p,
        ]
        l.ptpu_parse_csv.restype = ctypes.c_int64
        l.ptpu_parse_csv.argtypes = [
            ctypes.c_char_p,
            ctypes.c_int64,
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.c_int64,
        ]
        l.ptpu_format_csv.restype = ctypes.c_int64
        l.ptpu_format_csv.argtypes = [
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.c_int64,
            ctypes.POINTER(ctypes.c_uint8),
            ctypes.c_int64,
        ]
        l.ptpu_decode_tiered.restype = ctypes.c_void_p
        l.ptpu_decode_tiered.argtypes = [ctypes.c_char_p, ctypes.c_int64]
        l.ptpu_t_error.restype = ctypes.c_char_p
        l.ptpu_t_error.argtypes = [ctypes.c_void_p]
        l.ptpu_t_ops.restype = ctypes.c_int64
        l.ptpu_t_ops.argtypes = [ctypes.c_void_p]
        l.ptpu_t_counts.restype = None
        l.ptpu_t_counts.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64),
        ]
        l.ptpu_t_extract.restype = None
        l.ptpu_t_extract.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_uint32),
        ]
        l.ptpu_t_free.restype = None
        l.ptpu_t_free.argtypes = [ctypes.c_void_p]
        _lib = l
        return _lib


def available() -> bool:
    return lib() is not None


# ---------------------------------------------------------------------------
# high-level wrappers (None return = use the Python fallback)
# ---------------------------------------------------------------------------


class NativeCorruptError(ValueError):
    pass


def decode(data: bytes):
    """Roaring file -> ({key: uint64[1024]}, op_count) or None."""
    l = lib()
    if l is None:
        return None
    h = l.ptpu_decode(data, len(data))
    try:
        err = l.ptpu_error(h)
        if err is not None:
            raise NativeCorruptError(err.decode())
        nkeys = l.ptpu_nkeys(h)
        ops = l.ptpu_ops(h)
        keys = np.zeros(nkeys, dtype=np.uint64)
        words = np.zeros(nkeys * 1024, dtype=np.uint64)
        if nkeys:
            l.ptpu_extract(
                h,
                keys.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
                words.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
            )
        containers = {
            int(keys[i]): words[i * 1024 : (i + 1) * 1024] for i in range(nkeys)
        }
        return containers, int(ops)
    finally:
        l.ptpu_free(h)


def decode_tiered(data):
    """Roaring file bytes OR buffer (mmap/memoryview) ->
    ({key: uint64[1024]}, {key: sorted uint32 values}, op_count) or
    None.  Array containers never materialize to words — the
    tall-sparse loading path (see ops/roaring.decode_tiered).  Buffer
    inputs are read in place (no bytes copy): fragment open mmaps the
    file and decodes straight out of the page cache."""
    l = lib()
    if l is None:
        return None
    if isinstance(data, (bytes, bytearray)):
        buf, buf_len = bytes(data), len(data)
    else:
        # Zero-copy pointer into the buffer; `arr` pins it for the call.
        arr = np.frombuffer(data, dtype=np.uint8)
        buf, buf_len = ctypes.c_char_p(arr.ctypes.data), len(arr)
    h = l.ptpu_decode_tiered(buf, buf_len)
    try:
        err = l.ptpu_t_error(h)
        if err is not None:
            raise NativeCorruptError(err.decode())
        nw = ctypes.c_int64()
        na = ctypes.c_int64()
        tv = ctypes.c_int64()
        l.ptpu_t_counts(
            h, ctypes.byref(nw), ctypes.byref(na), ctypes.byref(tv)
        )
        nw, na, tv = nw.value, na.value, tv.value
        ops = l.ptpu_t_ops(h)
        wkeys = np.zeros(nw, dtype=np.uint64)
        wwords = np.zeros(nw * 1024, dtype=np.uint64)
        akeys = np.zeros(na, dtype=np.uint64)
        alens = np.zeros(na, dtype=np.int64)
        avals = np.zeros(tv, dtype=np.uint32)
        if nw or na:
            l.ptpu_t_extract(
                h,
                wkeys.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
                wwords.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
                akeys.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
                alens.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                avals.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
            )
        words = {
            int(wkeys[i]): wwords[i * 1024 : (i + 1) * 1024] for i in range(nw)
        }
        bounds = np.concatenate(([0], np.cumsum(alens))).astype(np.int64)
        arrays = {
            int(akeys[i]): avals[bounds[i] : bounds[i + 1]] for i in range(na)
        }
        return words, arrays, int(ops)
    finally:
        l.ptpu_t_free(h)


def encode(containers: dict[int, np.ndarray]) -> bytes | None:
    l = lib()
    if l is None:
        return None
    keys = np.array(sorted(containers), dtype=np.uint64)
    nkeys = len(keys)
    if nkeys:
        # One C-level concatenate instead of a Python slice-assign per
        # container (a dense fragment serializes tens of thousands).
        payloads = [
            np.asarray(containers[int(k)], dtype=np.uint64) for k in keys
        ]
        # Per-container length check: a total-length check alone would
        # let one short container silently shift every later payload.
        if any(p.shape != (1024,) for p in payloads):
            raise ValueError("container payloads must be 1024 words each")
        words = np.concatenate(payloads)
    else:
        words = np.zeros(0, dtype=np.uint64)
    return _encode_raw(l, keys, words)


def encode_packed(keys: np.ndarray, words2d: np.ndarray) -> bytes | None:
    """Encode a pre-packed dense tier: ``keys`` ascending uint64 and
    ``words2d[i]`` the 1024-word uint64 payload of ``keys[i]`` — zero
    per-container Python (the packed twin of :func:`encode`)."""
    l = lib()
    if l is None:
        return None
    keys = np.ascontiguousarray(keys, dtype=np.uint64)
    words2d = np.ascontiguousarray(words2d, dtype=np.uint64)
    if words2d.ndim != 2 or words2d.shape != (len(keys), 1024):
        raise ValueError("words2d must have shape (len(keys), 1024)")
    return _encode_raw(l, keys, words2d.reshape(-1))


def _encode_raw(l, keys: np.ndarray, words: np.ndarray) -> bytes | None:
    nkeys = len(keys)
    kp = keys.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64))
    wp = words.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64))
    size = l.ptpu_encode_size(kp, wp, nkeys)
    out = ctypes.create_string_buffer(max(size, 1))
    n = l.ptpu_encode(kp, wp, nkeys, out, size)
    if n < 0:
        return None
    return out.raw[:n]


def encode_op(typ: int, value: int) -> bytes | None:
    l = lib()
    if l is None:
        return None
    out = ctypes.create_string_buffer(13)
    l.ptpu_encode_op(typ, value, out)
    return out.raw


def parse_csv(data: bytes):
    """Parse 2-column \"row,col\" CSV -> (rows u64[], cols u64[]) or
    None (unavailable / has timestamps / malformed -> Python csv)."""
    l = lib()
    if l is None:
        return None
    cap = data.count(b"\n") + 2
    rows = np.zeros(cap, dtype=np.uint64)
    cols = np.zeros(cap, dtype=np.uint64)
    n = l.ptpu_parse_csv(
        data,
        len(data),
        rows.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        cols.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        cap,
    )
    if n < 0:
        return None
    return rows[:n], cols[:n]


def format_csv(rows: np.ndarray, cols: np.ndarray) -> bytes | None:
    """Format parallel row/col arrays as "row,col\\n" CSV bytes, or None
    when the native library is unavailable (caller falls back to numpy
    string formatting)."""
    l = lib()
    if l is None or len(rows) == 0:
        return b"" if l is not None else None
    rows = np.ascontiguousarray(rows, dtype=np.uint64)
    cols = np.ascontiguousarray(cols, dtype=np.uint64)
    # Exact per-record width bound from the widest values present.
    digits_r = len(str(int(rows.max())))
    digits_c = len(str(int(cols.max())))
    # +43 slack: the C side pre-checks worst-case record width, not the
    # actual one, so the buffer needs one worst-case record of headroom.
    cap = len(rows) * (digits_r + digits_c + 2) + 43
    out = np.empty(cap, dtype=np.uint8)  # no memset, unlike ctypes buffers
    n = l.ptpu_format_csv(
        rows.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        cols.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        len(rows),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        cap,
    )
    if n < 0:
        return None
    return out[:n].tobytes()
