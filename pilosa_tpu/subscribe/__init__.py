"""Standing queries: push-based PQL subscriptions (ROADMAP item 1).

Clients register ``Subscribe(Count(Intersect(...)))`` / ``Subscribe(
TopN(...))`` / ``Subscribe(Range(...))`` via ``POST /subscribe`` and
receive updates over SSE or long-poll as imports land, instead of
polling the pull path.  The registry compiles each subscription's
expression tree once (``exec.plan.decompose`` after the BSI rewrite)
and indexes it by the (index, frame, row) leaves it touches; a delta
engine fed by the fragment write listeners applies incremental updates
(a changed bit moves a single-leaf Count by exactly ±1; compound trees
re-evaluate only the touched slice against the authoritative host
planes; a full re-run happens only when a touched slice's delta budget
overflows or a TopN ranking may have shifted).  Notification batches
ride a dedicated bounded admission lane so subscribers can never
starve queries, and subscriptions follow their slices across rebalance
via the topology routing version (snapshot re-evaluation on every
flip, so no update is lost across the cutover).
"""

from pilosa_tpu.subscribe.registry import (  # noqa: F401
    KIND_COUNT,
    KIND_TOPN,
    SubscribeError,
    compile_subscription,
)
from pilosa_tpu.subscribe.engine import (  # noqa: F401,E402
    Subscription,
    SubscriptionManager,
)
