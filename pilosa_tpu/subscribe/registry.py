"""Standing-query compilation: validate ``Subscribe(...)``, pick the
incremental strategy, and index the tree by the leaves it touches.

A subscription compiles ONCE at registration:

* ``Subscribe(Count(<tree>))`` / ``Subscribe(<tree>)`` — a standing
  count.  The tree is BSI-rewritten and decomposed into the same
  ``(expr, leaves)`` program the fused interpreter and ``hosteval``
  share, so incremental re-evaluation is byte-identical to a pull by
  construction.
* ``Subscribe(TopN(...))`` — a standing ranking.  Any write to the
  frame may reshuffle it, so TopN subscriptions always re-run the full
  query on notification (the "ranking may have shifted" path).

``leaf_keys`` drive the write-side index: ``(frame, row)`` for plain
``Bitmap`` leaves (a write to another row cannot change the result),
``(frame, None)`` for everything whose touched rows aren't statically
known (Range time views, BSI predicate planes, inverse bitmaps, TopN).
"""

from __future__ import annotations

from pilosa_tpu.exec import plan
from pilosa_tpu.exec.executor import DEFAULT_FRAME
from pilosa_tpu.pql.parser import Call

KIND_COUNT = "count"
KIND_TOPN = "topn"


class SubscribeError(ValueError):
    """Invalid standing-query registration (HTTP 400)."""


def _leaf_keys_for_tree(tree: Call) -> tuple[set, bool]:
    """``({(frame, row|None)}, force_pull)`` for a bitmap tree.

    ``force_pull`` is True when incremental slice evaluation over the
    standard orientation would be wrong (inverse-oriented leaves) —
    those subscriptions re-run through the executor, which resolves
    orientation exactly like the pull path.
    """
    keys: set = set()
    force_pull = False
    for leaf in plan.collect_leaf_calls(tree):
        frame = leaf.args.get("frame") or DEFAULT_FRAME
        if leaf.name == "Bitmap":
            row = leaf.args.get("rowID")
            if isinstance(row, bool) or not isinstance(row, int):
                # Inverse orientation (columnID=) or malformed: watch
                # the whole frame and evaluate via the pull path.
                keys.add((frame, None))
                force_pull = True
            else:
                keys.add((frame, row))
        else:
            # Range: time views or BSI comparisons — the set of rows a
            # write can touch isn't statically known.
            keys.add((frame, None))
    return keys, force_pull


def compile_subscription(call: Call):
    """Validate a parsed ``Subscribe(...)`` call.

    Returns ``(kind, inner, tree, leaf_keys, force_pull)``:

    * ``kind`` — :data:`KIND_COUNT` or :data:`KIND_TOPN`;
    * ``inner`` — the call the pull path executes (``Count(...)`` or
      ``TopN(...)``);
    * ``tree`` — the bitmap tree for incremental host evaluation
      (None for TopN);
    * ``leaf_keys`` — ``{(frame, row|None)}`` the write index watches;
    * ``force_pull`` — never evaluate incrementally (inverse leaves).
    """
    if call.name != "Subscribe":
        raise SubscribeError("expected Subscribe(...)")
    if call.args:
        raise SubscribeError("Subscribe takes no arguments")
    if len(call.children) != 1:
        raise SubscribeError("Subscribe takes exactly one query call")
    inner = call.children[0]

    if inner.name == "TopN":
        frame = inner.args.get("frame") or DEFAULT_FRAME
        return KIND_TOPN, inner, None, {(frame, None)}, True

    if inner.name == "Count":
        if len(inner.children) != 1:
            raise SubscribeError("Count takes exactly one child call")
        tree = inner.children[0]
    elif inner.name in plan.FOLD_CALLS or inner.name in ("Bitmap", "Range"):
        # A bare bitmap tree subscribes to its Count: push updates
        # carry counts (row payloads stay on the pull path).
        tree = inner
        inner = Call(name="Count", children=[tree])
    else:
        raise SubscribeError(
            f"unsupported standing query: {inner.name}() "
            "(expected Count, TopN, or a bitmap tree)"
        )
    if tree.name not in plan.FOLD_CALLS and tree.name not in ("Bitmap", "Range"):
        raise SubscribeError(f"unsupported count subject: {tree.name}()")
    leaf_keys, force_pull = _leaf_keys_for_tree(tree)
    if not leaf_keys:
        raise SubscribeError("standing query touches no frames")
    return KIND_COUNT, inner, tree, leaf_keys, force_pull


def has_bsi_leaves(leaves) -> bool:
    """True when a decomposed program references BSI planes — its
    compiled form must be refreshed per evaluation because BSI depth
    grows with the values written (a new high limb adds leaves)."""
    return any(leaf.name in ("BsiPlane", "BsiPred", "BsiZero") for leaf in leaves)
