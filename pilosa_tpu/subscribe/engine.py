"""The standing-query delta engine: write listeners in, updates out.

Data flow::

    fragment._notify_write            (under the fragment lock)
        └─ SubscriptionManager.on_write   — match the (index, frame,
           row) write index, fold the delta into the pending map
           (exact single-leaf counts adjust by ±n; everything else
           marks the touched slice dirty), and wake the notifier.
           Leaf locks only, like DeltaLog.record.

    notifier thread (one per manager)
        └─ coalesce a batch → acquire the dedicated "subscribe"
           admission lane → re-evaluate each touched subscription
           (±adjust / dirty-slice hosteval / full re-run) → publish
           versioned updates to per-subscription queues → wake SSE
           and long-poll waiters.

Incremental strategy per (subscription, batch):

* ``adjust`` — the tree is a single standard ``Bitmap`` leaf and every
  contributing write was exact (point writes report only bits that
  actually changed): the count moves by exactly ±n, no evaluation.
* ``slice`` — compound tree, bounded dirt: re-evaluate only the dirty
  slices' compiled program over the authoritative host planes (the
  ``hosteval`` path — word-local numpy, byte-identical to a pull).
* ``full`` — the slice's delta budget overflowed, a TopN ranking may
  have shifted, the cluster is multi-node (remote slices feed no local
  listener), or the topology moved: re-run the whole query through the
  executor — the same fused-interpreter pull path clients use.

Incremental bases are race-free against in-flight writes: a write
commits to the plane, bumps the fragment's ``_version``, and notifies
listeners inside ONE fragment-lock critical section, and the plane
read that (re)bases a slice captures ``(_serial, _version)`` under the
same lock.  So an adj delta stamped at or below the base version is
provably already inside the base (dropped, never double-applied), one
stamped above it is provably not (applied), and a range straddling the
base — or a recreated fragment's incomparable serial — degrades to a
dirty re-evaluation instead of arithmetic on a guess.

Epoch-following: every batch compares ``cluster.routing_version``
(bumped on ring changes AND per-slice flips) against the last value it
saw; a change forces a full snapshot re-evaluation of every
subscription — snapshot-then-stream, so no update is lost across a
rebalance cutover.  Delivery is at-least-once with monotonically
increasing per-subscription versions; updates carry absolute values,
so a coalesced-away intermediate version loses no information.
"""

from __future__ import annotations

import os
import threading
import time
import uuid
from collections import deque

from pilosa_tpu.exec import plan
from pilosa_tpu.exec.executor import DEFAULT_FRAME
from pilosa_tpu.exec.hosteval import popcount_words
from pilosa_tpu.net import codec
from pilosa_tpu.obs import trace
from pilosa_tpu.obs.stats import NopStatsClient
from pilosa_tpu.ops import bitplane as bp
from pilosa_tpu.pql.parser import Query, parse_string
from pilosa_tpu.subscribe import registry as reg

# Snapshot caps: /debug/subscriptions lists at most this many entries.
_SNAPSHOT_SUBS = 100
# Ring of recent batch lags backing the /debug lag percentiles.
_LAG_RING = 512


class Subscription:
    """One registered standing query and its delivery state."""

    def __init__(
        self,
        sid: str,
        index: str,
        pql: str,
        kind: str,
        inner,
        tree,
        leaf_keys,
        force_pull: bool,
        queue_cap: int,
    ):
        self.id = sid
        self.index = index
        self.pql = pql
        self.kind = kind
        self.inner = inner          # Count(...) / TopN(...) — the pull call
        self.tree = tree            # bitmap tree (count kind) or None
        self.leaf_keys = leaf_keys  # {(frame, row|None)}
        self.force_pull = force_pull
        # Compiled program (count kind): filled by the manager at
        # registration; refreshed per-eval when it has BSI leaves.
        self.expr = None
        self.leaves: list = []
        self.has_bsi = False
        # Exact ±n fast path: single standard Bitmap leaf.
        self.fast_frame: str | None = None
        self.fast_row: int | None = None
        # Delivery state — guarded by ``cv``'s lock.
        self.cv = threading.Condition()
        self.version = 0
        self.value = None           # raw result (int | [Pair])
        self.value_json = None
        self.epoch = 0              # routing_version at last evaluation
        self.updates: deque = deque(maxlen=max(1, queue_cap))
        self.closed = False
        self.streams = 0            # live SSE connections
        self.delivered = 0          # updates handed to any waiter
        self.created = time.time()
        # False until the registration snapshot (version 1) is
        # published: the sub is in the watch index — so no write is
        # missed — but the notifier requeues its pending deltas
        # instead of racing the registering thread's evaluation.
        self.ready = False
        # Consecutive notifier-eval failures; drained deltas are
        # requeued (as full) until a small strike cap gives up.
        self.eval_failures = 0
        # Incremental per-slice counts — owned by the notifier thread.
        self.slice_counts: dict[int, int] = {}
        # Per-slice (fragment serial, write version) captured with the
        # plane read that produced slice_counts — the double-apply
        # fence for adj deltas (see module docstring).
        self.slice_vers: dict[int, tuple] = {}

    def watches(self, frame: str, rows) -> bool:
        """Does a write to ``frame`` touching ``rows`` intersect this
        subscription's leaves?  (Row-filtered only when every leaf in
        the frame names a concrete row.)"""
        wildcard = (frame, None) in self.leaf_keys
        if wildcard:
            return True
        return any((frame, int(r)) in self.leaf_keys for r in rows)


class SubscriptionManager:
    """Registry + delta engine + delivery for one node's standing
    queries.  Wired by the Server after the executor exists; the
    handler serves ``POST /subscribe`` and friends through it."""

    def __init__(
        self,
        executor,
        cluster=None,
        stats=None,
        tracer=None,
        admission=None,
        data_dir: str = "",
        logger=None,
        max_subscriptions: int = 10_000,
        queue_cap: int = 256,
        delta_cap: int = 50_000,
        coalesce_ms: float = 5.0,
        refresh_interval_ms: float = 500.0,
    ):
        self.ex = executor
        self.cluster = cluster
        self.stats = stats or NopStatsClient()
        self.tracer = tracer or trace.NOP_TRACER
        self.admission = admission
        self.data_dir = str(data_dir or "")
        # Node filter: normalized prefix WITH trailing separator so a
        # sibling data dir can never cross-match (/data/n1 vs /data/n10).
        self._data_dir_prefix = (
            os.path.normpath(self.data_dir) + os.sep if self.data_dir else ""
        )
        self.logger = logger or (lambda msg: None)
        self.max_subscriptions = int(max_subscriptions)
        self.queue_cap = int(queue_cap)
        self.delta_cap = int(delta_cap)
        self.coalesce_s = max(0.0, float(coalesce_ms)) / 1000.0
        self.refresh_s = max(0.05, float(refresh_interval_ms) / 1000.0)

        # Registry — mutations under _mu; readers use the published
        # immutable snapshots (_subs / _watch are REPLACED, never
        # mutated in place), so the write-side hot path is lock-free.
        self._mu = threading.Lock()
        self._subs: dict[str, Subscription] = {}
        # (index, frame) -> tuple[Subscription, ...]
        self._watch: dict[tuple[str, str], tuple] = {}

        # Pending deltas — the bounded "subscription delta log".
        # Guarded by _pending_mu, a LEAF lock: on_write runs under the
        # fragment lock and takes only this.
        self._pending_mu = threading.Lock()
        self._pending_cv = threading.Condition(self._pending_mu)
        # sid -> {"adj": {slice: [±n, frag_serial, ver_min, ver_max]},
        #         "dirty": {slice}, "full": bool,
        #         "t0": monotonic-first-touch}
        self._pending: dict[str, dict] = {}
        # (index, slice) -> bits accumulated since the last drain.
        self._pending_bits: dict[tuple[str, int], int] = {}
        self._busy = False

        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._last_routing = cluster.routing_version if cluster else 0
        self._last_refresh = time.monotonic()
        self._lag_ring: deque = deque(maxlen=_LAG_RING)

        # Lifetime counters (mirrored to stats with exec.subscribe.*).
        self.registered = 0
        self.unregistered = 0
        self.updates_emitted = 0
        self.batches = 0
        self.overflows = 0
        self.epoch_flips = 0
        self.evals = {"adjust": 0, "slice": 0, "full": 0}

    # -- lifecycle -----------------------------------------------------

    def open(self) -> None:
        from pilosa_tpu.core import fragment as fragment_mod

        self._last_routing = (
            self.cluster.routing_version if self.cluster else 0
        )
        fragment_mod.register_write_listener(self.on_write)
        fragment_mod.register_close_listener(self.on_fragment_close)
        self._thread = threading.Thread(
            target=self._notify_loop, name="subscribe-notify", daemon=True
        )
        self._thread.start()

    def close(self) -> None:
        from pilosa_tpu.core import fragment as fragment_mod

        fragment_mod.unregister_write_listener(self.on_write)
        fragment_mod.unregister_close_listener(self.on_fragment_close)
        self._stop.set()
        with self._pending_cv:
            self._pending_cv.notify_all()
        t = self._thread
        if t is not None:
            t.join(timeout=5)
        for sub in list(self._subs.values()):
            self._close_sub(sub)

    # -- registration --------------------------------------------------

    def register(self, index: str, pql: str) -> Subscription:
        """Parse, compile, index, THEN snapshot-evaluate one standing
        query; returns the live subscription with version 1 == the
        registration snapshot.  Publishing into the watch index before
        the snapshot is taken closes the registration window: a write
        landing during the evaluation is captured in the pending map
        and re-applied by the notifier (stale deltas are version-
        filtered on apply), so snapshot-then-stream holds from birth
        even on a single node with no refresh tick."""
        q = parse_string(pql)
        if len(q.calls) != 1:
            raise reg.SubscribeError("exactly one Subscribe(...) call required")
        kind, inner, tree, leaf_keys, force_pull = reg.compile_subscription(
            q.calls[0]
        )
        if self.ex.holder.index(index) is None:
            raise reg.SubscribeError(f"index {index!r} does not exist")
        sub = Subscription(
            sid=uuid.uuid4().hex[:16],
            index=index,
            pql=pql,
            kind=kind,
            inner=inner,
            tree=tree,
            leaf_keys=leaf_keys,
            force_pull=force_pull,
            queue_cap=self.queue_cap,
        )
        if kind == reg.KIND_COUNT:
            self._compile(sub)
        with self._mu:
            if len(self._subs) >= self.max_subscriptions:
                raise reg.SubscribeError(
                    f"subscription limit reached ({self.max_subscriptions})"
                )
            subs = dict(self._subs)
            subs[sub.id] = sub
            self._subs = subs
            self._rebuild_watch_locked()
        try:
            # Snapshot evaluation OUTSIDE any engine lock (takes
            # fragment locks via the host planes / executor).  The
            # notifier sees the sub but defers its deltas until ready.
            value = self._evaluate_full(sub)
            routing = self.cluster.routing_version if self.cluster else 0
            self._emit(sub, value, routing, force=True)
        except BaseException:
            with self._mu:
                if sub.id in self._subs:
                    subs = dict(self._subs)
                    del subs[sub.id]
                    self._subs = subs
                    self._rebuild_watch_locked()
            with self._pending_cv:
                self._pending.pop(sub.id, None)
            raise
        with self._pending_cv:
            sub.ready = True
            if sub.id in self._pending:
                # Writes landed mid-snapshot: have the notifier fold
                # them in now rather than on the next matching write.
                self._pending_cv.notify()
        self.registered += 1
        self.stats.count("exec.subscribe.registered")
        return sub

    def unregister(self, sid: str) -> bool:
        with self._mu:
            sub = self._subs.get(sid)
            if sub is None:
                return False
            subs = dict(self._subs)
            del subs[sid]
            self._subs = subs
            self._rebuild_watch_locked()
        with self._pending_mu:
            self._pending.pop(sid, None)
        self._close_sub(sub)
        self.unregistered += 1
        self.stats.count("exec.subscribe.unregistered")
        return True

    def get(self, sid: str) -> Subscription | None:
        return self._subs.get(sid)

    def _close_sub(self, sub: Subscription) -> None:
        with sub.cv:
            sub.closed = True
            sub.cv.notify_all()

    def _compile(self, sub: Subscription) -> None:
        """Compile the tree once: BSI rewrite + decompose — the same
        program the interpreter and hosteval evaluate."""
        rewritten = self.ex._rewrite_bsi(sub.index, sub.tree)
        sub.expr, sub.leaves = plan.decompose(rewritten)
        sub.has_bsi = reg.has_bsi_leaves(sub.leaves)
        if (
            not sub.force_pull
            and not sub.has_bsi
            and sub.expr == ("leaf", 0)
            and sub.leaves[0].name == "Bitmap"
        ):
            row = sub.leaves[0].args.get("rowID")
            if isinstance(row, int) and not isinstance(row, bool):
                sub.fast_frame = (
                    sub.leaves[0].args.get("frame") or DEFAULT_FRAME
                )
                sub.fast_row = int(row)

    def _rebuild_watch_locked(self) -> None:
        watch: dict[tuple[str, str], dict] = {}
        for sub in self._subs.values():
            for frame, _row in sub.leaf_keys:
                watch.setdefault((sub.index, frame), {})[sub.id] = sub
        self._watch = {k: tuple(v.values()) for k, v in watch.items()}

    # -- the fragment write listener (under the fragment lock) ---------

    def on_write(
        self, frag, set_rows, set_cols, clear_rows, clear_cols, exact=False
    ) -> None:
        """Fold one write into the pending delta map.  Called under the
        fragment lock — takes only the pending lock (a leaf in the
        lock hierarchy, like DeltaLog.record).  ``exact`` gates the ±n
        fast path: only bits that provably changed may adjust a count
        without re-evaluation."""
        if self._foreign(frag):
            return  # another in-process node's fragment
        watch = self._watch
        if not watch:
            return
        entries = watch.get((frag.index, frag.frame))
        if not entries:
            return
        n = len(set_rows) + len(clear_rows)
        if n == 0:
            return
        now = time.monotonic()
        overflow_slices: list[int] = []
        with self._pending_cv:
            key = (frag.index, frag.slice)
            before = self._pending_bits.get(key, 0)
            self._pending_bits[key] = before + n
            overflowed = before + n > self.delta_cap
            if overflowed and before <= self.delta_cap:
                overflow_slices.append(frag.slice)
            touched = False
            for sub in entries:
                if not sub.watches(
                    frag.frame, list(set_rows) + list(clear_rows)
                ):
                    continue
                p = self._pending.get(sub.id)
                if p is None:
                    p = self._pending[sub.id] = {
                        "adj": {},
                        "dirty": set(),
                        "full": False,
                        "t0": now,
                    }
                touched = True
                if overflowed:
                    p["full"] = True
                    continue
                if (
                    exact
                    and sub.fast_row is not None
                    and frag.view == "standard"
                    and frag.frame == sub.fast_frame
                ):
                    d = sum(1 for r in set_rows if int(r) == sub.fast_row)
                    d -= sum(1 for r in clear_rows if int(r) == sub.fast_row)
                    if d:
                        # Stamp with the fragment's write version
                        # (already bumped for this write, same lock
                        # hold) — the apply side drops deltas the
                        # slice base provably includes.
                        adj = p["adj"]
                        ver = frag._version
                        cur = adj.get(frag.slice)
                        if cur is None:
                            adj[frag.slice] = [d, frag._serial, ver, ver]
                        elif cur[1] == frag._serial:
                            cur[0] += d
                            cur[3] = ver  # monotonic per fragment
                        else:
                            # Recreated fragment under this slice:
                            # stamps incomparable — degrade to dirty.
                            adj.pop(frag.slice, None)
                            p["dirty"].add(frag.slice)
                else:
                    p["dirty"].add(frag.slice)
            if touched:
                self._pending_cv.notify()
        for s in overflow_slices:
            self.overflows += 1
            self.stats.count_with_custom_tags(
                "exec.subscribe.overflows", 1, [f"slice:{frag.index}/{s}"]
            )

    def _foreign(self, frag) -> bool:
        """True when the fragment belongs to another in-process node
        (multi-server tests/benches share the module-wide listener)."""
        if not self._data_dir_prefix:
            return False
        path = os.path.normpath(str(getattr(frag, "path", "")))
        return not path.startswith(self._data_dir_prefix)

    def on_fragment_close(self, frag) -> None:
        """Fragment left service (close/retire/demotion, including a
        rebalanced-away slice): drop its pending budget and force the
        affected subscriptions to re-base that slice — incremental
        state must never survive the plane it was computed from."""
        if self._foreign(frag):
            return
        watch = self._watch
        entries = watch.get((frag.index, frag.frame)) if watch else None
        with self._pending_cv:
            self._pending_bits.pop((frag.index, frag.slice), None)
            if not entries:
                return
            for sub in entries:
                p = self._pending.get(sub.id)
                if p is None:
                    p = self._pending[sub.id] = {
                        "adj": {},
                        "dirty": set(),
                        "full": False,
                        "t0": time.monotonic(),
                    }
                p["full"] = True
            self._pending_cv.notify()

    # -- the notifier thread -------------------------------------------

    def _notify_loop(self) -> None:
        while not self._stop.is_set():
            try:
                self._drain_once()
            except Exception as e:  # noqa: BLE001 — the loop must survive
                self.logger(f"subscribe: notify loop error: {e}")
                self._stop.wait(0.2)

    def _actionable_locked(self) -> bool:
        """Any pending entry whose subscription is ready (or gone)?
        Entries for subs whose registration snapshot is still in
        flight are deferred — they must not wake or spin the loop."""
        for sid in self._pending:
            sub = self._subs.get(sid)
            if sub is None or sub.ready:
                return True
        return False

    def _drain_once(self) -> None:
        with self._pending_cv:
            if not self._actionable_locked():
                self._pending_cv.wait(self.refresh_s)
        if self._stop.is_set():
            return
        if self.coalesce_s > 0:
            # Coalescing window: let a write burst accumulate into one
            # batch instead of one notification per bit.
            self._stop.wait(self.coalesce_s)
        with self._pending_cv:
            batch = {}
            for sid in list(self._pending):
                sub = self._subs.get(sid)
                if sub is not None and not sub.ready:
                    continue  # deferred until the snapshot publishes
                batch[sid] = self._pending.pop(sid)
            self._pending_bits = {}
            self._busy = bool(batch)

        routing = self.cluster.routing_version if self.cluster else 0
        epoch_flip = routing != self._last_routing
        now = time.monotonic()
        refresh_due = (
            self._multi_node()
            and now - self._last_refresh >= self.refresh_s
            and bool(self._subs)
        )
        if epoch_flip or refresh_due:
            # Snapshot-then-stream: full re-evaluation of every
            # subscription.  On a topology move this is what carries a
            # subscription across the cutover; on a quiet multi-node
            # tick it feeds subscriptions whose slices live remotely
            # (their writes fire no local listener).
            for sub in list(self._subs.values()):
                p = batch.setdefault(
                    sub.id,
                    {"adj": {}, "dirty": set(), "full": False, "t0": now},
                )
                p["full"] = True
            self._last_refresh = now
            if epoch_flip and self._subs:
                self.epoch_flips += 1
                self.stats.count("exec.subscribe.epochFlips")
        self._last_routing = routing
        if not batch:
            with self._pending_cv:
                self._busy = False
            return
        with self._pending_cv:
            self._busy = True
        try:
            self._process_batch(batch, routing, force=epoch_flip)
        finally:
            with self._pending_cv:
                self._busy = False

    def _process_batch(self, batch: dict, routing: int, force: bool) -> None:
        """Evaluate one drained batch.  The deltas are already out of
        the pending map, so every failure path — the admission lane
        shedding (shared with POST /subscribe), a per-subscription
        eval error, anything unexpected — must push its unprocessed
        entries BACK via _requeue: a silently dropped adj delta is
        permanent drift, a dropped dirty mark permanent staleness."""
        t0 = min(p["t0"] for p in batch.values())
        root = self.tracer.start_trace("subscribe", subscriptions=len(batch))
        remaining = dict(batch)
        requeue: dict[str, dict] = {}
        inflight: str | None = None
        ticket = None
        try:
            if self.admission is not None:
                from pilosa_tpu.net import admission as adm

                with self.tracer.span("admission", parent=root):
                    ticket = self.admission.acquire(adm.CLASS_SUBSCRIBE)
            with self.tracer.span("subscribe.eval", parent=root) as sp:
                n_updates = 0
                for sid, p in batch.items():
                    sub = self._subs.get(sid)
                    if sub is None or sub.closed:
                        del remaining[sid]
                        continue
                    if not sub.ready:
                        # Registration snapshot still in flight —
                        # defer, don't race the registering thread.
                        requeue[sid] = p
                        del remaining[sid]
                        continue
                    try:
                        inflight = sid
                        changed = self._reevaluate(sub, p, routing, force)
                        inflight = None
                        sub.eval_failures = 0
                    except Exception as e:  # noqa: BLE001
                        inflight = None
                        sub.eval_failures += 1
                        if sub.eval_failures <= 3:
                            p["full"] = True
                            requeue[sid] = p
                        else:
                            # Give up on the entry, but invalidate the
                            # (possibly half-adjusted) bases so the
                            # next delta re-evaluates from planes.
                            sub.slice_counts = {}
                            sub.slice_vers = {}
                        self.logger(
                            f"subscribe: eval failed for {sid}: {e}"
                        )
                        del remaining[sid]
                        continue
                    del remaining[sid]
                    if changed:
                        n_updates += 1
                sp.annotate(updates=n_updates)
        except BaseException:
            # Batch-level failure (admission shed, ...): everything
            # not yet individually settled goes back on the map; the
            # notify loop logs and retries after a short backoff.  An
            # eval interrupted mid-flight may have half-applied its
            # adj deltas — force that one to re-base in full.
            if inflight is not None and inflight in remaining:
                remaining[inflight]["full"] = True
            requeue.update(remaining)
            raise
        finally:
            self._requeue(requeue)
            if ticket is not None:
                ticket.release()
            self.tracer.finish_root(root)
        lag_ms = (time.monotonic() - t0) * 1000.0
        self._lag_ring.append(lag_ms)
        self.batches += 1
        self.stats.count("exec.subscribe.notifyBatches")
        self.stats.histogram("exec.subscribe.lagMs", lag_ms)

    def _requeue(self, entries: dict) -> None:
        """Merge drained-but-unprocessed entries back into the live
        pending map (see _process_batch)."""
        if not entries:
            return
        with self._pending_cv:
            for sid, src in entries.items():
                p = self._pending.get(sid)
                if p is None:
                    self._pending[sid] = src
                else:
                    self._merge_entry(p, src)
            self._pending_cv.notify()

    @staticmethod
    def _merge_entry(p: dict, src: dict) -> None:
        """Fold ``src`` (an older drained entry) into live entry ``p``."""
        p["full"] = p["full"] or src["full"]
        p["t0"] = min(p["t0"], src["t0"])
        p["dirty"] |= src["dirty"]
        adj = p["adj"]
        for s, (d, serial, vmin, vmax) in src["adj"].items():
            if s in p["dirty"]:
                continue  # the dirty re-evaluation subsumes the delta
            cur = adj.get(s)
            if cur is None:
                adj[s] = [d, serial, vmin, vmax]
            elif cur[1] == serial:
                adj[s] = [
                    cur[0] + d, serial, min(cur[2], vmin), max(cur[3], vmax)
                ]
            else:
                adj.pop(s, None)
                p["dirty"].add(s)

    def _multi_node(self) -> bool:
        return self.cluster is not None and len(self.cluster.nodes) > 1

    def _reevaluate(self, sub, p: dict, routing: int, force: bool) -> bool:
        """Bring one subscription current; returns True when an update
        was emitted."""
        full = (
            p["full"]
            or sub.kind == reg.KIND_TOPN
            or sub.force_pull
            or self._multi_node()
            or (self.cluster is not None and self.cluster.transition is not None)
        )
        if full:
            value = self._evaluate_full(sub)
            self.evals["full"] += 1
            self.stats.count_with_custom_tags(
                "exec.subscribe.evals", 1, ["mode:full"]
            )
        else:
            value = self._evaluate_incremental(sub, p)
        return self._emit(sub, value, routing, force=force)

    def _evaluate_full(self, sub):
        """Snapshot evaluation — the pull path itself, so the value is
        correct regardless of slice placement; resets the incremental
        per-slice base for the count kind on a single node."""
        if sub.kind == reg.KIND_COUNT and not sub.force_pull and not self._multi_node():
            idx = self.ex.holder.index(sub.index)
            if idx is None:
                sub.slice_counts = {}
                sub.slice_vers = {}
                return 0
            slices = list(range(idx.max_slice() + 1))
            sub.slice_counts, sub.slice_vers = self._slice_count(sub, slices)
            return sum(sub.slice_counts.values())
        sub.slice_counts = {}
        sub.slice_vers = {}
        res = self.ex.execute(sub.index, Query(calls=[sub.inner]))
        return res[0]

    def _evaluate_incremental(self, sub, p: dict):
        """Single-node count kind: ±adjust exact deltas, re-evaluate
        only the dirty slices' compiled program over the host planes.

        An adj delta is applied ONLY when its whole write-version
        range lies above the slice base's stamp; at or below the stamp
        it was already counted by the plane read that produced the
        base (the double-apply fence — see the module docstring), and
        a straddling range or recreated-fragment serial degrades to a
        dirty re-evaluation."""
        dirty = set(p["dirty"])
        counts = sub.slice_counts
        vers = sub.slice_vers
        for s, (d, serial, vmin, vmax) in p["adj"].items():
            if s in dirty:
                continue  # the re-evaluation below subsumes the delta
            base = vers.get(s)
            if s not in counts or base is None:
                dirty.add(s)  # no stamped base yet — evaluate, don't guess
            elif serial != base[0]:
                dirty.add(s)  # fragment recreated: stamps incomparable
            elif vmax <= base[1]:
                continue      # fully inside the base plane read already
            elif vmin > base[1]:
                counts[s] += d
            else:
                dirty.add(s)  # straddles the base read — re-evaluate
        if dirty:
            new_counts, new_vers = self._slice_count(sub, sorted(dirty))
            counts.update(new_counts)
            vers.update(new_vers)
            self.evals["slice"] += 1
            self.stats.count_with_custom_tags(
                "exec.subscribe.evals", 1, ["mode:slice"]
            )
        elif p["adj"]:
            self.evals["adjust"] += 1
            self.stats.count_with_custom_tags(
                "exec.subscribe.evals", 1, ["mode:adjust"]
            )
        return sum(counts.values())

    def _slice_count(self, sub, slices) -> tuple[dict, dict]:
        """Per-slice counts of the compiled program over the
        authoritative host planes (word-local numpy — the hosteval
        evaluation, reusing the registration-time compile); returns
        ``(counts, version stamps)``.

        For the single-leaf fast path the plane read captures the
        fragment's ``(_serial, _version)`` under the SAME fragment-lock
        hold — anchoring exactly which adj deltas the base includes.
        Compound trees take no stamp: they only ever receive dirty
        marks, which are idempotent."""
        out: dict[int, int] = {}
        vers: dict[int, tuple] = {}
        if sub.fast_row is not None:
            for s in slices:
                frag = self.ex.holder.fragment(
                    sub.index, sub.fast_frame, "standard", s
                )
                if frag is None:
                    # No fragment yet: serial -1 never matches a real
                    # write's stamp, so the first delta re-evaluates.
                    out[s] = 0
                    vers[s] = (-1, -1)
                    continue
                with frag._mu:
                    stamp = (frag._serial, frag._version)
                    row = frag._row_words_host(sub.fast_row)
                out[s] = 0 if row is None else popcount_words(row)
                vers[s] = stamp
            return out, vers
        expr, leaves = sub.expr, sub.leaves
        if sub.has_bsi:
            # BSI depth grows with written values (new high limbs add
            # leaves) — refresh the compile so incremental results stay
            # byte-identical to a pull.
            rewritten = self.ex._rewrite_bsi(sub.index, sub.tree)
            expr, leaves = plan.decompose(rewritten)
        for s in slices:
            rows = [
                self.ex._leaf_row_host(sub.index, leaf, s) for leaf in leaves
            ]
            r = plan.eval_expr_np(expr, rows, bp.WORDS_PER_SLICE)
            out[s] = 0 if r is None else popcount_words(r)
        return out, vers

    # -- delivery ------------------------------------------------------

    def _emit(self, sub, value, routing: int, force: bool = False) -> bool:
        changed = value != sub.value
        if not changed and not force and routing == sub.epoch:
            return False
        value_json = codec.result_to_json(value)
        with sub.cv:
            sub.value = value
            sub.value_json = value_json
            sub.version += 1
            sub.epoch = routing
            sub.updates.append(
                {
                    "id": sub.id,
                    "version": sub.version,
                    "epoch": routing,
                    "value": value_json,
                }
            )
            sub.cv.notify_all()
        self.updates_emitted += 1
        self.stats.count("exec.subscribe.updates")
        return True

    def wait_update(self, sub, after: int, timeout: float):
        """Block until the subscription moves past ``after`` (long-poll
        / SSE wait).  Returns the oldest retained update newer than
        ``after`` — or the current snapshot when the queue already
        rotated past it (at-least-once: the absolute value subsumes the
        missed versions) — or None on timeout / closed."""
        deadline = time.monotonic() + max(0.0, timeout)
        with sub.cv:
            while not sub.closed and sub.version <= after:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return None
                sub.cv.wait(remaining)
            if sub.version <= after:
                return None  # closed without news
            for u in sub.updates:
                if u["version"] > after:
                    sub.delivered += 1
                    return u
            sub.delivered += 1
            return {
                "id": sub.id,
                "version": sub.version,
                "epoch": sub.epoch,
                "value": sub.value_json,
            }

    # -- test / smoke support ------------------------------------------

    def flush(self, timeout: float = 10.0) -> bool:
        """Wait until every pending delta has been evaluated and
        published — the quiescence point tests compare against the
        oracle at."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._pending_mu:
                idle = not self._pending and not self._busy
            if idle:
                return True
            time.sleep(0.002)
        return False

    # -- observability -------------------------------------------------

    def _lag_percentiles(self) -> dict:
        lags = sorted(self._lag_ring)
        if not lags:
            return {"p50": None, "p99": None, "samples": 0}
        def pct(p):
            return round(lags[min(len(lags) - 1, int(p * (len(lags) - 1)))], 3)
        return {"p50": pct(0.50), "p99": pct(0.99), "samples": len(lags)}

    def snapshot(self) -> dict:
        """The ``GET /debug/subscriptions`` document."""
        subs = list(self._subs.values())
        with self._pending_mu:
            pending = len(self._pending)
            pending_bits = sum(self._pending_bits.values())
        return {
            "count": len(subs),
            "maxSubscriptions": self.max_subscriptions,
            "deltaCap": self.delta_cap,
            "routingVersion": self._last_routing,
            "pending": {"subscriptions": pending, "bits": pending_bits},
            "lagMs": self._lag_percentiles(),
            "counters": {
                "registered": self.registered,
                "unregistered": self.unregistered,
                "updates": self.updates_emitted,
                "batches": self.batches,
                "overflows": self.overflows,
                "epochFlips": self.epoch_flips,
                "evals": dict(self.evals),
            },
            "subscriptions": [
                {
                    "id": s.id,
                    "index": s.index,
                    "query": s.pql,
                    "kind": s.kind,
                    "version": s.version,
                    "epoch": s.epoch,
                    "streams": s.streams,
                    "delivered": s.delivered,
                    "value": s.value_json,
                }
                for s in subs[:_SNAPSHOT_SUBS]
            ],
        }

    def gauges(self) -> dict:
        return {
            "exec.subscribe.active": float(len(self._subs)),
            "exec.subscribe.pendingBits": float(
                sum(self._pending_bits.values())
            ),
        }
