"""SSE framing for standing-query delivery, on the stream plane.

Server-Sent Events over the existing chunked-transfer machinery
(net/handler.py ``Response.stream`` → ``stream.body.IterBody``), with
one deliberate difference: IterBody re-slices producer chunks into
fixed-size output chunks, buffering until one fills — correct for bulk
export, fatal for push delivery (an update would sit in the buffer
until enough LATER updates arrive to flush it).  :class:`EventBody`
therefore passes producer chunks through verbatim: every yielded SSE
event is written (and flushed) as its own chunk the moment it exists.

Wire format (one event per notification)::

    event: update
    id: <version>
    data: {"id": "...", "version": N, "epoch": E, "value": ...}

plus ``: keepalive`` comment lines while idle, so intermediaries don't
reap the connection and clients can distinguish "quiet" from "dead".
"""

from __future__ import annotations

import json
from collections.abc import Iterable

from pilosa_tpu.stream.body import IterBody

CONTENT_TYPE = "text/event-stream"
KEEPALIVE = b": keepalive\n\n"


class EventBody(IterBody):
    """IterBody that does NOT rechunk — each produced event flushes
    immediately as its own transfer chunk."""

    def __init__(self, chunks: Iterable[bytes]):
        super().__init__(chunks, chunk_bytes=1)

    def __iter__(self):
        return iter(self._source)


def format_event(update: dict) -> bytes:
    """One ``update`` event: the SSE ``id:`` field carries the
    subscription version, so a reconnecting client resumes with
    ``?after=<last id>`` (at-least-once, version-monotonic)."""
    data = json.dumps(update, separators=(",", ":"))
    return (
        f"id: {update['version']}\nevent: update\ndata: {data}\n\n"
    ).encode()


def event_stream(manager, sub, after: int, keepalive_s: float = 15.0):
    """Generator of SSE frames for one subscription: every retained
    update newer than ``after`` (or the current snapshot when the
    queue rotated past it), then live updates as the engine publishes
    them; keepalive comments while idle.  Ends when the subscription
    is unregistered or the manager shuts down.  The ``finally`` leg
    runs on client disconnect too (IterBody.close reaches the
    generator), so stream accounting can't leak."""
    with sub.cv:
        sub.streams += 1
    try:
        yield b": subscribed\n\n"
        while True:
            upd = manager.wait_update(sub, after, timeout=keepalive_s)
            if upd is None:
                if sub.closed:
                    return
                yield KEEPALIVE
                continue
            after = upd["version"]
            yield format_event(upd)
    finally:
        with sub.cv:
            sub.streams -= 1
