"""Per-fragment write-ahead log with group commit.

Durability discipline (ARIES-style log-before-data): every changed bit
is appended to a per-fragment WAL *before* the write is acknowledged,
and the ack blocks only on the WAL fsync — never on the fragment's
snapshot cycle.  Concurrent writers are batched into one fsync by a
dedicated committer thread per holder (System R-era group commit): a
writer parks on a shared :class:`concurrent.futures.Future`, the
committer lingers for the ``[ingest] group-commit-ms`` window (or until
``group-commit-max`` ops are pending), seals the buffered ops into one
checksummed frame, fsyncs once, and resolves the future for every
waiter at once.

Segment layout (``<fragment-path>.wal``)::

    header   "<4sIQQ"  magic=b"PWAL"  version=1  base_op_version  snap_size
    frame*   "<IIQ"    payload_len  n_ops  end_op_version
             payload   n_ops x 13-byte roaring op records
             digest    sha256(frame_header + payload), 32 bytes

``base_op_version`` is the fragment's logical op-version at the last
truncating snapshot; a frame's ``end_op_version`` is the version after
its last op, so replay can skip frames already covered by the snapshot.
``snap_size`` records the data file's op-region offset at truncation —
if the snapshot changed while the WAL was detached, the stale segment
is discarded rather than replayed against the wrong base.  The sha256
framing mirrors the PR-13 tar self-verification: a torn tail (partial
frame from a crash mid-append) fails its digest and replay stops at the
first bad frame, exactly the set of ops that were never acked.

Lock order (enforced by pilosa_tpu/analyze): ``frag._mu`` →
``WalWriter._io_mu`` → ``WalWriter._mu``.  The hot path
(:meth:`WalWriter.log`, called under ``frag._mu``) takes only ``_mu``
and never blocks on I/O; the committer takes ``_io_mu`` for the fsync
and ``_mu`` only for the buffer swap, so an in-flight fsync never
stalls a writer's append.

This module must not import :mod:`pilosa_tpu.core.fragment` at module
scope (the fragment module imports this package).
"""

from __future__ import annotations

import hashlib
import os
import struct
import threading
import time
from concurrent.futures import Future

from pilosa_tpu.obs.stats import NopStatsClient
from pilosa_tpu.ops import roaring

MAGIC = b"PWAL"
FORMAT_VERSION = 1

_HEADER = struct.Struct("<4sIQQ")  # magic, version, base_op_version, snap_size
_FRAME = struct.Struct("<IIQ")  # payload_len, n_ops, end_op_version
HEADER_SIZE = _HEADER.size
FRAME_HEADER_SIZE = _FRAME.size
DIGEST_SIZE = 32

# A frame payload is a run of fixed-size roaring op records; cap it so a
# corrupt length field can't allocate unbounded memory during replay.
MAX_FRAME_OPS = 1 << 20
MAX_FRAME_PAYLOAD = MAX_FRAME_OPS * roaring.OP_SIZE


class WalClosed(RuntimeError):
    """The WAL (or its manager) was closed while a write waited on it."""


def wal_path(fragment_path: str) -> str:
    return fragment_path + ".wal"


def encode_header(base_op_version: int, snap_size: int) -> bytes:
    return _HEADER.pack(MAGIC, FORMAT_VERSION, base_op_version, snap_size)


def encode_frame(payload: bytes, n_ops: int, end_op_version: int) -> bytes:
    hdr = _FRAME.pack(len(payload), n_ops, end_op_version)
    digest = hashlib.sha256(hdr + payload).digest()
    return hdr + payload + digest


class Segment:
    """A decoded WAL segment: the verified prefix of one ``.wal`` file."""

    __slots__ = ("base_op_version", "snap_size", "frames", "torn",
                 "good_bytes", "problem")

    def __init__(self, base_op_version: int = 0, snap_size: int = 0):
        self.base_op_version = base_op_version
        self.snap_size = snap_size
        # [(end_op_version, n_ops, payload bytes)] in append order.
        self.frames: list[tuple[int, int, bytes]] = []
        self.torn = False
        self.good_bytes = HEADER_SIZE
        self.problem: str | None = None

    @property
    def n_ops(self) -> int:
        return sum(n for _, n, _ in self.frames)

    @property
    def end_op_version(self) -> int:
        if self.frames:
            return self.frames[-1][0]
        return self.base_op_version


def load_segment(path: str) -> Segment | None:
    """Decode the WAL at ``path``; ``None`` if absent or header-corrupt.

    Tolerates a torn tail: decoding stops at the first frame whose
    length, digest, or op records fail verification (``seg.torn`` set,
    ``seg.good_bytes`` marks the durable prefix).  A header that does
    not verify means nothing in the file can be trusted — the caller
    should discard the segment entirely.
    """
    try:
        with open(path, "rb") as fh:
            data = fh.read()
    except FileNotFoundError:
        return None
    if len(data) < HEADER_SIZE:
        return None
    magic, version, base, snap_size = _HEADER.unpack_from(data, 0)
    if magic != MAGIC or version != FORMAT_VERSION:
        return None
    seg = Segment(base, snap_size)
    pos = HEADER_SIZE
    expect_version = base
    while pos < len(data):
        if pos + FRAME_HEADER_SIZE > len(data):
            seg.torn = True
            seg.problem = "torn frame header"
            break
        payload_len, n_ops, end_version = _FRAME.unpack_from(data, pos)
        if (payload_len > MAX_FRAME_PAYLOAD
                or payload_len != n_ops * roaring.OP_SIZE
                or n_ops == 0
                or end_version != expect_version + n_ops):
            seg.torn = True
            seg.problem = "bad frame header"
            break
        frame_end = pos + FRAME_HEADER_SIZE + payload_len + DIGEST_SIZE
        if frame_end > len(data):
            seg.torn = True
            seg.problem = "torn frame"
            break
        payload = data[pos + FRAME_HEADER_SIZE:frame_end - DIGEST_SIZE]
        digest = data[frame_end - DIGEST_SIZE:frame_end]
        want = hashlib.sha256(
            data[pos:pos + FRAME_HEADER_SIZE] + payload
        ).digest()
        if digest != want:
            seg.torn = True
            seg.problem = "frame checksum mismatch"
            break
        # The payload is raw roaring op records; verify each record's
        # own FNV checksum too so a bit-flip inside a frame that
        # somehow passes sha256 (or a hand-edited file) still rejects.
        ok = True
        for off in range(0, payload_len, roaring.OP_SIZE):
            _, _, problem = roaring._read_op(payload, off)
            if problem is not None:
                seg.torn = True
                seg.problem = f"op record: {problem}"
                ok = False
                break
        if not ok:
            break
        seg.frames.append((end_version, n_ops, payload))
        expect_version = end_version
        pos = frame_end
        seg.good_bytes = pos
    return seg


def _fsync_dir(path: str) -> None:
    """fsync the directory containing ``path`` so renames/creates in it
    survive a crash (POSIX makes the entry durable only after the
    *directory* is synced, not the file)."""
    d = os.path.dirname(path) or "."
    try:
        fd = os.open(d, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


class WalWriter:
    """One fragment's WAL segment: lock-free-of-I/O append + group commit.

    ``log()`` runs under ``frag._mu`` on the write hot path and only
    buffers; the manager's committer thread calls ``commit()`` which
    does the actual frame write + fsync.  ``truncate_segment()`` is
    called by the fragment's snapshot path (under ``frag._mu``, after
    the snapshot and its directory entry are themselves fsynced) and
    resets the segment to empty with a new base version.
    """

    def __init__(self, frag, path: str, base_op_version: int,
                 snap_size: int, manager: "IngestManager",
                 *, fresh: bool):
        self.frag = frag
        self.path = path
        self._manager = manager
        self.stats = manager.stats
        # Lock order: frag._mu -> _io_mu -> _mu.  _mu guards the
        # buffered (not yet durable) state; _io_mu serializes file
        # writes/fsyncs/truncations so commit never holds _mu across
        # I/O.
        self._io_mu = threading.Lock()
        self._mu = threading.Lock()
        self._buf = bytearray()
        self._buf_ops = 0
        self._op_version = base_op_version
        self._base = base_op_version
        self._snap_size = snap_size
        self._pending: Future | None = None
        self._closed = False
        self._wal_bytes = HEADER_SIZE
        self._last_fsync_ms = 0.0
        self._last_group = 0
        self._appends = 0
        self._fsyncs = 0
        # Cumulative frame bytes fsynced over the writer's lifetime —
        # unlike _wal_bytes this survives segment truncation, so the
        # bench can rate the log write bandwidth.
        self._bytes_written = 0
        if fresh:
            self._rewrite_locked_io(base_op_version, snap_size)
        else:
            self._file = open(path, "ab")
            self._wal_bytes = self._file.tell()

    # -- hot path (under frag._mu) ------------------------------------

    def log(self, typ: int, pos: int) -> Future:
        """Buffer one op record; returns the Future that resolves when
        the record is durable.  Never touches the file."""
        with self._mu:
            if self._closed:
                raise WalClosed(f"wal closed: {self.path}")
            self._buf += roaring.encode_op(typ, pos)
            self._buf_ops += 1
            self._op_version += 1
            self._appends += 1
            if self._pending is None:
                self._pending = Future()
            fut = self._pending
        self.stats.count("ingest.wal.appends")
        _note_pending(self, fut)
        self._manager._poke(self)
        return fut

    @property
    def op_version(self) -> int:
        with self._mu:
            return self._op_version

    # -- committer side -----------------------------------------------

    def commit(self) -> int:
        """Seal the buffered ops into one frame and fsync it.  Returns
        the number of ops made durable (0 if the buffer was empty)."""
        with self._io_mu:
            with self._mu:
                if self._closed or not self._buf_ops:
                    return 0
                payload = bytes(self._buf)
                n_ops = self._buf_ops
                end_version = self._op_version
                fut = self._pending
                self._buf = bytearray()
                self._buf_ops = 0
                self._pending = None
            frame = encode_frame(payload, n_ops, end_version)
            t0 = time.perf_counter()
            try:
                self._file.write(frame)
                self._file.flush()
                os.fsync(self._file.fileno())
            except OSError as e:
                if fut is not None and not fut.done():
                    fut.set_exception(e)
                raise
            self._wal_bytes += len(frame)
            self._last_fsync_ms = (time.perf_counter() - t0) * 1e3
            self._last_group = n_ops
            self._fsyncs += 1
            self._bytes_written += len(frame)
        self.stats.count("ingest.wal.fsyncs")
        self.stats.histogram("ingest.wal.groupSize", float(n_ops))
        if fut is not None and not fut.done():
            fut.set_result(None)
        return n_ops

    def truncate_segment(self, snap_size: int) -> None:
        """Reset the segment after a truncating snapshot.

        Caller holds ``frag._mu`` and has already fsynced the snapshot
        file AND its directory entry — every op the WAL covers (durable
        or still buffered) is now captured by the snapshot, so buffered
        waiters resolve as durable-by-snapshot and the log restarts
        empty at the new base version.
        """
        with self._io_mu:
            with self._mu:
                if self._closed:
                    return
                base = self._op_version
                fut = self._pending
                self._buf = bytearray()
                self._buf_ops = 0
                self._pending = None
                self._base = base
                self._snap_size = snap_size
            self._rewrite_locked_io(base, snap_size)
        self.stats.count("ingest.wal.truncations")
        if fut is not None and not fut.done():
            fut.set_result(None)

    def _rewrite_locked_io(self, base: int, snap_size: int) -> None:
        """(Re)create the segment file with just a header.  Caller holds
        ``_io_mu`` (or is the constructor, pre-publication)."""
        tmp = self.path + ".tmp"
        with open(tmp, "wb") as fh:
            fh.write(encode_header(base, snap_size))
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, self.path)
        _fsync_dir(self.path)
        self._file = open(self.path, "ab")
        self._wal_bytes = HEADER_SIZE

    def close(self, *, final_commit: bool = True) -> None:
        """Detach: optionally flush the tail, then close the file.
        Pending waiters that can't be committed fail with WalClosed."""
        if final_commit:
            try:
                self.commit()
            except OSError:
                pass
        with self._io_mu:
            with self._mu:
                if self._closed:
                    return
                self._closed = True
                fut = self._pending
                self._pending = None
                self._buf = bytearray()
                self._buf_ops = 0
            try:
                self._file.close()
            except OSError:
                pass
        if fut is not None and not fut.done():
            fut.set_exception(WalClosed(f"wal closed: {self.path}"))

    def snapshot(self) -> dict:
        with self._mu:
            return {
                "path": self.path,
                "walBytes": int(self._wal_bytes),
                "bufferedOps": int(self._buf_ops),
                "opVersion": int(self._op_version),
                "baseOpVersion": int(self._base),
                "lastFsyncMs": round(self._last_fsync_ms, 3),
                "lastGroupSize": int(self._last_group),
                "appends": int(self._appends),
                "fsyncs": int(self._fsyncs),
                "walBytesWritten": int(self._bytes_written),
            }


# -- per-thread durable-wait bookkeeping ------------------------------

_local = threading.local()


def _note_pending(writer: WalWriter, fut: Future) -> None:
    """Record this thread's latest un-awaited future per writer.
    Futures for one writer resolve in seal order, so waiting on the
    latest one covers every earlier append by the same thread."""
    pending = getattr(_local, "pending", None)
    if pending is None:
        pending = _local.pending = {}
    pending[id(writer)] = fut


class IngestManager:
    """Holder-scoped WAL orchestration: one committer thread batching
    every attached fragment's appends into per-fragment group commits.

    Registered in a module-level list so :func:`attach_fragment` (called
    from ``Fragment.open``) can find the manager owning a fragment by
    path prefix — keeps multiple in-process servers (tests) isolated.
    """

    def __init__(self, data_dir: str, *, wal: bool = True,
                 group_commit_ms: float = 2.0, group_commit_max: int = 128,
                 wal_segment_bytes: int = 4 << 20, stats=None, logger=None,
                 versions=None):
        self.data_dir = os.path.realpath(data_dir)
        self.wal_enabled = bool(wal)
        self.group_commit_ms = float(group_commit_ms)
        self.group_commit_max = int(group_commit_max)
        self.wal_segment_bytes = int(wal_segment_bytes)
        self.stats = stats if stats is not None else NopStatsClient()
        self.logger = logger or (lambda m: None)
        self.versions = versions
        self._mu = threading.Lock()
        self._cv = threading.Condition(self._mu)
        self._writers: dict[int, WalWriter] = {}
        self._dirty: dict[int, WalWriter] = {}
        self._dirty_since: float | None = None
        self._dirty_ops = 0
        self._closed = False
        self._last_replay: dict | None = None
        self._replays = 0
        self._replayed_ops = 0
        # appends/fsyncs from writers that already detached, so the
        # holder-wide totals in snapshot() survive fragment close.
        self._gone_appends = 0
        self._gone_fsyncs = 0
        self._thread: threading.Thread | None = None
        if self.wal_enabled:
            self._thread = threading.Thread(
                target=self._run, name="ingest-committer", daemon=True
            )
            self._thread.start()

    # -- registry -----------------------------------------------------

    def owns(self, path: str) -> bool:
        return os.path.realpath(path).startswith(self.data_dir + os.sep)

    def attach(self, frag) -> None:
        """Wire a fragment to this manager: replay any durable WAL tail
        newer than its snapshot, then install a fresh/continuing writer
        as ``frag._wal``.  Called from ``Fragment.open`` under
        ``frag._mu`` (lock order frag._mu -> wal locks holds)."""
        if not self.wal_enabled:
            return
        from pilosa_tpu.ingest import recovery

        path = wal_path(frag.path)
        seg = load_segment(path)
        snap_size, data_ops = _data_state(frag)
        fresh = True
        base = 0
        if seg is not None:
            wal_ops = b"".join(p for _, _, p in seg.frames)
            if seg.snap_size != snap_size:
                # Snapshot advanced while the WAL was detached (or the
                # data file was replaced out from under us): the
                # segment's base no longer matches, replay would
                # double- or mis-apply.  Discard and restart.
                self.logger(
                    f"[ingest] discarding stale wal segment {path} "
                    f"(snap_size {seg.snap_size} != {snap_size})"
                )
            elif not wal_ops.startswith(data_ops):
                # The data file's op-log is NOT a prefix of the WAL's
                # op sequence: the fragment took writes while the WAL
                # was detached (e.g. the WAL was toggled off for a
                # while).  The two histories can't be ordered, so the
                # stale segment is forfeited — logged loudly because
                # any op unique to it is lost.
                self.logger(
                    f"[ingest] discarding diverged wal segment {path} "
                    f"(data op-log is not a prefix of the logged ops; "
                    f"{len(seg.frames)} frames forfeited)"
                )
            else:
                report = recovery.replay(frag, seg, self)
                self._note_replay(frag, report)
                base = seg.end_op_version
                if report["replayed"] or report["unchanged"]:
                    # Post-recovery checkpoint (ARIES-style restart
                    # checkpoint): fold the replayed tail into a fresh
                    # snapshot so the data op-log and the WAL restart
                    # aligned at the new base version.
                    frag.snapshot()
                    snap_size, _ = _data_state(frag)
                else:
                    fresh = False
                    if seg.torn:
                        # Drop the un-verifiable tail so new frames
                        # append after the last good one, not after
                        # garbage.
                        _truncate_file(path, seg.good_bytes)
        if fresh and frag._op_n:
            # A fresh segment starts at base with an implicit "zero
            # preceding ops" contract; fold any existing op-log tail
            # into the snapshot so a future recovery's skip count can't
            # desync from the frame versions.
            frag.snapshot()
            snap_size, _ = _data_state(frag)
        writer = WalWriter(frag, path, base, snap_size, self, fresh=fresh)
        frag._wal = writer
        with self._mu:
            if self._closed:
                raise WalClosed("ingest manager closed")
            self._writers[id(writer)] = writer

    def detach(self, writer: WalWriter) -> None:
        """Called from ``Fragment.close`` (under frag._mu)."""
        with self._mu:
            self._writers.pop(id(writer), None)
            self._dirty.pop(id(writer), None)
        writer.close(final_commit=True)
        with self._mu:
            self._gone_appends += writer._appends
            self._gone_fsyncs += writer._fsyncs

    def _note_replay(self, frag, report: dict) -> None:
        with self._mu:
            self._replays += 1
            self._replayed_ops += int(report.get("replayed", 0))
            self._last_replay = report
        self.logger(
            f"[ingest] replayed {report['replayed']} wal ops for "
            f"{frag.index}/{frag.frame}/{frag.view}/{frag.slice}"
            + (" (torn tail)" if report.get("torn") else "")
        )

    # -- group commit -------------------------------------------------

    def _poke(self, writer: WalWriter) -> None:
        with self._mu:
            if self._closed:
                return
            self._dirty[id(writer)] = writer
            self._dirty_ops += 1
            if self._dirty_since is None:
                self._dirty_since = time.monotonic()
            self._cv.notify()

    def _run(self) -> None:
        window = self.group_commit_ms / 1e3
        while True:
            with self._mu:
                while not self._dirty and not self._closed:
                    self._cv.wait()
                if self._closed and not self._dirty:
                    return
                # Linger: let concurrent writers pile into this frame
                # until the window elapses or the batch is full.
                while not self._closed:
                    elapsed = time.monotonic() - (self._dirty_since or 0.0)
                    if (elapsed >= window
                            or self._dirty_ops >= self.group_commit_max):
                        break
                    self._cv.wait(timeout=window - elapsed)
                batch = list(self._dirty.values())
                self._dirty.clear()
                self._dirty_since = None
                self._dirty_ops = 0
            rollover = []
            for w in batch:
                try:
                    w.commit()
                except OSError as e:
                    self.logger(f"[ingest] wal commit error: {e}")
                    continue
                if w._wal_bytes > self.wal_segment_bytes:
                    rollover.append(w)
            for w in rollover:
                # Snapshot truncates the segment (frag.snapshot ->
                # truncate_segment).  No manager locks held: snapshot
                # takes frag._mu which is above the wal locks.
                try:
                    w.frag.snapshot()
                except Exception as e:
                    self.logger(f"[ingest] rollover snapshot error: {e}")
            for w in batch:
                # Background mirror maintenance: fold the writes this
                # commit covered into the device mirror off the read
                # path, so a read storm usually finds it clean instead
                # of paying the scatter launch inline.  Same lock
                # position as the rollover snapshot (frag._mu, no
                # manager locks held); best-effort — a failure just
                # leaves the apply to the next read.
                try:
                    apply_fn = getattr(w.frag, "apply_pending_scatter", None)
                    if apply_fn is not None:
                        apply_fn()
                except Exception as e:
                    self.logger(f"[ingest] background scatter error: {e}")

    def wait_durable(self, timeout: float = 30.0) -> None:
        """Block until every append made by THIS thread is durable.
        No-op when the WAL is disabled or the thread wrote nothing."""
        pending = getattr(_local, "pending", None)
        if not pending:
            return
        futs = list(pending.values())
        pending.clear()
        for fut in futs:
            fut.result(timeout=timeout)

    # -- lifecycle / debug --------------------------------------------

    def close(self) -> None:
        with self._mu:
            if self._closed:
                return
            self._closed = True
            self._cv.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
        with self._mu:
            writers = list(self._writers.values())
            self._writers.clear()
            self._dirty.clear()
        for w in writers:
            w.close(final_commit=True)

    def snapshot(self) -> dict:
        with self._mu:
            writers = list(self._writers.values())
            doc = {
                "walEnabled": self.wal_enabled,
                "groupCommitMs": self.group_commit_ms,
                "groupCommitMax": self.group_commit_max,
                "walSegmentBytes": self.wal_segment_bytes,
                "fragments": len(writers),
                "replays": self._replays,
                "replayedOps": self._replayed_ops,
                "lastReplay": self._last_replay,
            }
            gone_appends = self._gone_appends
            gone_fsyncs = self._gone_fsyncs
        doc["writers"] = [w.snapshot() for w in writers]
        doc["totalAppends"] = gone_appends + sum(
            w["appends"] for w in doc["writers"]
        )
        doc["totalFsyncs"] = gone_fsyncs + sum(
            w["fsyncs"] for w in doc["writers"]
        )
        return doc


def _truncate_file(path: str, size: int) -> None:
    with open(path, "r+b") as fh:
        fh.truncate(size)
        fh.flush()
        os.fsync(fh.fileno())


def _data_state(frag) -> tuple[int, bytes]:
    """The data file's op-region offset (the byte size of the snapshot
    portion — identifies WHICH snapshot a WAL segment was truncated
    against) plus the parsed op-log bytes, truncated to the records the
    fragment actually recovered (``frag._op_n`` — a torn op tail is
    excluded so the WAL prefix comparison isn't spooked by it)."""
    try:
        with open(frag.path, "rb") as fh:
            data = fh.read()
    except FileNotFoundError:
        return 0, b""
    if not data:
        return 0, b""
    try:
        off = roaring.ops_region_offset(data)
    except roaring.CorruptError:
        return 0, b""
    return off, bytes(data[off:off + frag._op_n * roaring.OP_SIZE])


# -- module registry --------------------------------------------------

_reg_mu = threading.Lock()
_managers: list[IngestManager] = []


def register_manager(m: IngestManager) -> None:
    with _reg_mu:
        _managers.append(m)


def unregister_manager(m: IngestManager) -> None:
    with _reg_mu:
        try:
            _managers.remove(m)
        except ValueError:
            pass


def attach_fragment(frag) -> None:
    """Called from ``Fragment.open``: find the manager owning this
    fragment's path (if any) and attach.  Silently a no-op for
    fragments outside any managed data dir (unit tests, tools)."""
    with _reg_mu:
        managers = list(_managers)
    for m in managers:
        if m.owns(frag.path):
            m.attach(frag)
            return
