"""Device delta-scatter: incremental HBM-mirror maintenance.

The seed design invalidated the device mirror on every point write,
forcing a multi-MB plane re-stage on the next read — fatal under the
sustained write streams replication and standing queries now invite.
With scatter enabled, a fragment instead queues its point-write deltas
(slot, word, set/clear mask) and :func:`apply` folds them into unique
(slot, word, or-mask, andnot-mask) updates applied to the resident
plane as ONE tiny fused jitted launch (:func:`pilosa_tpu.exec.plan.
scatter_apply`).  The update count is pow2-bucketed — padding repeats
the LAST real entry so duplicate scatter indices write identical
values (deterministic) — keeping the ``plan.scatter`` program cache
bounded by the bucket grid.  The launch rides the PlanePool pin lease
(caller pins the mirror key) and the collective launch discipline.

``_invalidate_device()`` remains the fallback for structural changes:
row growth past the padded plane shape, ``import_bulk`` above
:data:`IMPORT_SCATTER_MAX` queued updates, or scatter disabled by
config.  Module-level counters feed ``exec.scatter.*`` metrics and the
``/debug/ingest`` document.

This module must not import :mod:`pilosa_tpu.core.fragment` at module
scope (the fragment module imports this package).
"""

from __future__ import annotations

import threading

import numpy as np

from pilosa_tpu.ops import bitplane as bp

# Flipped by Server from ``[ingest] scatter``; module-level so fragments
# see the setting without per-fragment plumbing.  Off restores the
# historical invalidate-on-write behavior (and gives the ingest bench
# its re-stage contrast arm).
ENABLED = True

# import_bulk queues per-bit scatter updates only below this count;
# past it, a bulk import re-stages the whole plane (one upload beats
# tens of thousands of folded updates).
IMPORT_SCATTER_MAX = 4096

# Floor of the pow2 update-count bucket grid.  Point-write batches are
# almost always tiny (a group-commit tick's worth of deltas), and each
# DISTINCT bucket pays a one-time XLA compile (~tens of ms) while the
# committer holds the fragment lock — a read-tail cliff.  Padding every
# small batch up to one shared bucket trades a few dozen no-op scatter
# lanes (microseconds) for hitting a warm program on every apply.
UPDATE_BUCKET_FLOOR = 32

_mu = threading.Lock()
_launches = 0
_updates_applied = 0
_fallback_invalidations = 0


def fold(pending) -> tuple:
    """Fold a [(slot, word, mask, op)] queue into unique per-word
    (slots, words, or_masks, andnot_masks) arrays, later ops winning
    per bit — the same cancellation rule the host-side pending fold
    has always used (set clears the bit from the andnot mask and vice
    versa)."""
    acc: dict[tuple[int, int], list[int]] = {}
    for slot, word, mask, op in pending:
        cell = acc.setdefault((slot, word), [0, 0])
        if op:
            cell[0] |= mask
            cell[1] &= ~mask & 0xFFFFFFFF
        else:
            cell[1] |= mask
            cell[0] &= ~mask & 0xFFFFFFFF
    n = len(acc)
    slots = np.empty(n, dtype=np.int32)
    words = np.empty(n, dtype=np.int32)
    or_m = np.empty(n, dtype=np.uint32)
    andnot_m = np.empty(n, dtype=np.uint32)
    for i, ((slot, word), (s, c)) in enumerate(acc.items()):
        slots[i] = slot
        words[i] = word
        or_m[i] = s
        andnot_m[i] = c
    return slots, words, or_m, andnot_m


def _pad_to_bucket(slots, words, or_m, andnot_m):
    """Pad the update axis to its pow2 bucket by REPEATING the last
    real entry: duplicate indices then scatter identical values, which
    is deterministic regardless of XLA's duplicate-index ordering."""
    n = len(slots)
    b = bp.pow2_bucket(n, UPDATE_BUCKET_FLOOR)
    if b == n:
        return slots, words, or_m, andnot_m
    pad = b - n
    return (
        np.concatenate([slots, np.repeat(slots[-1:], pad)]),
        np.concatenate([words, np.repeat(words[-1:], pad)]),
        np.concatenate([or_m, np.repeat(or_m[-1:], pad)]),
        np.concatenate([andnot_m, np.repeat(andnot_m[-1:], pad)]),
    )


def apply(dev, pending):
    """Apply a pending delta queue to device plane ``dev`` in one fused
    scatter launch; returns the NEW plane array (old left intact for
    concurrent readers).  Caller holds the fragment lock and the
    PlanePool pin lease for the mirror key, and ``pending`` is
    non-empty."""
    global _launches, _updates_applied
    from pilosa_tpu.exec import plan

    slots, words, or_m, andnot_m = _pad_to_bucket(*fold(pending))
    with plan.collective_launch():
        out = plan.scatter_apply(dev, slots, words, or_m, andnot_m)
    with _mu:
        _launches += 1
        _updates_applied += len(pending)
    return out


def note_fallback(n: int = 1) -> None:
    """Record a structural-change fallback to full mirror invalidation
    (feeds ``exec.scatter.fallbackInvalidations``)."""
    global _fallback_invalidations
    with _mu:
        _fallback_invalidations += n


def counters() -> dict:
    with _mu:
        return {
            "launches": _launches,
            "updatesApplied": _updates_applied,
            "fallbackInvalidations": _fallback_invalidations,
        }


def publish_stats(stats) -> None:
    """Push the module counters as gauges (called from the server's
    stats loop alongside the other exec gauges)."""
    c = counters()
    stats.gauge("exec.scatter.launches", float(c["launches"]))
    stats.gauge("exec.scatter.updatesApplied", float(c["updatesApplied"]))
    stats.gauge(
        "exec.scatter.fallbackInvalidations",
        float(c["fallbackInvalidations"]),
    )


def reset_counters() -> None:
    """Test isolation."""
    global _launches, _updates_applied, _fallback_invalidations
    with _mu:
        _launches = _updates_applied = _fallback_invalidations = 0
