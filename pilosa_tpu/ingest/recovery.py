"""Crash recovery: replay the WAL tail newer than the snapshot.

At fragment open, :func:`pilosa_tpu.ingest.wal.IngestManager.attach`
decodes the fragment's ``.wal`` segment (checksum-verified, torn tail
dropped at the first bad frame) and calls :func:`replay` with it.  The
data file's own op-log and the WAL record the SAME changed-op sequence
— the data op-log is just the possibly-shorter prefix that happened to
be flushed before the crash (``_op_buf`` batches up to 64 KiB before
hitting the file) — so recovery is exactly: skip the first
``frag._op_n`` WAL ops (already in the data file and applied by
``_open_storage``), replay the rest through ``set_bit``/``clear_bit``
with ``frag._wal_replaying`` set (suppresses write-listener fanout,
WAL re-logging, and mid-replay auto-snapshots), and stamp
``replicate.versions`` by the applied count so quorum read-repair
accounting stays consistent with what peers saw acked.

Replay runs under ``frag._mu`` (it is invoked from ``Fragment.open``);
``set_bit`` re-enters the RLock harmlessly.
"""

from __future__ import annotations

from pilosa_tpu.ops import bitplane as bp
from pilosa_tpu.ops import roaring

SLICE_WIDTH = bp.SLICE_WIDTH


def replay(frag, seg, manager) -> dict:
    """Apply the WAL ops in ``seg`` that are newer than the fragment's
    recovered state.  Returns a report dict for /debug/ingest."""
    skip = frag._op_n  # ops already durable in the data file's op-log
    applied = 0
    unchanged = 0
    seen = 0
    col_base = frag.slice * SLICE_WIDTH
    frag._wal_replaying = True
    try:
        for _end_version, n_ops, payload in seg.frames:
            for off in range(0, n_ops * roaring.OP_SIZE, roaring.OP_SIZE):
                seen += 1
                if seen <= skip:
                    continue
                typ, pos, _ = roaring._read_op(payload, off)
                row = pos // SLICE_WIDTH
                col = col_base + pos % SLICE_WIDTH
                if typ == roaring.OP_ADD:
                    changed = frag.set_bit(row, col)
                else:
                    changed = frag.clear_bit(row, col)
                if changed:
                    applied += 1
                else:
                    unchanged += 1
    finally:
        frag._wal_replaying = False
    if applied:
        manager.stats.count("ingest.wal.replayedRecords", applied)
        if manager.versions is not None:
            # Each replayed op was acked pre-crash and (under quorum)
            # counted by peers; advance the local version clock so
            # read-repair doesn't treat this replica as behind.
            manager.versions.bump_many(frag.index, frag.slice, applied)
    if seg.torn:
        manager.stats.count("ingest.wal.tornTail")
    return {
        "fragment": f"{frag.index}/{frag.frame}/{frag.view}/{frag.slice}",
        "walOps": seg.n_ops,
        "skipped": min(skip, seen),
        "replayed": applied,
        "unchanged": unchanged,
        "torn": bool(seg.torn),
        "problem": seg.problem,
    }
