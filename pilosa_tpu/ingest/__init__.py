"""Durable ingest — the write-optimized half of the engine (ISSUE 18).

Three coupled pieces:

- :mod:`pilosa_tpu.ingest.wal` — per-fragment write-ahead log of
  sha256-framed op records with a group-commit committer thread
  (ARIES-style log-before-data; System R-era commit batching).  Acks
  return only after the record is durable.
- :mod:`pilosa_tpu.ingest.recovery` — at fragment open, replay WAL
  records newer than the snapshot's op-version (checksum-verified,
  torn-tail tolerated) and stamp replicate/versions so quorum
  accounting stays consistent after a ``kill -9``.
- :mod:`pilosa_tpu.ingest.scatter` — incremental HBM-mirror
  maintenance: queued point-write deltas apply as ONE tiny fused
  jitted scatter launch (pow2-bucketed update count) instead of
  invalidating and re-staging the whole plane.

None of these modules import :mod:`pilosa_tpu.core.fragment` at module
scope — the fragment module imports this package for its write hooks,
so the dependency edge must stay one-way at import time.
"""

from pilosa_tpu.ingest import scatter  # noqa: F401 — re-export
from pilosa_tpu.ingest.wal import (  # noqa: F401 — re-export
    IngestManager,
    WalClosed,
    WalWriter,
    attach_fragment,
    load_segment,
)
