"""Multi-host device meshes — jax.distributed wiring.

Two distribution regimes compose in this framework (SURVEY.md §5
"distributed communication backend"):

1. **HTTP+protobuf across clusters of independent hosts** — the
   reference-compatible path (net/, cluster/): each node owns slices,
   queries fan out, reduces merge on the coordinator.  Works anywhere,
   no shared ICI required.
2. **One JAX process group across hosts that share an ICI/DCN domain**
   (a TPU pod slice): all hosts join a single runtime via
   ``jax.distributed.initialize``; ``jax.devices()`` then spans every
   host, the slices mesh covers the pod, and cross-host reduces ride
   ICI/DCN as XLA collectives instead of HTTP fan-in.

This module wires regime 2.  Call :func:`initialize` once per process
before any JAX computation; afterwards ``parallel.mesh`` and the
executor's sharded path transparently use the global device set
(``jax.local_devices()`` stays host-local, which keeps fragment
placement host-local — each host still owns the slices whose planes it
pins; global collectives happen inside the jitted query programs).
"""

from __future__ import annotations

import os


def initialize(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> None:
    """Join this process to a multi-host JAX runtime.

    Only gates on ``JAX_COORDINATOR_ADDRESS`` (or the explicit
    argument); everything else passes through as ``None`` so
    ``jax.distributed.initialize`` keeps its own env/cluster
    auto-detection (Cloud-TPU / Slurm plugins fill per-host process ids
    only for params left unset — supplying defaults here would break
    pod launches).  No-ops when unconfigured (single-host deployments)
    or when the process group already exists.
    """
    import jax

    coordinator_address = coordinator_address or os.environ.get(
        "JAX_COORDINATOR_ADDRESS"
    )
    if coordinator_address is None:
        return
    # Re-init guard: jax.distributed.initialize raises if called twice.
    if jax.distributed.is_initialized():
        return
    env_np = os.environ.get("JAX_NUM_PROCESSES")
    env_pid = os.environ.get("JAX_PROCESS_ID")
    if num_processes is None and env_np is not None:
        num_processes = int(env_np)
    if process_id is None and env_pid is not None:
        process_id = int(env_pid)
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def global_device_count() -> int:
    import jax

    return len(jax.devices())


def is_multihost() -> bool:
    import jax

    return jax.process_count() > 1
