"""Device-mesh execution: slices sharded over TPU chips, ICI reduces.

The reference distributes slices over *nodes* and reduces over HTTP
(reference: executor.go:1149-1243 mapReduce; SURVEY.md §2.10).  Within a
TPU host the same map lives on a `jax.sharding.Mesh`:

* **slices axis** — the unbounded column axis, 2^20 columns per slice
  (the reference's inter-node data parallelism).  Slices are disjoint
  column ranges, so a cross-slice "Union" of result rows is a *merge*,
  never an OR; the only cross-slice collectives are ``psum`` for counts
  and gather/merge for TopN pairs.
* **rows axis** — shards a fragment's row dimension for TopN scoring
  (the analog of tensor parallelism: one row-block per device, scored
  against a replicated src row).

Planes are laid out ``uint32[n_slices, rows, words]`` and sharded
``P(AXIS_SLICES, AXIS_ROWS, None)``; the word axis stays contiguous so
the fused bitwise+popcount kernels see full 128 KiB slice-rows.

Multi-host: the same mesh spans hosts via jax distributed initialization,
with XLA routing the psum over ICI within a pod slice and DCN across
pods — no NCCL/MPI translation, per SURVEY.md §5 "distributed
communication backend".
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from pilosa_tpu.exec import plan

AXIS_SLICES = "slices"
AXIS_ROWS = "rows"

_slices_mesh: Mesh | None = None


def default_slices_mesh() -> Mesh | None:
    """A 1-D slices mesh over the participating local devices; None on
    single-device hosts (the executor then uses the plain vmapped
    path)."""
    global _slices_mesh
    n = mesh_device_count()
    if n < 2:
        return None
    devs = jax.local_devices()[:n]
    if _slices_mesh is None or _slices_mesh.devices.size != n:
        _slices_mesh = Mesh(np.array(devs), (AXIS_SLICES,))
    return _slices_mesh


from pilosa_tpu.ops.bitplane import (  # noqa: E402 — re-export; placement
    home_device,  # policy lives with the kernels so core/ never imports
    mesh_device_count,  # this module.
)


def assemble_sharded_batch(blocks: list[jax.Array], mesh: Mesh) -> jax.Array:
    """Glue per-device blocks (block d committed to mesh device d, all
    the same shape) into one global array sharded P(slices) on axis 0
    — no device-to-device traffic."""
    chunk = blocks[0].shape[0]
    shape = (len(blocks) * chunk,) + blocks[0].shape[1:]
    spec = P(AXIS_SLICES, *([None] * (len(shape) - 1)))
    return jax.make_array_from_single_device_arrays(
        shape, NamedSharding(mesh, spec), blocks
    )


def slice_mesh(n_devices: int | None = None, row_shards: int = 1) -> Mesh:
    """A (slices, rows) mesh over the first ``n_devices`` devices.

    ``row_shards`` splits the row axis (TopN scoring parallelism); the
    remaining devices shard the slice axis.
    """
    devs = jax.devices()
    n = n_devices or len(devs)
    if n % row_shards != 0:
        raise ValueError(f"n_devices {n} not divisible by row_shards {row_shards}")
    grid = np.array(devs[:n]).reshape(n // row_shards, row_shards)
    return Mesh(grid, (AXIS_SLICES, AXIS_ROWS))


def plane_spec() -> P:
    return P(AXIS_SLICES, AXIS_ROWS, None)


def shard_planes(planes: np.ndarray, mesh: Mesh) -> jax.Array:
    """Place ``uint32[n_slices, rows, words]`` onto the mesh, slice axis
    over AXIS_SLICES and row axis over AXIS_ROWS.  Pads the slice axis up
    to the mesh size (zero slices contribute nothing to any query)."""
    n_sl = mesh.shape[AXIS_SLICES]
    n_rw = mesh.shape[AXIS_ROWS]
    s, r, w = planes.shape
    pad_s = (-s) % n_sl
    pad_r = (-r) % n_rw
    if pad_s or pad_r:
        planes = np.pad(planes, ((0, pad_s), (0, pad_r), (0, 0)))
    return jax.device_put(planes, NamedSharding(mesh, plane_spec()))


# ---------------------------------------------------------------------------
# Distributed query kernels.  Each is jitted with the mesh baked in via
# sharding annotations — XLA inserts the ICI collectives.
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, static_argnames=("expr",))
def _count_tree(expr: tuple, leaf_planes: jax.Array) -> jax.Array:
    """Fused tree-count over ``uint32[n_slices, n_leaves, rows, words]``:
    evaluates the bitmap expression and popcount-reduces the word axis to
    int32[n_slices, rows] partials.  One slice-row holds at most 2^20
    bits so a partial always fits int32; the unbounded cross-slice /
    cross-row total is summed on host in int64 (JAX x64 is off)."""
    out = plan._eval_expr(expr, leaf_planes.swapaxes(0, 1))
    return jnp.sum(jax.lax.population_count(out).astype(jnp.int32), axis=-1)


def _collective(fn, health=None):
    """Run one collective-bearing dispatch+fetch serialized with every
    other collective in the process — and, when a device-health manager
    (device/health.py) is passed, under its hung-collective watchdog
    and quarantine breaker (``LaunchWatchdogTimeout`` /
    ``CollectiveUnavailable`` propagate to the caller, who falls back
    to the non-collective path)."""
    if health is not None:
        return health.run_collective(fn)
    with plan.collective_launch():
        return fn()


def distributed_count(
    expr: tuple,
    leaf_planes: jax.Array,
    n_partials: int | None = None,
    health=None,
) -> int:
    """Count(tree) where each leaf is a full sharded plane.

    ``leaf_planes``: uint32[n_slices, n_leaves, rows, words] sharded
    P(slices, None, rows, None).  The cross-slice/cross-row reduce runs
    on-device (plan.compiled_total_count — all-reduce over the mesh)
    whenever the partial count fits the int32 budget; beyond that the
    per-partial host sum (int64) takes over.  Callers whose planes carry
    zero padding (shard_planes) may pass the REAL slice-row count as
    ``n_partials`` — zero pads cannot overflow the budget.  A watchdog
    trip or a quarantined collective path (``health``) degrades to the
    per-partial host sum instead of wedging.
    """
    if n_partials is None:
        n_partials = leaf_planes.shape[0] * leaf_planes.shape[2]
    sh = leaf_planes.sharding
    if isinstance(sh, NamedSharding) and n_partials <= plan.MAX_ONDEVICE_COUNT_PARTIALS:
        try:
            limbs = _collective(
                lambda: jax.device_get(
                    plan.compiled_total_count(expr, sh.mesh)(leaf_planes)
                ),
                health,
            )
            return plan.recombine_count_limbs(limbs)
        except Exception as e:
            if not _collective_degraded(e, health):
                raise
    return int(np.asarray(_count_tree(expr, leaf_planes), dtype=np.int64).sum())


def _collective_degraded(exc, health) -> bool:
    """Whether a collective failure should degrade to the
    non-collective path (watchdog trip / quarantined) rather than
    propagate."""
    if health is None:
        return False
    from pilosa_tpu.device import health as health_mod

    return isinstance(
        exc,
        (health_mod.LaunchWatchdogTimeout, health_mod.CollectiveUnavailable),
    )


@jax.jit
def _topn_partials(plane: jax.Array, src: jax.Array):
    """Per-(slice, row) |row AND src| -> int32[n_slices, rows].

    ``plane``: uint32[n_slices, rows, words] sharded (slices, rows, -).
    ``src``:   uint32[n_slices, words] sharded (slices, -) — one src row
    per slice (a RowBitmap's segments, stacked).

    Only the word axis reduces on device (a partial <= 2^20 always fits
    int32); the cross-slice per-row total — unbounded — is summed on
    host in int64.
    """
    return jnp.sum(
        jax.lax.population_count(plane & src[:, None, :]).astype(jnp.int32),
        axis=-1,
    )


@functools.lru_cache(maxsize=8)
def _topn_total_fn(mesh: Mesh):
    """Per-row |row AND src| totals with the cross-slice reduce
    on-device: the slice-axis sum inside the jitted program becomes an
    all-reduce over the slices mesh axis (and an all-gather over the
    rows axis for the replicated output) — only the per-row limb totals
    ever reach the host, not the [n_slices, rows] partials.  Like
    plan.compiled_total_count, the sums run in 16-bit limbs (TPUs have
    no int64), int32-exact up to 2^15 slices; returns int32[2, rows] =
    (hi, lo) with per-row total = (hi << 16) + lo."""
    rep = NamedSharding(mesh, P())

    def fn(plane, src):
        partials = jnp.sum(
            jax.lax.population_count(plane & src[:, None, :]).astype(jnp.int32),
            axis=-1,
        )  # int32[n_slices, rows], each <= 2^20
        lo = jnp.sum(partials & 0xFFFF, axis=0)
        hi = jnp.sum(partials >> 16, axis=0)
        return jnp.stack([hi, lo])

    return jax.jit(fn, out_shardings=rep)


def distributed_topn(plane: jax.Array, src: jax.Array, k: int, health=None):
    """TopN(Src=...) over a sharded fragment-stack: returns (counts,
    row_ids) host arrays, count-descending, ties toward lower id —
    matching the reference Pair sort (reference: cache.go:316-330).

    The cross-slice per-row reduce runs on-device (all-reduce) within
    the limb budget; the final rank (a [rows] vector) keeps the
    host stable-argsort for the exact reference tie-break.  Like
    distributed_count, a watchdog trip / quarantined collective
    (``health``) degrades to the per-partial host sum."""
    per = None
    sh = plane.sharding
    if isinstance(sh, NamedSharding) and plane.shape[0] <= plan.MAX_ONDEVICE_COUNT_PARTIALS:
        try:
            per = plan.recombine_count_limbs(
                _collective(
                    lambda: jax.device_get(_topn_total_fn(sh.mesh)(plane, src)),
                    health,
                )
            )
        except Exception as e:
            if not _collective_degraded(e, health):
                raise
    if per is None:
        per = np.asarray(_topn_partials(plane, src), dtype=np.int64).sum(axis=0)
    k = min(k, per.shape[0])
    ids = np.argsort(-per, kind="stable")[:k]
    return per[ids], ids


# ---------------------------------------------------------------------------
# The full sharded step for dry-run / benchmarking: mutate + query + topn.
# ---------------------------------------------------------------------------


def query_step(mesh: Mesh):
    """Build a jitted end-to-end step over ``mesh``: applies a batch of
    bit mutations (scatter-OR), then runs Count(Intersect(r0, r1)) and a
    TopN scoring pass — the write+read cycle of SURVEY.md §3.2/§3.3 as
    one compiled program.

    Returns ``step(planes, rows_upd, words_upd, masks) -> (planes',
    count, top_counts, top_ids)`` where planes is
    uint32[n_slices, rows, words] sharded (slices, rows, None) and the
    update batch indexes [n_upd] within every slice's local block.

    The (rows_upd, words_upd) pairs must be unique: the scatter computes
    ``old | mask`` per entry, so duplicate targets would race.  The host
    write path pre-combines duplicates (``np.bitwise_or.at`` in
    ops/bitplane.np_set_bulk) before flushing a batch to the device.
    """
    pspec = NamedSharding(mesh, plane_spec())
    rep = NamedSharding(mesh, P())

    @functools.partial(
        jax.jit,
        out_shardings=(pspec, rep, rep, rep),
    )
    def step(planes, rows_upd, words_upd, masks):
        # Write path: batched scatter-OR of the update batch into every
        # slice (each slice applies its own mask batch).
        def upd_one(pl, m):
            return pl.at[rows_upd, words_upd].set(pl[rows_upd, words_upd] | m)

        planes = jax.vmap(upd_one)(planes, masks)
        # Read path: Count(Intersect(row0, row1)) across all slices;
        # int32 partials per slice (one slice-row <= 2^20 bits).
        a = planes[:, 0, :]
        b = planes[:, 1, :]
        count = jnp.sum(jax.lax.population_count(a & b).astype(jnp.int32), axis=-1)
        # TopN: per-row intersection counts with row 0 as src, global
        # top-4.  int32 is safe up to 2047 slices (2047 x 2^20 < 2^31);
        # the production path (distributed_topn) host-sums in int64.
        per_row = jnp.sum(
            jax.lax.population_count(planes & a[:, None, :]).astype(jnp.int32),
            axis=(0, 2),
        )
        top_counts, top_ids = jax.lax.top_k(per_row, 4)
        return planes, count, top_counts, top_ids

    return step
