"""Command logic for the CLI (reference: ctl/*.go, server/server.go).

Each ``run_*`` takes the parsed argparse namespace.  Separated from the
flag definitions the way the reference splits ``ctl/`` from ``cmd/``.
"""

from __future__ import annotations

import csv
import io
import os
import sys
import time
from datetime import datetime, timezone

from pilosa_tpu import config as config_mod
from pilosa_tpu.ops import roaring
from pilosa_tpu.ops.bitplane import SLICE_WIDTH

# reference: pilosa.go:107-108
TIME_FORMAT = "%Y-%m-%dT%H:%M"


class CommandError(RuntimeError):
    pass


def _client(host: str):
    from pilosa_tpu.net.client import InternalClient

    return InternalClient(host, timeout=60.0)


def _out(args, attr="output_file"):
    path = getattr(args, attr, "") or ""
    if path:
        return open(path, "wb")
    return sys.stdout.buffer


# ---------------------------------------------------------------------------
# server (reference: server/server.go:49-203)
# ---------------------------------------------------------------------------


def build_server(cfg: config_mod.Config):
    """Config -> wired Server (the reference's SetupServer)."""
    from pilosa_tpu.cluster import broadcast as bc
    from pilosa_tpu.cluster.topology import Cluster
    from pilosa_tpu.net.server import Server
    from pilosa_tpu.obs.stats import new_stats_client

    if cfg.tpu.mesh_shape:
        os.environ["PILOSA_TPU_MESH_SHAPE"] = cfg.tpu.mesh_shape


    # Logging: log-path file or stderr (reference: server/server.go:125-133).
    if cfg.log_path:
        log_file = open(os.path.expanduser(cfg.log_path), "a", buffering=1)

        def logger(msg: str) -> None:
            log_file.write(msg.rstrip() + "\n")
    else:

        def logger(msg: str) -> None:
            print(msg, file=sys.stderr)

    cluster = Cluster(
        replica_n=cfg.cluster.replicas,
        long_query_time=cfg.cluster.long_query_time,
    )
    for host in cfg.cluster.hosts:
        cluster.add_node(host)

    stats = new_stats_client(cfg.metrics.service, cfg.metrics.host)
    broadcaster = bc.NopBroadcaster()
    receiver = bc.NopBroadcastReceiver()
    if cfg.cluster.type == "http":
        peers = [h for h in cfg.cluster.internal_hosts]
        broadcaster = bc.HTTPBroadcaster(peers)
        bind = cfg.host.split(":")[0] or "0.0.0.0"
        receiver = bc.HTTPBroadcastReceiver(bind, cfg.cluster.internal_port)
    elif cfg.cluster.type == "gossip":
        from pilosa_tpu.cluster.gossip import GossipNodeSet

        nodeset = GossipNodeSet(
            host=cfg.host,
            seed=cfg.cluster.gossip_seed,
            logger=logger,
            stats=stats,
            ack_timeout=cfg.gossip.ack_timeout_ms / 1000.0,
            stream_timeout=cfg.gossip.stream_timeout_ms / 1000.0,
        )
        broadcaster = nodeset
        receiver = nodeset
        cluster.node_set = nodeset

    return Server(
        data_dir=os.path.expanduser(cfg.data_dir),
        host=cfg.host,
        cluster=cluster,
        broadcaster=broadcaster,
        broadcast_receiver=receiver,
        anti_entropy_interval=cfg.anti_entropy_interval,
        polling_interval=cfg.cluster.polling_interval,
        max_writes_per_request=cfg.max_writes_per_request,
        logger=logger,
        stats=stats,
        compilation_cache_dir=_resolve_cache_dir(cfg),
        prewarm=cfg.tpu.prewarm,
        stream_chunk_bytes=cfg.net.stream_chunk_bytes,
        slow_query_ms=cfg.obs.slow_query_ms,
        trace_ring=cfg.obs.trace_ring,
        latency_buckets_ms=(cfg.obs.latency_buckets_ms or None),
        slo_ms=cfg.obs.slo_ms,
        slo_objective=cfg.obs.slo_objective,
        floor_probe=cfg.obs.floor_probe,
        mesh_devices=cfg.device.mesh_devices,
        hbm_budget_bytes=cfg.device.hbm_budget_bytes,
        device_prefetch=cfg.device.prefetch,
        device_stage=cfg.device.stage,
        stage_throttle_ms=cfg.device.stage_throttle_ms,
        launch_watchdog_ms=cfg.device.launch_watchdog_ms,
        quarantine_threshold=cfg.device.quarantine_threshold,
        quarantine_open_ms=cfg.device.quarantine_open_ms,
        quarantine_probe_successes=cfg.device.quarantine_probe_successes,
        plane_format=cfg.device.plane_format,
        plane_sparse_max_bytes=cfg.device.plane_sparse_max_bytes,
        plane_rle_max_bytes=cfg.device.plane_rle_max_bytes,
        coalesce=cfg.exec.coalesce,
        coalesce_max_batch=cfg.exec.coalesce_max_batch,
        coalesce_max_wait_us=cfg.exec.coalesce_max_wait_us,
        fuse=cfg.exec.fuse,
        fuse_max_programs=cfg.exec.fuse_max_programs,
        query_timeout_ms=cfg.net.query_timeout_ms,
        broadcast_timeout_ms=cfg.net.broadcast_timeout_ms,
        retry_attempts=cfg.net.retry_attempts,
        retry_backoff_ms=cfg.net.retry_backoff_ms,
        breaker_failure_threshold=cfg.net.breaker_failure_threshold,
        breaker_open_ms=cfg.net.breaker_open_ms,
        admission=cfg.net.admission,
        admission_point_concurrency=cfg.net.admission_point_concurrency,
        admission_heavy_concurrency=cfg.net.admission_heavy_concurrency,
        admission_write_concurrency=cfg.net.admission_write_concurrency,
        admission_internal_concurrency=cfg.net.admission_internal_concurrency,
        admission_queue_depth=cfg.net.admission_queue_depth,
        admission_subscribe_concurrency=cfg.net.admission_subscribe_concurrency,
        tenants=cfg.net.tenants,
        tenant_keys=cfg.net.tenant_keys,
        tenant_default=cfg.net.tenant_default,
        tenant_internal_token=cfg.net.tenant_internal_token,
        rebalance_throttle_mbps=cfg.cluster.rebalance_throttle_mbps,
        rebalance_verify_rounds=cfg.cluster.rebalance_verify_rounds,
        rebalance_delta_cap=cfg.cluster.rebalance_delta_cap,
        rebalance_release_delay_ms=cfg.cluster.rebalance_release_delay_ms,
        rebalance_on_join=cfg.cluster.rebalance_on_join,
        write_consistency=cfg.cluster.write_consistency,
        read_consistency=cfg.cluster.read_consistency,
        hint_cap=cfg.cluster.hint_cap,
        hint_replay_throttle_mbps=cfg.cluster.hint_replay_throttle_mbps,
        tier_store=cfg.tier.store,
        tier_hydrate_throttle_mbps=cfg.tier.hydrate_throttle_mbps,
        tier_disk_budget_bytes=cfg.tier.disk_budget_bytes,
        tier_retention_age_s=cfg.tier.retention_age_s,
        tier_retention_delete_s=cfg.tier.retention_delete_s,
        tier_sweep_interval_s=cfg.tier.sweep_interval_s,
        subscribe_enabled=cfg.subscribe.enabled,
        subscribe_max_subscriptions=cfg.subscribe.max_subscriptions,
        subscribe_queue_cap=cfg.subscribe.queue_cap,
        subscribe_delta_cap=cfg.subscribe.delta_cap,
        subscribe_coalesce_ms=cfg.subscribe.coalesce_ms,
        subscribe_refresh_ms=cfg.subscribe.refresh_interval_ms,
        ingest_wal=cfg.ingest.wal,
        ingest_group_commit_ms=cfg.ingest.group_commit_ms,
        ingest_group_commit_max=cfg.ingest.group_commit_max,
        ingest_scatter=cfg.ingest.scatter,
        ingest_wal_segment_bytes=cfg.ingest.wal_segment_bytes,
    )


def _resolve_cache_dir(cfg) -> str | None:
    """tpu.compilation-cache-dir: "" -> <data-dir>/.jax-compile-cache,
    "off" -> disabled, else the given path."""
    raw = cfg.tpu.compilation_cache_dir
    if raw == "off":
        return None
    if raw:
        return os.path.expanduser(raw)
    return os.path.join(os.path.expanduser(cfg.data_dir), ".jax-compile-cache")


def run_server(args) -> int:
    overrides = {}
    if args.data_dir:
        overrides["data_dir"] = args.data_dir
    if args.bind:
        overrides["host"] = args.bind
    cfg = config_mod.load(args.config or None, overrides=overrides)
    server = build_server(cfg)
    if args.dry_run:
        print("dry-run: config ok", file=sys.stderr)
        return 0
    # Join a multi-host JAX process group when the launcher configured
    # one (JAX_COORDINATOR_ADDRESS etc.); after the dry-run exit — the
    # coordinator barrier blocks until all peers connect.
    from pilosa_tpu.parallel import multihost

    multihost.initialize()
    server.open()
    print(f"listening on http://{server.host}", file=sys.stderr)
    stop_profile = _start_cpu_profile(
        getattr(args, "cpuprofile", ""), getattr(args, "cputime", 30)
    )
    # SIGTERM must run the shutdown path (close listeners, flush caches,
    # finalize --cpuprofile), not hard-kill the process.
    import signal

    def _on_term(_sig, _frame):
        raise SystemExit(0)

    signal.signal(signal.SIGTERM, _on_term)
    try:
        while True:
            time.sleep(3600)
    except (KeyboardInterrupt, SystemExit):
        pass
    finally:
        # A second TERM during cleanup must not abort server.close();
        # restore the default disposition so it hard-kills instead of
        # raising mid-finally.
        signal.signal(signal.SIGTERM, signal.SIG_DFL)
        stop_profile()
        server.close()
    return 0


def _start_cpu_profile(path: str, seconds: int):
    """Server-side CPU profiling flags (reference: server/server.go:56-57
    cpuprofile/cputime): run the same folded-stack sampler the
    /debug/pprof/profile endpoint uses, in a daemon thread, writing to
    ``path`` when sampling ends (the --cputime deadline, or shutdown for
    ``seconds == 0``).  Returns a callable that finalizes the file (a
    no-op when profiling is off)."""
    if not path:
        return lambda: None
    import threading

    from pilosa_tpu.net import handler as _handler

    stop = threading.Event()
    # Shared with the sampler thread, which accumulates in place — the
    # stop path can write a snapshot even if the thread is wedged.
    counts: dict[str, int] = {}

    def _write() -> None:
        # dict(counts) is a single C-level copy under the GIL, safe even
        # if the sampler thread is still inserting keys.
        with open(path, "w") as f:
            f.write(_handler._fold_counts(dict(counts)))
        print(f"cpu profile written to {path}", file=sys.stderr)

    def _run() -> None:
        if seconds > 0:
            _handler._sample_cpu_counts(seconds, stop=stop, counts=counts)
        else:
            # "until shutdown", literally: re-arm in bounded legs.
            while not stop.is_set():
                _handler._sample_cpu_counts(3600, stop=stop, counts=counts)
        _write()

    t = threading.Thread(target=_run, daemon=True, name="cpuprofile")
    t.start()

    def _stop() -> None:
        stop.set()
        t.join(timeout=30)
        if t.is_alive():
            print(
                "warning: cpu profiler did not stop; writing snapshot",
                file=sys.stderr,
            )
            try:
                _write()
            except OSError as e:  # never abort the shutdown path
                print(f"warning: cpu profile write failed: {e}", file=sys.stderr)

    return _stop


def run_warm(args) -> int:
    """Offline compile warm-up: populate the persistent XLA compile
    cache with the standard query-shape programs AND the coalescer's
    power-of-two bucket shapes, so a subsequently started server (or
    the next process on this machine) answers its first queries — and
    its first coalesced batches — without a multi-second cold compile.
    Honors the config's `[tpu] compilation-cache-dir` resolution; the
    warm is wasted (in-process only) when the cache is disabled, which
    is reported."""
    from pilosa_tpu.exec import warmup

    cfg = config_mod.load(args.config or None)
    cache_dir = _resolve_cache_dir(cfg)
    if cache_dir and warmup.enable_compile_cache(cache_dir):
        print(f"compilation cache: {warmup.enabled_cache_dir()}", file=sys.stderr)
    else:
        print(
            "warning: persistent compile cache disabled; warming only "
            "this process's in-memory jit cache",
            file=sys.stderr,
        )
    t0 = time.monotonic()
    n = warmup.prewarm(coalesce=cfg.exec.coalesce)
    if not cfg.exec.coalesce:
        print(
            "note: [exec] coalesce is off; coalescer buckets not warmed",
            file=sys.stderr,
        )
    print(
        f"warmed {n} query programs in {time.monotonic() - t0:.1f}s",
        file=sys.stderr,
    )
    return 0


# ---------------------------------------------------------------------------
# import (reference: ctl/import.go:30-195)
# ---------------------------------------------------------------------------


def run_import(args) -> int:
    client = _client(args.host)
    for path in args.paths:
        if getattr(args, "value", ""):
            _import_value_path(client, args, path)
        else:
            _import_path(client, args, path)
    return 0


def _import_value_path(client, args, path: str) -> None:
    """``--value FIELD``: CSV records are ``column,value`` (signed
    integers), imported columnar into a BSI field via /import-value."""
    if path == "-":
        _import_value_reader(client, args, sys.stdin)
        return
    with open(path, newline="") as f:
        _import_value_reader(client, args, f)


def _import_value_reader(client, args, f) -> None:
    buf: list[tuple[int, int]] = []
    for rnum, record in enumerate(csv.reader(f), start=1):
        if not record or record[0] == "":
            continue
        if len(record) < 2:
            raise CommandError(f"bad column count on row {rnum}")
        try:
            col_id = int(record[0])
        except ValueError:
            raise CommandError(f"invalid column id on row {rnum}: {record[0]!r}") from None
        try:
            value = int(record[1])
        except ValueError:
            raise CommandError(f"invalid value on row {rnum}: {record[1]!r}") from None
        buf.append((col_id, value))
        if len(buf) >= args.buffer_size:
            _flush_values(client, args, buf)
            buf.clear()
    _flush_values(client, args, buf)


def _flush_values(client, args, pairs: list[tuple[int, int]]) -> None:
    if not pairs:
        return
    by_slice: dict[int, list] = {}
    for col, val in pairs:
        by_slice.setdefault(col // SLICE_WIDTH, []).append((col, val))
    for slice_i in sorted(by_slice):
        group = by_slice[slice_i]
        print(
            f"importing values: slice={slice_i}, n={len(group)}",
            file=sys.stderr,
        )
        client.import_value(
            args.index,
            args.frame,
            args.value,
            slice_i,
            [c for c, _ in group],
            [v for _, v in group],
            consistency=getattr(args, "consistency", "quorum"),
        )


# Native CSV fast path reads the file in blocks of this many bytes, so
# memory stays bounded regardless of file size.
_CSV_BLOCK = 64 << 20


def _import_path(client, args, path: str) -> None:
    if path == "-":
        _import_reader(client, args, sys.stdin)
        return
    # Fast path: the native CSV parser handles plain "row,col" files,
    # streamed block-by-block (split at the last newline); anything it
    # can't parse (timestamps, quoting) falls back to Python csv.  A
    # fallback after a partially imported file is safe: imports are
    # idempotent bit-sets, so re-importing earlier records is a no-op.
    if _import_native(client, args, path):
        return
    with open(path, newline="") as f:
        _import_reader(client, args, f)


def _import_native(client, args, path: str) -> bool:
    from pilosa_tpu import native

    if not native.available():
        return False
    with open(path, "rb") as fb:
        carry = b""
        while True:
            block = fb.read(_CSV_BLOCK)
            if not block:
                break
            block = carry + block
            cut = block.rfind(b"\n") + 1
            if cut == 0:
                carry, block = b"", block  # no newline: final partial line
            else:
                carry, block = block[cut:], block[:cut]
            if not _import_parsed_block(client, args, block):
                return False
        if carry and not _import_parsed_block(client, args, carry):
            return False
    return True


def _import_parsed_block(client, args, block: bytes) -> bool:
    from pilosa_tpu import native

    if not block:
        return True
    parsed = native.parse_csv(block)
    if parsed is None:
        return False
    rows, cols = parsed
    import numpy as np

    from pilosa_tpu.ops.bitplane import np_group_by

    # Fully vectorized: one stable sort groups by slice (no per-bit
    # Python objects, no per-slice full-array rescans), shipped to the
    # client in buffer_size chunks so request payloads stay bounded.
    slices = cols // np.uint64(SLICE_WIDTH)
    for s, (r_s, c_s) in np_group_by(slices, rows, cols):
        print(f"importing slice: {s}, n={len(r_s)}", file=sys.stderr)
        for lo in range(0, len(r_s), args.buffer_size):
            client.import_bits(
                args.index,
                args.frame,
                s,
                (r_s[lo : lo + args.buffer_size], c_s[lo : lo + args.buffer_size]),
                consistency=getattr(args, "consistency", "quorum"),
            )
    return True


def _import_reader(client, args, f) -> None:
    buf: list[tuple[int, int, int]] = []
    for rnum, record in enumerate(csv.reader(f), start=1):
        if not record or record[0] == "":
            continue
        if len(record) < 2:
            raise CommandError(f"bad column count on row {rnum}")
        try:
            row_id = int(record[0])
        except ValueError:
            raise CommandError(f"invalid row id on row {rnum}: {record[0]!r}") from None
        try:
            col_id = int(record[1])
        except ValueError:
            raise CommandError(f"invalid column id on row {rnum}: {record[1]!r}") from None
        ts = 0
        if len(record) > 2 and record[2]:
            try:
                dt = datetime.strptime(record[2], TIME_FORMAT)
            except ValueError:
                raise CommandError(
                    f"invalid timestamp on row {rnum}: {record[2]!r}"
                ) from None
            # wire carries unix nanoseconds (reference: ctl/import.go:157)
            ts = int(dt.replace(tzinfo=timezone.utc).timestamp() * 1e9)
        buf.append((row_id, col_id, ts))
        if len(buf) >= args.buffer_size:
            _flush_bits(client, args, buf)
            buf.clear()
    _flush_bits(client, args, buf)


def _flush_bits(client, args, bits: list[tuple[int, int, int]]) -> None:
    if not bits:
        return
    by_slice: dict[int, list] = {}
    for b in bits:
        by_slice.setdefault(b[1] // SLICE_WIDTH, []).append(b)
    for slice_i in sorted(by_slice):
        print(
            f"importing slice: {slice_i}, n={len(by_slice[slice_i])}",
            file=sys.stderr,
        )
        client.import_bits(
            args.index,
            args.frame,
            slice_i,
            by_slice[slice_i],
            consistency=getattr(args, "consistency", "quorum"),
        )


# ---------------------------------------------------------------------------
# export / backup / restore (reference: ctl/export.go, backup.go, restore.go)
# ---------------------------------------------------------------------------


def run_export(args) -> int:
    client = _client(args.host)
    w = _out(args)
    try:
        max_slices = client.max_slice_by_index()
        for slice_i in range(max_slices.get(args.index, 0) + 1):
            # Chunked end to end: the server streams csv_chunks and
            # export_to copies constant-size chunks straight into the
            # output file — no slice is ever held whole.
            client.export_to(w, args.index, args.frame, args.view, slice_i)
    finally:
        if w is not sys.stdout.buffer:
            w.close()
    return 0


def run_backup(args) -> int:
    client = _client(args.host)
    if getattr(args, "store", ""):
        return _backup_to_store(client, args)
    if not args.frame:
        raise CommandError("--frame required (unless backing up --store)")
    w = _out(args)
    try:
        client.backup_to(w, args.index, args.frame, args.view)
    finally:
        if w is not sys.stdout.buffer:
            w.close()
    return 0


def _backup_to_store(client, args) -> int:
    """``backup --store URL``: archive the server's schema plus every
    fragment tar of the view into the object store (the tier layout —
    ``schema.json`` + ``fragments/<index>/<frame>/<view>/<slice>.tar``)
    so a node with only ``[tier] store`` configured cold-boots the
    index from the store alone."""
    import json as _json

    from pilosa_tpu.tier import fragment_store_key, open_store
    from pilosa_tpu.tier.manager import SCHEMA_KEY

    store = open_store(args.store)
    if store is None:
        raise CommandError("--store must name a store location")
    schema = client.schema()
    store.put(SCHEMA_KEY, _json.dumps({"indexes": schema}).encode())
    frames = (
        [args.frame]
        if args.frame
        else [
            f["name"]
            for idx in schema
            if idx["name"] == args.index
            for f in idx.get("frames", [])
        ]
    )
    n = 0
    for frame in frames:
        views = (
            [args.view] if args.view else client.frame_views(args.index, frame)
        )
        for view in views:
            max_slices = client.max_slice_by_index(
                inverse=view.startswith("inverse")
            )
            for slice_i in range(max_slices.get(args.index, 0) + 1):
                payload = client.backup_slice(args.index, frame, view, slice_i)
                if payload is None:
                    continue
                store.put(
                    fragment_store_key(args.index, frame, view, slice_i),
                    payload,
                )
                n += 1
    print(f"backed up {n} fragment(s) to {store.url}", file=sys.stderr)
    return 0


def run_restore(args) -> int:
    client = _client(args.host)
    if getattr(args, "store", ""):
        return _restore_from_store(client, args)
    if not args.input_file:
        raise CommandError("--input-file (or --store) required")
    if not args.frame:
        raise CommandError("--frame required (unless restoring --store)")
    with open(args.input_file, "rb") as r:
        client.restore_from(r, args.index, args.frame, args.view)
    return 0


def _restore_from_store(client, args) -> int:
    """``restore --store URL``: push every matching fragment tar from
    the object store into the server (its restore endpoint verifies
    the tar's embedded checksums before installing)."""
    import io as _io

    from pilosa_tpu.tier import open_store, parse_fragment_store_key
    from pilosa_tpu.tier.manager import FRAGMENT_PREFIX

    store = open_store(args.store)
    if store is None:
        raise CommandError("--store must name a store location")
    prefix = f"{FRAGMENT_PREFIX}{args.index}/"
    if args.frame:
        prefix += f"{args.frame}/"
        if args.view:
            prefix += f"{args.view}/"
    n = 0
    for meta in store.list(prefix):
        parsed = parse_fragment_store_key(meta.key)
        if parsed is None:
            continue
        index, frame, view, slice_i = parsed
        client.restore_slice_from(
            index, frame, view, slice_i, _io.BytesIO(store.get(meta.key))
        )
        n += 1
    if n == 0:
        raise CommandError(f"store holds no fragments under {prefix!r}")
    print(f"restored {n} fragment(s) from {store.url}", file=sys.stderr)
    return 0


# ---------------------------------------------------------------------------
# check / inspect (reference: ctl/check.go:46-125, ctl/inspect.go)
# ---------------------------------------------------------------------------


def _map_or_read(f):
    """mmap a data file for O(file) checks without heap-copying it
    (reference: ctl/check.go mmaps before roaring.Check); empty files
    (not mmap-able) read as bytes."""
    import mmap as _mmap

    try:
        return _mmap.mmap(f.fileno(), 0, access=_mmap.ACCESS_READ)
    except (ValueError, OSError):
        return f.read()


def run_check(args) -> int:
    """Offline consistency check of roaring data files; skips .cache and
    .snapshotting files like the reference."""
    ok = True
    for path in args.paths:
        if path.endswith(".cache") or path.endswith(".snapshotting"):
            print(f"skipping: {path}", file=sys.stderr)
            continue
        with open(path, "rb") as f:
            data = _map_or_read(f)
        try:
            problems = roaring.check(data)
        except roaring.CorruptError as e:
            problems = [str(e)]
        if problems:
            ok = False
            for p in problems:
                print(f"{path}: {p}")
        else:
            print(f"{path}: ok", file=sys.stderr)
    return 0 if ok else 1


def run_inspect(args) -> int:
    for path in args.paths:
        with open(path, "rb") as f:
            data = _map_or_read(f)
        bi = roaring.info(data)
        print(f"{path}:")
        print(f"  containers: {len(bi.containers)}")
        print(f"  bits: {sum(c.n for c in bi.containers)}")
        print(f"  ops: {bi.ops}")
        for c in bi.containers:
            print(f"  container key={c.key} type={c.type} n={c.n}")
    return 0


# ---------------------------------------------------------------------------
# bench (reference: ctl/bench.go:52-102)
# ---------------------------------------------------------------------------


def run_bench(args) -> int:
    import random

    client = _client(args.host)
    if args.operation == "set-bit":
        n = args.num
        if n <= 0:
            raise CommandError("--num must be > 0")
        # Mirror of the reference's random set-bit workload
        # (reference: ctl/bench.go:70-102): rowID in [0,1000), columnID in
        # [0,100000).
        t0 = time.monotonic()
        batch = []
        for _ in range(n):
            row = random.randrange(1000)
            col = random.randrange(100000)
            batch.append(f'SetBit(frame="{args.frame}", rowID={row}, columnID={col})')
            if len(batch) == 1000:
                client.execute_query(args.index, "\n".join(batch))
                batch.clear()
        if batch:
            client.execute_query(args.index, "\n".join(batch))
        elapsed = time.monotonic() - t0
        print(f"executed {n} operations in {elapsed:.3f}s ({n / elapsed:.0f} op/sec)")
        return 0

    # Read-query benches over EXISTING data (BASELINE.json configs[1-2]):
    # p50/p95 over --num iterations (default 20) of one PQL query.
    if args.operation == "intersect-count":
        pql = (
            f'Count(Intersect(Bitmap(frame="{args.frame}", rowID={args.row1}),'
            f' Bitmap(frame="{args.frame}", rowID={args.row2})))'
        )
    else:  # topn
        pql = f'TopN(frame="{args.frame}", n={args.topn_n})'
    iters = args.num if args.num > 0 else 20
    result = client.execute_pql(args.index, pql)  # warm (compile/caches)
    lat = []
    for _ in range(iters):
        t0 = time.monotonic()
        result = client.execute_pql(args.index, pql)
        lat.append(time.monotonic() - t0)
    lat.sort()
    p50 = lat[len(lat) // 2]
    p95 = lat[min(len(lat) - 1, int(len(lat) * 0.95))]
    shown = result if isinstance(result, int) else f"{len(result)} pairs"
    print(
        f"{args.operation}: {iters} queries, p50 {p50*1e3:.2f} ms,"
        f" p95 {p95*1e3:.2f} ms (result: {shown})"
    )
    return 0


# ---------------------------------------------------------------------------
# resize — live cluster grow/drain (pilosa_tpu/rebalance)
# ---------------------------------------------------------------------------


def run_resize(args) -> int:
    """Drive a live topology change: POST /cluster/resize with the
    complete target host list (grow = current + joiners, drain =
    current - leavers), then optionally poll /debug/rebalance until the
    background migration completes."""
    import json as _json

    client = _client(args.host)

    def status() -> dict:
        st, data = client._request("GET", "/debug/rebalance")
        return _json.loads(client._check(st, data))

    if args.status:
        print(_json.dumps(status(), indent=2, sort_keys=True))
        return 0
    if args.abort:
        st, data = client._request("POST", "/cluster/resize/abort")
        client._check(st, data)
        print("resize aborted", file=sys.stderr)
        return 0
    hosts = [h.strip() for h in (args.hosts or "").split(",") if h.strip()]
    if not hosts:
        raise CommandError("--hosts required (the complete target host list)")
    st, data = client._request(
        "POST", "/cluster/resize", body=_json.dumps({"hosts": hosts}).encode()
    )
    client._check(st, data)
    print(f"resize to {hosts} started", file=sys.stderr)
    if not args.wait:
        print("poll with: pilosa-tpu resize --status", file=sys.stderr)
        return 0
    while True:
        snap = status()
        if not snap.get("running"):
            coord = snap.get("coordinator") or {}
            if coord.get("error") or snap.get("lastError"):
                raise CommandError(
                    f"migration stopped: {coord.get('error') or snap['lastError']}"
                )
            if snap.get("transition") is None:
                print("resize complete", file=sys.stderr)
                return 0
        states = (snap.get("coordinator") or {}).get("sliceStates", {})
        print(f"migrating: {states}", file=sys.stderr)
        time.sleep(1.0)


# ---------------------------------------------------------------------------
# sort (reference: ctl/sort.go)
# ---------------------------------------------------------------------------


def run_sort(args) -> int:
    if args.path == "-":
        rows = list(csv.reader(sys.stdin))
    else:
        with open(args.path, newline="") as f:
            rows = list(csv.reader(f))
    rows = [r for r in rows if r and r[0] != ""]
    try:
        rows.sort(key=lambda r: (int(r[1]) // SLICE_WIDTH, int(r[0]), int(r[1])))
    except (ValueError, IndexError) as e:
        raise CommandError(f"bad csv row: {e}") from e
    w = csv.writer(sys.stdout)
    w.writerows(rows)
    return 0


# ---------------------------------------------------------------------------
# config / generate-config (reference: ctl/config.go, generate_config.go)
# ---------------------------------------------------------------------------


def run_config(args) -> int:
    cfg = config_mod.load(args.config or None)
    sys.stdout.write(cfg.to_toml())
    return 0


def run_generate_config(args) -> int:
    sys.stdout.write(config_mod.Config().to_toml())
    return 0
