"""CLI entry point — argparse subcommands over ctl command logic.

Flag names and defaults mirror the reference (reference: cmd/backup.go:
44-49, cmd/bench.go:44-49, cmd/export.go:51-57, cmd/import.go:52-56,
cmd/restore.go:45-50, cmd/root.go:65-67); command logic lives in
pilosa_tpu/cli/ctl.py the way the reference splits cmd/ from ctl/.
"""

from __future__ import annotations

import argparse
import sys

from pilosa_tpu import __version__
from pilosa_tpu.cli import ctl


def _add_host(p: argparse.ArgumentParser) -> None:
    p.add_argument(
        "--host", default="localhost:10101", help="host:port of the server"
    )


def build_parser() -> argparse.ArgumentParser:
    root = argparse.ArgumentParser(
        prog="pilosa-tpu",
        description="TPU-native distributed bitmap index",
    )
    root.add_argument("--version", action="version", version=__version__)
    sub = root.add_subparsers(dest="command", required=True)

    p = sub.add_parser("server", help="run a node daemon")
    p.add_argument("-c", "--config", default="", help="TOML config file")
    p.add_argument("-d", "--data-dir", default=None, help="data directory")
    p.add_argument("--bind", default=None, help="host:port to bind (overrides config host)")
    p.add_argument("--dry-run", action="store_true", help="stop before serving")
    p.add_argument(
        "--cpuprofile", default="", metavar="PATH",
        help="write a folded-stack CPU profile of the first --cputime "
        "seconds to PATH",
    )
    p.add_argument(
        "--cputime", type=int, default=30, metavar="SECONDS",
        help="with --cpuprofile: sampling duration (0 = until shutdown)",
    )
    p.set_defaults(fn=ctl.run_server)

    p = sub.add_parser(
        "warm",
        help="pre-compile standard + coalescer query programs into the "
        "persistent compile cache",
    )
    p.add_argument("-c", "--config", default="", help="TOML config file")
    p.set_defaults(fn=ctl.run_warm)

    p = sub.add_parser(
        "import",
        help="bulk-import CSV bits (row,col[,ts]);"
        " with --value FIELD, integer values (col,value)",
    )
    p.add_argument(
        "--value",
        default="",
        metavar="FIELD",
        help="import integer values (col,value CSV) into this BSI field",
    )
    _add_host(p)
    p.add_argument("-i", "--index", required=True)
    p.add_argument("-f", "--frame", required=True)
    p.add_argument(
        "-s", "--buffer-size", type=int, default=10_000_000,
        help="bits to buffer/sort before importing",
    )
    p.add_argument(
        "--consistency",
        default="quorum",
        choices=("one", "quorum", "all"),
        help="replica acks required per slice payload (W-of-N; "
        "unreachable replicas get hinted handoff)",
    )
    p.add_argument("paths", nargs="+", help="CSV files ('-' = stdin)")
    p.set_defaults(fn=ctl.run_import)

    p = sub.add_parser("export", help="export a frame as CSV")
    _add_host(p)
    p.add_argument("-i", "--index", required=True)
    p.add_argument("-f", "--frame", required=True)
    p.add_argument("-v", "--view", default="standard")
    p.add_argument("-o", "--output-file", default="", help="default stdout")
    p.set_defaults(fn=ctl.run_export)

    p = sub.add_parser(
        "backup",
        help="backup a view to a tar archive, or the whole index into "
        "a tier object store (--store)",
    )
    _add_host(p)
    p.add_argument("-i", "--index", required=True)
    p.add_argument(
        "-f", "--frame", default="",
        help="frame to back up (with --store: default = every frame)",
    )
    p.add_argument(
        "-v", "--view", default="standard",
        help="view to back up; with --store pass '' for every view",
    )
    p.add_argument("-o", "--output-file", default="", help="default stdout")
    p.add_argument(
        "--store", default="", metavar="URL",
        help="tier object-store target (http://host:port, file:///path, "
        "or a bare path): uploads schema.json + per-fragment tars in "
        "the [tier] store layout",
    )
    p.set_defaults(fn=ctl.run_backup)

    p = sub.add_parser(
        "restore",
        help="restore a view from a tar archive, or fragments from a "
        "tier object store (--store)",
    )
    _add_host(p)
    p.add_argument("-i", "--index", required=True)
    p.add_argument(
        "-f", "--frame", default="",
        help="frame to restore (with --store: default = every frame)",
    )
    p.add_argument(
        "-v", "--view", default="standard",
        help="view to restore; with --store pass '' for every view",
    )
    p.add_argument("-d", "--input-file", default="")
    p.add_argument(
        "--store", default="", metavar="URL",
        help="tier object-store source (see backup --store)",
    )
    p.set_defaults(fn=ctl.run_restore)

    p = sub.add_parser("check", help="offline consistency check of data files")
    p.add_argument("paths", nargs="+")
    p.set_defaults(fn=ctl.run_check)

    p = sub.add_parser("inspect", help="dump container stats of a data file")
    p.add_argument("paths", nargs="+")
    p.set_defaults(fn=ctl.run_inspect)

    p = sub.add_parser("bench", help="benchmark operations against a server")
    _add_host(p)
    p.add_argument("-i", "--index", required=True)
    p.add_argument("-f", "--frame", required=True)
    p.add_argument(
        "-o",
        "--operation",
        default="set-bit",
        choices=["set-bit", "intersect-count", "topn"],
        help="set-bit: random writes (reference parity, ctl/bench.go);"
        " intersect-count / topn: the BASELINE.json query configs"
        " against existing data",
    )
    p.add_argument("-n", "--num", type=int, default=0, help="operations to run")
    p.add_argument("--row1", type=int, default=1, help="intersect-count row A")
    p.add_argument("--row2", type=int, default=2, help="intersect-count row B")
    p.add_argument("--topn-n", type=int, default=100, help="topn result size")
    p.set_defaults(fn=ctl.run_bench)

    p = sub.add_parser(
        "resize",
        help="live cluster resize: grow/drain the ring with background "
        "slice migration (--hosts = the COMPLETE target host list)",
    )
    _add_host(p)
    p.add_argument(
        "--hosts",
        default="",
        help="comma-separated target host list (omit with --status/--abort)",
    )
    p.add_argument(
        "--abort", action="store_true",
        help="abort the in-flight resize (reverse-migrates flipped slices)",
    )
    p.add_argument(
        "--status", action="store_true",
        help="print the /debug/rebalance migration status and exit",
    )
    p.add_argument(
        "--wait", action="store_true",
        help="block until the migration completes (polls /debug/rebalance)",
    )
    p.set_defaults(fn=ctl.run_resize)

    p = sub.add_parser("sort", help="sort a CSV file by slice for import")
    p.add_argument("path", help="CSV file ('-' = stdin)")
    p.set_defaults(fn=ctl.run_sort)

    p = sub.add_parser("config", help="validate and print a config file")
    p.add_argument("-c", "--config", default="", help="TOML config file")
    p.set_defaults(fn=ctl.run_config)

    p = sub.add_parser("generate-config", help="print the default config")
    p.set_defaults(fn=ctl.run_generate_config)

    return root


def main(argv: list[str] | None = None) -> int:
    from pilosa_tpu.config import ConfigError
    from pilosa_tpu.net.client import ClientError

    args = build_parser().parse_args(argv)
    try:
        return args.fn(args) or 0
    except (ctl.CommandError, ConfigError, ClientError, OSError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    except KeyboardInterrupt:
        return 130
