"""Cluster resilience primitives: deadlines, retries, circuit breakers.

The distributed read path (executor map/reduce + InternalClient) used to
ride flat timeouts: a 30 s socket timeout per unary RPC, zero retries,
and nothing that remembered a host was down — one flapping node made
every fan-out burn a full timeout before failover.  This module supplies
the three mechanisms the rest of ``net/`` and ``exec/`` compose:

* **Deadlines.**  A query carries one absolute deadline (``[net]
  query-timeout-ms``, overridable per request via the ``X-Deadline-Ms``
  header).  The deadline lives in a ``contextvars.ContextVar`` — the
  executor's pool already copies the submitting context into workers, so
  every remote leg, retry sleep, and coalesce wait derives its timeout
  from the REMAINING budget.  Each outbound RPC re-exports the remaining
  milliseconds as ``X-Deadline-Ms`` so the peer inherits the budget
  (measured at send time; network delay grants the peer slack rather
  than double-charging it).  An expired deadline raises
  :class:`DeadlineExceeded`, which the handler maps to HTTP 504.

* **Retries.**  :class:`RetryPolicy` is capped jittered-exponential
  backoff over transport failures (the policy shape of
  ``stream/client.py:open_with_retry``): transient dial/read errors on
  IDEMPOTENT calls get ``attempts`` tries; a retry never sleeps past the
  deadline; writes stay single-shot unless explicitly marked idempotent.

* **Circuit breakers.**  One :class:`CircuitBreaker` per remote host
  (closed → open after ``failure_threshold`` consecutive transport
  failures → half-open probe every ``open_s`` → closed on probe
  success).  While open, calls fail in microseconds with
  :class:`BreakerOpenError` — the executor's failover then skips
  straight to replicas instead of burning a timeout per query.  State is
  surfaced at ``GET /debug/health`` and as ``net.breaker.*`` counters.
"""

from __future__ import annotations

import contextvars
import http.client
import random
import threading
import time
from collections.abc import Callable
from contextlib import contextmanager
from typing import Any

# Header carrying the REMAINING deadline budget in milliseconds at send
# time.  The receiver restarts the clock on receipt.
DEADLINE_HEADER = "X-Deadline-Ms"

# Transient transport failures worth a retry and worth counting against
# a host's breaker; HTTP-status errors mean the server answered and are
# judged separately (see is_node_failure).  Same shape as
# stream/client.py RETRYABLE.
TRANSPORT_ERRORS = (OSError, http.client.HTTPException)


class DeadlineExceeded(RuntimeError):
    """The query's deadline expired.  The HTTP handler maps this to 504
    (with the trace id); it must never be swallowed into replica
    failover — an exhausted budget fails the query, not the node."""

    def __init__(self, message: str = "deadline exceeded"):
        super().__init__(message)


class ShedError(RuntimeError):
    """The request was shed by admission control (HTTP 429): the server
    is healthy but at capacity, and predicted queue wait would not fit
    the request's remaining deadline budget — so it answered before
    burning any executor/coalescer/device work.

    Carries the server's ``Retry-After`` hint in seconds.  A shed must
    NOT count against the host's circuit breaker (the node answered,
    quickly and deliberately), but IS a node failure for the purposes
    of replica failover: another replica may have capacity right now.
    """

    status = 429

    def __init__(
        self,
        message: str = "request shed",
        retry_after_s: float = 1.0,
        host: str = "",
        cost_class: str = "",
    ):
        super().__init__(message)
        self.retry_after_s = max(float(retry_after_s), 0.0)
        self.host = host
        self.cost_class = cost_class


class BreakerOpenError(RuntimeError):
    """Fast-fail for a host whose circuit breaker is open.  Deliberately
    NOT a transport error: retrying against an open breaker is pointless
    (it would fail just as fast), but the executor's failover treats it
    as a node failure — which is the point."""

    def __init__(self, host: str):
        super().__init__(f"circuit breaker open for {host}")
        self.host = host


def is_node_failure(exc: BaseException) -> bool:
    """Whether an error from a remote leg indicts the NODE (transport
    failure, open breaker, or a 5xx answer) — eligible for replica
    failover and, under ``allow_partial``, for dropping the slice —
    as opposed to a semantic error that would fail identically
    everywhere."""
    if isinstance(exc, BreakerOpenError):
        return True
    if isinstance(exc, DeadlineExceeded):
        return False
    # A shed leg (429) indicts the node only in the failover sense:
    # this replica is at capacity, another may not be.  It never counts
    # against the breaker (see InternalClient._attempt).
    if isinstance(exc, ShedError):
        return True
    if isinstance(exc, TRANSPORT_ERRORS):
        return True
    status = getattr(exc, "status", None)
    return isinstance(status, int) and status >= 500


# ---------------------------------------------------------------------------
# deadlines
# ---------------------------------------------------------------------------


class Deadline:
    """An absolute point on the monotonic clock.  Cheap value object —
    every remote leg reads it, so no locks, no allocation beyond the
    float."""

    __slots__ = ("_at",)

    def __init__(self, at: float):
        self._at = at

    @classmethod
    def after_ms(cls, ms: float) -> "Deadline":
        return cls(time.monotonic() + ms / 1000.0)

    @classmethod
    def from_header(cls, value: str) -> "Deadline | None":
        """Parse an ``X-Deadline-Ms`` header value; None when absent or
        malformed (a garbage header must not 500 the request)."""
        if not value:
            return None
        try:
            return cls.after_ms(float(value))
        except (TypeError, ValueError):
            return None

    def remaining(self) -> float:
        """Seconds of budget left (negative when expired)."""
        return self._at - time.monotonic()

    def remaining_ms(self) -> float:
        return self.remaining() * 1000.0

    @property
    def expired(self) -> bool:
        return self.remaining() <= 0

    def clamp(self, timeout: float) -> float:
        """``timeout`` bounded by the remaining budget (never below 0)."""
        return max(min(timeout, self.remaining()), 0.0)

    def header_value(self) -> str:
        """The remaining budget as an ``X-Deadline-Ms`` value (floored
        at 1 ms so an about-to-expire deadline still travels as a
        deadline rather than vanishing)."""
        return str(max(1, int(self.remaining_ms())))


_current_deadline: "contextvars.ContextVar[Deadline | None]" = (
    contextvars.ContextVar("pilosa_deadline", default=None)
)


def current_deadline() -> Deadline | None:
    return _current_deadline.get()


@contextmanager
def deadline_scope(dl: Deadline | None):
    """Install ``dl`` as the current deadline for the dynamic extent.
    ``None`` is a no-op scope (no deadline)."""
    if dl is None:
        yield None
        return
    token = _current_deadline.set(dl)
    try:
        yield dl
    finally:
        _current_deadline.reset(token)


def check_deadline(what: str = "") -> None:
    """Raise :class:`DeadlineExceeded` when the current deadline has
    expired; no-op without a deadline."""
    dl = _current_deadline.get()
    if dl is not None and dl.expired:
        raise DeadlineExceeded(
            f"deadline exceeded{f' ({what})' if what else ''}"
        )


# ---------------------------------------------------------------------------
# retries
# ---------------------------------------------------------------------------


class RetryPolicy:
    """Capped jittered-exponential retry for idempotent unary RPCs.

    ``attempts`` total tries; sleeps ``backoff * 2^i`` capped at
    ``max_backoff``, each shrunk by up to ``jitter`` (fraction) so a
    fan-out's retries don't stampede in lockstep.  Deadline-aware: a
    retry whose sleep would outlive the current deadline raises
    :class:`DeadlineExceeded` instead of sleeping into a guaranteed
    failure."""

    def __init__(
        self,
        attempts: int = 3,
        backoff: float = 0.1,
        max_backoff: float = 2.0,
        jitter: float = 0.5,
        stats=None,
        seed: int | None = None,
    ):
        from pilosa_tpu.obs.stats import NopStatsClient

        self.attempts = max(1, int(attempts))
        self.backoff = float(backoff)
        self.max_backoff = float(max_backoff)
        self.jitter = min(max(float(jitter), 0.0), 1.0)
        self.stats = stats or NopStatsClient()
        self._rng = random.Random(seed)

    def call(
        self,
        fn: Callable[[], Any],
        retryable: tuple[type[BaseException], ...] = TRANSPORT_ERRORS,
    ) -> Any:
        """Run ``fn()`` with up to ``attempts`` tries.  Only
        ``retryable`` exceptions retry; everything else (including
        DeadlineExceeded and BreakerOpenError) propagates at once."""
        from pilosa_tpu.obs import trace as trace_mod

        delay = self.backoff
        for attempt in range(self.attempts):
            try:
                result = fn()
            except retryable as e:
                if attempt == self.attempts - 1:
                    self.stats.count("net.retry.exhausted")
                    raise
                dl = current_deadline()
                if dl is not None and dl.expired:
                    raise DeadlineExceeded(
                        f"deadline exceeded after transport error: {e}"
                    ) from e
                sleep_s = min(delay, self.max_backoff)
                sleep_s *= 1.0 - self.jitter * self._rng.random()
                # A shed (429) carries the server's Retry-After hint:
                # honor it — retrying sooner would just be shed again.
                # When the hint outlives the remaining budget, surface
                # the shed NOW so the caller can fail over to a replica
                # instead of sleeping into a guaranteed 504.
                retry_after = getattr(e, "retry_after_s", None)
                if retry_after is not None:
                    sleep_s = max(sleep_s, float(retry_after))
                    self.stats.count("net.retry.shed")
                    if dl is not None and dl.remaining() < sleep_s:
                        raise
                if dl is not None:
                    sleep_s = dl.clamp(sleep_s)
                self.stats.count("net.retry.attempt")
                sp = trace_mod.current_span()
                if sp is not None:
                    sp.annotate(retries=attempt + 1)
                time.sleep(sleep_s)
                delay = min(delay * 2, self.max_backoff)
                continue
            return result

    def snapshot(self) -> dict:
        return {
            "attempts": self.attempts,
            "backoffMs": round(self.backoff * 1000.0, 3),
            "maxBackoffMs": round(self.max_backoff * 1000.0, 3),
            "jitter": self.jitter,
        }


# ---------------------------------------------------------------------------
# circuit breakers
# ---------------------------------------------------------------------------

STATE_CLOSED = "closed"
STATE_OPEN = "open"
STATE_HALF_OPEN = "half_open"


class CircuitBreaker:
    """Per-host closed/open/half-open state machine.

    ``failure_threshold`` consecutive transport failures trip the
    breaker open; after ``open_s`` the next ``allow()`` admits exactly
    ONE half-open probe (a stale probe — its caller died without
    recording an outcome — expires after another ``open_s`` so the
    breaker can never wedge); the probe's success closes the breaker,
    its failure re-opens it."""

    def __init__(
        self,
        host: str,
        failure_threshold: int = 5,
        open_s: float = 10.0,
        stats=None,
    ):
        from pilosa_tpu.obs.stats import NopStatsClient

        self.host = host
        self.failure_threshold = max(1, int(failure_threshold))
        self.open_s = float(open_s)
        self.stats = stats or NopStatsClient()
        self._mu = threading.Lock()
        self._state = STATE_CLOSED
        self._failures = 0  # consecutive
        self._opened_at = 0.0
        self._probe_started: float | None = None
        self.opens = 0

    @property
    def state(self) -> str:
        with self._mu:
            return self._state

    def allow(self) -> bool:
        """Whether a call to this host may proceed right now.  In the
        open state this is where the half-open transition happens."""
        with self._mu:
            if self._state == STATE_CLOSED:
                return True
            now = time.monotonic()
            if self._state == STATE_OPEN:
                if now - self._opened_at < self.open_s:
                    return False
                self._state = STATE_HALF_OPEN
                self._probe_started = now
                self.stats.count("net.breaker.halfOpen")
                return True
            # half-open: one probe in flight at a time; a probe whose
            # caller vanished expires so the breaker cannot wedge.
            if (
                self._probe_started is not None
                and now - self._probe_started < self.open_s
            ):
                return False
            self._probe_started = now
            return True

    def record_success(self) -> None:
        with self._mu:
            self._failures = 0
            self._probe_started = None
            if self._state != STATE_CLOSED:
                self._state = STATE_CLOSED
                self.stats.count("net.breaker.close")

    def record_failure(self) -> None:
        with self._mu:
            self._probe_started = None
            self._failures += 1
            if self._state == STATE_HALF_OPEN:
                self._trip_locked()
            elif (
                self._state == STATE_CLOSED
                and self._failures >= self.failure_threshold
            ):
                self._trip_locked()

    def _trip_locked(self) -> None:
        self._state = STATE_OPEN
        self._opened_at = time.monotonic()
        self.opens += 1
        self.stats.count("net.breaker.open")

    def snapshot(self) -> dict:
        with self._mu:
            out = {
                "state": self._state,
                "consecutiveFailures": self._failures,
                "opens": self.opens,
            }
            if self._state != STATE_CLOSED:
                out["sinceOpenMs"] = round(
                    (time.monotonic() - self._opened_at) * 1000.0, 1
                )
            return out


class BreakerRegistry:
    """Lazily-created breaker per remote host, shared by every client a
    server hands out.  ``check`` is the single call-site gate: it either
    admits the call or raises :class:`BreakerOpenError` in microseconds."""

    def __init__(
        self, failure_threshold: int = 5, open_s: float = 10.0, stats=None
    ):
        from pilosa_tpu.obs.stats import NopStatsClient

        self.failure_threshold = max(1, int(failure_threshold))
        self.open_s = float(open_s)
        self.stats = stats or NopStatsClient()
        self._mu = threading.Lock()
        self._breakers: dict[str, CircuitBreaker] = {}

    def for_host(self, host: str) -> CircuitBreaker:
        with self._mu:
            b = self._breakers.get(host)
            if b is None:
                b = self._breakers[host] = CircuitBreaker(
                    host,
                    failure_threshold=self.failure_threshold,
                    open_s=self.open_s,
                    stats=self.stats,
                )
            return b

    def check(self, host: str) -> None:
        if not self.for_host(host).allow():
            self.stats.count("net.breaker.rejected")
            raise BreakerOpenError(host)

    def record(self, host: str, ok: bool) -> None:
        b = self.for_host(host)
        if ok:
            b.record_success()
        else:
            b.record_failure()

    def state(self, host: str) -> str:
        return self.for_host(host).state

    def snapshot(self) -> dict:
        with self._mu:
            breakers = dict(self._breakers)
        return {host: b.snapshot() for host, b in sorted(breakers.items())}


# ---------------------------------------------------------------------------
# bundle
# ---------------------------------------------------------------------------


class Resilience:
    """The server's resilience wiring in one handle: the retry policy
    and breaker registry its clients share, plus the default query
    deadline.  Handed to the Handler for ``GET /debug/health``."""

    def __init__(
        self,
        retry: RetryPolicy | None = None,
        breakers: BreakerRegistry | None = None,
        query_timeout_ms: float = 0.0,
        stats=None,
    ):
        self.retry = retry or RetryPolicy(stats=stats)
        self.breakers = breakers or BreakerRegistry(stats=stats)
        self.query_timeout_ms = float(query_timeout_ms)

    def query_deadline(self, header_value: str = "") -> Deadline | None:
        """The deadline for one query: the request's ``X-Deadline-Ms``
        when present, else the configured default (0 = none)."""
        dl = Deadline.from_header(header_value)
        if dl is not None:
            return dl
        if self.query_timeout_ms > 0:
            return Deadline.after_ms(self.query_timeout_ms)
        return None

    def snapshot(self) -> dict:
        return {
            "queryTimeoutMs": self.query_timeout_ms,
            "retry": self.retry.snapshot(),
            "breakers": self.breakers.snapshot(),
        }
