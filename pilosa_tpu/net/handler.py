"""HTTP API — the full REST surface of the framework.

Route table and response shapes reproduce the reference's handler
(reference: handler.go:93-133 router, :1380-1470 codecs) so external
clients of the reference server work unchanged:

  GET    /                                  web console
  GET    /assets/{file}                     console assets
  GET    /schema | /index                   schema listing
  GET    /status /hosts /version            introspection
  GET    /slices/max                        per-index max slice (json|proto)
  GET/POST/DELETE /index/{i}                index CRUD
  POST   /index/{i}/query                   PQL execution (body = raw PQL
                                            or protobuf QueryRequest)
  PATCH  /index/{i}/time-quantum
  POST   /index/{i}/attr/diff               column-attr anti-entropy
  POST/DELETE /index/{i}/frame/{f}          frame CRUD
  PATCH  /index/{i}/frame/{f}/time-quantum
  GET    /index/{i}/frame/{f}/views
  POST   /index/{i}/frame/{f}/attr/diff     row-attr anti-entropy
  POST   /index/{i}/frame/{f}/restore       pull frame from another cluster
  POST   /import                            protobuf bulk import
  GET    /export                            CSV fragment export
  GET    /fragment/nodes                    owners of a slice
  GET/POST /fragment/data                   fragment tar backup/restore
  GET    /fragment/blocks /fragment/block/data   sync checksums / block dump
  GET    /debug/vars /debug/pprof/          expvar metrics / profiling info
  GET    /debug/hbm                         HBM residency (budget/resident/pinned)

The handler itself is transport-independent: ``Handler.dispatch`` maps a
parsed request to a ``Response``; ``serve`` mounts it on a stdlib
ThreadingHTTPServer (the reference rides net/http + gorilla/mux).
"""

from __future__ import annotations

import base64
import io
import json
import os
import re
import shutil
import sys
import tarfile
import tempfile
import threading
import time
import traceback
import urllib.parse
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from collections.abc import Callable, Iterable
from typing import Any

import numpy as np

from pilosa_tpu import __version__
from pilosa_tpu import stream as stream_mod
from pilosa_tpu.core import attr as attr_mod
from pilosa_tpu.core import timequantum as tq
from pilosa_tpu.core.bitmap import RowBitmap
from pilosa_tpu.exec import plan as plan_mod
from pilosa_tpu.exec.executor import (
    ExecOptions,
    ExecutorError,
    TooManyWritesError,
)
from pilosa_tpu.net import admission as adm
from pilosa_tpu.net import codec
from pilosa_tpu.net import resilience as rz
from pilosa_tpu.net import wire_pb2 as wire
from pilosa_tpu.obs import perf as perf_mod
from pilosa_tpu.obs import prom, trace
from pilosa_tpu.pql.parser import ParseError, parse_string
from pilosa_tpu.replicate import quorum as replicate_mod
from pilosa_tpu.subscribe import registry as subscribe_reg
from pilosa_tpu.subscribe import sse as sse_mod
from pilosa_tpu.testing import faults

PROTOBUF = "application/x-protobuf"
JSON = "application/json"


@dataclass
class Request:
    method: str
    path: str
    query: dict[str, str] = field(default_factory=dict)
    headers: dict[str, str] = field(default_factory=dict)
    body: bytes = b""
    # Incremental body source (file-like with read(n)); set by the HTTP
    # adapter instead of materializing the payload.  Routes marked
    # @stream_body consume it directly; everyone else gets ``body``
    # materialized by dispatch.
    stream: Any = None

    def header(self, key: str) -> str:
        return self.headers.get(key.lower(), "")

    def body_reader(self):
        """The body as a file object — the pending stream when one
        exists, else the materialized bytes."""
        return self.stream if self.stream is not None else io.BytesIO(self.body)

    def read_body(self) -> bytes:
        """Materialize (and cache) the body."""
        if self.stream is not None:
            self.body = self.stream.read()
            self.stream = None
        return self.body


def stream_body(fn):
    """Mark a route handler as consuming ``Request.stream`` itself —
    dispatch will not materialize the body first."""
    fn.streams_body = True
    return fn


def _route_template(pattern: str) -> str:
    """Route regex -> bounded metric label: named groups become
    ``{name}`` placeholders (``/index/(?P<index>[^/]+)/query`` ->
    ``/index/{index}/query``), so the HTTP latency histogram's ``path``
    label set is the route table, never raw request paths."""
    tmpl = re.sub(r"\(\?P<(\w+)>[^)]*\)", r"{\1}", pattern)
    return tmpl.replace("?", "") or "/"


@dataclass
class Response:
    status: int = 200
    body: bytes = b""
    content_type: str = JSON
    # Iterator body: when set, the HTTP adapter streams it with chunked
    # transfer encoding and constant-size writes instead of sending
    # ``body`` with a Content-Length.
    body_iter: Iterable[bytes] | None = None
    # Extra response headers (trace span export, etc.).
    headers: dict[str, str] = field(default_factory=dict)

    @classmethod
    def stream(
        cls, chunks: Iterable[bytes], content_type: str, chunk_bytes: int = 0
    ) -> "Response":
        return cls(
            body_iter=stream_mod.IterBody(chunks, chunk_bytes=chunk_bytes),
            content_type=content_type,
        )

    @classmethod
    def json(cls, obj: Any, status: int = 200) -> "Response":
        return cls(status=status, body=(json.dumps(obj) + "\n").encode())

    @classmethod
    def proto(cls, msg, status: int = 200) -> "Response":
        return cls(status=status, body=msg.SerializeToString(), content_type=PROTOBUF)

    @classmethod
    def error(cls, message: str, status: int) -> "Response":
        # reference uses http.Error (text/plain); we keep a JSON body and
        # the same status codes.
        return cls.json({"error": message}, status=status)


class Handler:
    """Routes requests to the holder/executor/cluster underneath."""

    def __init__(
        self,
        holder=None,
        executor=None,
        cluster=None,
        broadcaster=None,
        client_factory=None,
        version: str = __version__,
        logger=None,
        stats=None,
        stream_chunk_bytes: int = 0,
        tracer=None,
        slow_query_ms: float = 0.0,
        resilience=None,
        admission=None,
        tenants=None,
        rebalance=None,
        tier=None,
        replication=None,
        latency_buckets_ms=None,
        slo_ms: float = 0.0,
        slo_objective: float = 0.999,
    ):
        self.holder = holder
        self.executor = executor
        self.cluster = cluster
        self.broadcaster = broadcaster
        self.client_factory = client_factory
        self.version = version
        self.logger = logger or (lambda msg: print(msg, file=sys.stderr))
        self.stats = stats
        # Query-path tracing (obs/trace.py): always-on when a Tracer is
        # wired (Server does); NOP otherwise.
        self.tracer = tracer or trace.NOP_TRACER
        # Structured slow-query log threshold in ms ([obs] slow-query-ms);
        # 0 disables.  Distinct from cluster.long-query-time (the
        # reference-parity plain-text log below).
        self.slow_query_ms = slow_query_ms
        # Resilience bundle (net/resilience.py): supplies the default
        # query deadline and the breaker registry behind
        # GET /debug/health.  None = no deadlines, no health detail.
        self.resilience = resilience
        # Admission control (net/admission.py): per-cost-class
        # concurrency gates + bounded queues in front of the executor.
        # A request the node cannot serve within its deadline answers
        # 429 + Retry-After BEFORE any coalescer/device work.  None =
        # admit everything (bare handler / tests).
        self.admission = admission
        # Tenant QoS (net/admission.py TenantRegistry): API-key ->
        # tenant resolution, internal-lane token verification, and the
        # per-tenant table behind GET /debug/tenants.  None = every
        # request rides the default tenant and the internal lane is
        # open (bare handler / tests).
        self.tenants = tenants
        # Elastic-cluster rebalancer (pilosa_tpu/rebalance): topology
        # events, resize coordination, delta-log/copy/release
        # endpoints, /debug/rebalance.  None = static cluster surface
        # (the endpoints answer 501).
        self.rebalance = rebalance
        # Tiered storage (pilosa_tpu/tier): the TierManager behind
        # GET /debug/tier and the store-riding rebalance restore
        # endpoint POST /tier/restore.  None = no cold tier (the
        # endpoints answer 501 / a stub document).
        self.tier = tier
        # Quorum replication (pilosa_tpu/replicate): version/hint
        # endpoints, /debug/replication, per-request consistency
        # overrides, and the X-Write-Version stamp on remote write
        # legs.  None = static single-copy surface (endpoints 501).
        self.replication = replication
        # Standing queries (pilosa_tpu/subscribe): POST /subscribe
        # registration, SSE / long-poll delivery, /debug/subscriptions.
        # Wired by the Server after the executor exists (like
        # ``executor`` itself); None = endpoints answer 501.
        self.subscribe = None
        # Staging-lane prefetcher (device/prefetch.py), wired by the
        # Server: fragments restored with ?stage=true (migration
        # arrivals) register their HBM mirrors through it.
        self.prefetcher = None
        # Durable ingest (pilosa_tpu/ingest): WAL group-commit manager,
        # wired by the Server when [ingest] wal is on.  Serves
        # GET /debug/ingest; None = WAL disabled (stub JSON).
        self.ingest = None
        # Native fixed-bucket latency histograms + SLO burn rate
        # (obs/perf.py): query latency per admission class, HTTP
        # latency per route template — rendered as Prometheus
        # histogram families on /metrics alongside the Expvar
        # summaries.
        self.latency = perf_mod.LatencyHistograms(
            buckets_ms=latency_buckets_ms,
            slo_ms=slo_ms,
            slo_objective=slo_objective,
        )
        # Base dir for /debug/profile trace tarballs, wired by the
        # Server (data dir); bare handlers fall back to a tempdir.
        self.profile_dir = None
        # Single-flight guard for /debug/profile: one device trace at a
        # time, concurrent requests answer 409.
        self._profile_mu = threading.Lock()
        # Chunk size for streamed (chunked transfer encoding) bodies:
        # CSV export and fragment archives move in writes of this size.
        self.stream_chunk_bytes = stream_chunk_bytes or stream_mod.DEFAULT_CHUNK_BYTES
        # Serialized NodeStatus provider (wired by Server): serves the
        # gossip stream fallback's GET /state (the TCP push/pull analog,
        # reference: gossip/gossip.go:191-222).
        self.state_provider = None
        # (method, compiled-regex, fn) — order matters, first match wins
        # (reference: handler.go:93-133).
        self._routes: list[tuple[str, re.Pattern, Callable]] = [
            ("GET", r"/", self.handle_webui),
            ("GET", r"/assets/(?P<file>[^/]+)", self.handle_webui_asset),
            ("GET", r"/schema", self.handle_get_schema),
            ("GET", r"/status", self.handle_get_status),
            ("GET", r"/state", self.handle_get_state),
            ("GET", r"/hosts", self.handle_get_hosts),
            ("GET", r"/version", self.handle_get_version),
            ("GET", r"/slices/max", self.handle_get_slice_max),
            ("GET", r"/index", self.handle_get_indexes),
            ("GET", r"/index/(?P<index>[^/]+)", self.handle_get_index),
            ("POST", r"/index/(?P<index>[^/]+)", self.handle_post_index),
            ("DELETE", r"/index/(?P<index>[^/]+)", self.handle_delete_index),
            ("POST", r"/index/(?P<index>[^/]+)/query", self.handle_post_query),
            ("PATCH", r"/index/(?P<index>[^/]+)/time-quantum", self.handle_patch_index_time_quantum),
            ("POST", r"/index/(?P<index>[^/]+)/attr/diff", self.handle_post_index_attr_diff),
            ("POST", r"/index/(?P<index>[^/]+)/frame/(?P<frame>[^/]+)", self.handle_post_frame),
            ("DELETE", r"/index/(?P<index>[^/]+)/frame/(?P<frame>[^/]+)", self.handle_delete_frame),
            ("PATCH", r"/index/(?P<index>[^/]+)/frame/(?P<frame>[^/]+)/time-quantum", self.handle_patch_frame_time_quantum),
            ("GET", r"/index/(?P<index>[^/]+)/frame/(?P<frame>[^/]+)/views", self.handle_get_frame_views),
            ("POST", r"/index/(?P<index>[^/]+)/frame/(?P<frame>[^/]+)/attr/diff", self.handle_post_frame_attr_diff),
            ("POST", r"/index/(?P<index>[^/]+)/frame/(?P<frame>[^/]+)/restore", self.handle_post_frame_restore),
            ("GET", r"/index/(?P<index>[^/]+)/frame/(?P<frame>[^/]+)/fields", self.handle_get_frame_fields),
            ("POST", r"/index/(?P<index>[^/]+)/frame/(?P<frame>[^/]+)/field/(?P<fld>[^/]+)", self.handle_post_frame_field),
            ("DELETE", r"/index/(?P<index>[^/]+)/frame/(?P<frame>[^/]+)/field/(?P<fld>[^/]+)", self.handle_delete_frame_field),
            ("POST", r"/import", self.handle_post_import),
            ("POST", r"/import-value", self.handle_post_import_value),
            ("GET", r"/export", self.handle_get_export),
            ("GET", r"/fragment/nodes", self.handle_get_fragment_nodes),
            ("GET", r"/fragment/data", self.handle_get_fragment_data),
            ("POST", r"/fragment/data", self.handle_post_fragment_data),
            ("GET", r"/fragment/blocks", self.handle_get_fragment_blocks),
            ("POST", r"/fragment/import-view", self.handle_post_import_view),
            ("GET", r"/fragment/block/data", self.handle_get_fragment_block_data),
            ("POST", r"/cluster/resize", self.handle_post_resize),
            ("POST", r"/cluster/resize/abort", self.handle_post_resize_abort),
            ("POST", r"/cluster/topology", self.handle_post_topology),
            ("POST", r"/rebalance/delta", self.handle_post_rebalance_delta),
            ("POST", r"/rebalance/release", self.handle_post_rebalance_release),
            ("POST", r"/tier/restore", self.handle_post_tier_restore),
            ("POST", r"/replicate/versions", self.handle_post_replicate_versions),
            ("POST", r"/replicate/hint", self.handle_post_replicate_hint),
            ("POST", r"/replicate/replay", self.handle_post_replicate_replay),
            ("POST", r"/subscribe", self.handle_post_subscribe),
            ("GET", r"/subscribe/(?P<sid>[^/]+)/stream", self.handle_get_subscribe_stream),
            ("GET", r"/subscribe/(?P<sid>[^/]+)/poll", self.handle_get_subscribe_poll),
            ("DELETE", r"/subscribe/(?P<sid>[^/]+)", self.handle_delete_subscribe),
            ("GET", r"/debug/subscriptions", self.handle_get_subscriptions),
            ("GET", r"/debug/replication", self.handle_get_replication),
            ("GET", r"/debug/tier", self.handle_get_tier),
            ("GET", r"/debug/ingest", self.handle_get_ingest),
            ("GET", r"/debug/rebalance", self.handle_get_rebalance),
            ("GET", r"/debug/vars", self.handle_get_vars),
            ("GET", r"/debug/tenants", self.handle_get_tenants),
            ("GET", r"/debug/health", self.handle_get_health),
            ("GET", r"/debug/hbm", self.handle_get_hbm),
            ("GET", r"/debug/perf", self.handle_get_perf),
            ("GET", r"/debug/profile", self.handle_get_profile),
            ("GET", r"/debug/stacks", self.handle_get_stacks),
            ("GET", r"/debug/traces", self.handle_get_traces),
            ("GET", r"/metrics", self.handle_get_metrics),
            ("GET", r"/debug/pprof(?P<rest>/.*)?", self.handle_get_pprof),
        ]
        self._compiled = [
            (m, re.compile("^" + p + "$"), fn, _route_template(p))
            for m, p, fn in self._routes
        ]
        self._start_time = time.time()

    # ------------------------------------------------------------------
    # dispatch
    # ------------------------------------------------------------------

    def dispatch(self, req: Request) -> Response:
        t0 = time.monotonic()
        route = None  # matched route TEMPLATE (bounded label cardinality)
        try:
            # Chaos hook: the RPC-receive boundary (testing/faults.py).
            # An injected error here answers 500 — the shape of a node
            # that accepted the connection but is failing inside.
            faults.check(
                "rpc.recv",
                host=getattr(self.executor, "host", "") or None,
                path=req.path,
            )
            for method, pattern, fn, tmpl in self._compiled:
                m = pattern.match(req.path.rstrip("/") or "/")
                if m and method == req.method:
                    route = tmpl
                    if req.stream is not None and not getattr(
                        fn, "streams_body", False
                    ):
                        req.read_body()
                    resp = fn(req, **m.groupdict())
                    break
            else:
                resp = Response.error("not found", 404)
        except Exception as e:  # noqa: BLE001 — API boundary
            self.logger(f"handler error {req.method} {req.path}: {e}\n"
                        + traceback.format_exc())
            resp = Response.error(str(e), 500)
        elapsed = time.monotonic() - t0
        # Metrics and logging never drop a response, and a failing stats
        # backend must not silence the slow-query log: each observes
        # independently.
        try:
            self._observe_stats(req, elapsed, route)
        except Exception:  # noqa: BLE001
            pass
        try:
            self._observe_slow_query(req, elapsed)
        except Exception:  # noqa: BLE001
            pass
        return resp

    def _observe_stats(
        self, req: Request, elapsed: float, route: str | None = None
    ) -> None:
        if self.stats is not None:
            # per-endpoint latency histogram (reference: handler.go:140-167)
            self.stats.histogram(
                f"http.{req.method}.{req.path.split('?')[0]}", elapsed * 1000.0
            )
        if route is not None:
            # Native bucketed HTTP histogram keyed by route TEMPLATE
            # ("/index/{index}/query"), not the raw path — per-index
            # paths would be an unbounded label cardinality.
            self.latency.observe_http(req.method, route, elapsed * 1000.0)

    def _observe_slow_query(self, req: Request, elapsed: float) -> None:
        # slow-query log gated by cluster.long-query-time
        # (reference: handler.go:158-163); exact route match so frames
        # legally named "query" don't trigger it
        lqt = getattr(self.cluster, "long_query_time", 0.0) if self.cluster else 0.0
        is_query_route = req.method == "POST" and bool(
            re.match(r"^/index/[^/]+/query$", req.path)
        )
        if float(lqt) > 0 and elapsed > float(lqt) and is_query_route:
            if req.header("Content-Type") == PROTOBUF:
                try:
                    pb = wire.QueryRequest()
                    pb.ParseFromString(req.body)
                    query_text = pb.Query
                except Exception:  # noqa: BLE001 — logging only
                    query_text = "<unparseable protobuf>"
            else:
                query_text = req.body[:512].decode(errors="replace")
            self.logger(f"slow query {elapsed:.3f}s: {query_text[:512]}")

    # ------------------------------------------------------------------
    # introspection
    # ------------------------------------------------------------------

    def handle_webui(self, req: Request) -> Response:
        from pilosa_tpu.net import webui

        return Response(body=webui.INDEX_HTML.encode(), content_type="text/html")

    def handle_webui_asset(self, req: Request, file: str) -> Response:
        from pilosa_tpu.net import webui

        asset = webui.ASSETS.get(file)
        if asset is None:
            return Response.error("not found", 404)
        body, ctype = asset
        return Response(body=body.encode(), content_type=ctype)

    def handle_get_schema(self, req: Request) -> Response:
        return Response.json({"indexes": self.holder.schema()})

    def handle_get_indexes(self, req: Request) -> Response:
        return self.handle_get_schema(req)

    def handle_get_status(self, req: Request) -> Response:
        if self.cluster is not None:
            # Refresh Node.state from the membership backend (or the
            # static all-UP default) before reporting.
            self.cluster.node_states()
        status = {
            "Nodes": [
                {
                    "Host": n.host,
                    "State": n.state,
                    "Indexes": self.holder.schema() if n.host == getattr(self.executor, "host", None) else [],
                }
                for n in (self.cluster.nodes if self.cluster else [])
            ]
        }
        return Response.json({"status": status})

    def handle_get_state(self, req: Request) -> Response:
        """The node's serialized state blob (NodeStatus protobuf) — the
        gossip stream fallback pulls it here when UDP chunking stalls
        or the blob is large."""
        if self.state_provider is None:
            return Response.error("state provider not configured", 404)
        body = self.state_provider()
        return Response(body=body, content_type=PROTOBUF)

    def handle_get_hosts(self, req: Request) -> Response:
        return Response.json([n.to_dict() for n in self.cluster.nodes])

    def handle_get_version(self, req: Request) -> Response:
        return Response.json({"version": self.version})

    def handle_get_slice_max(self, req: Request) -> Response:
        inverse = req.query.get("inverse") == "true"
        ms = (
            self.holder.max_inverse_slices()
            if inverse
            else self.holder.max_slices()
        )
        if PROTOBUF in req.header("Accept"):
            pb = wire.MaxSlicesResponse()
            for k, v in ms.items():
                pb.MaxSlices[k] = v
            return Response.proto(pb)
        return Response.json({"maxSlices": ms})

    # ------------------------------------------------------------------
    # index CRUD
    # ------------------------------------------------------------------

    def handle_get_index(self, req: Request, index: str) -> Response:
        idx = self.holder.index(index)
        if idx is None:
            return Response.error("index not found", 404)
        return Response.json({"index": {"name": idx.name}})

    def handle_post_index(self, req: Request, index: str) -> Response:
        options = {}
        if req.body:
            try:
                payload = json.loads(req.body)
            except json.JSONDecodeError as e:
                return Response.error(str(e), 400)
            options = payload.get("options", {}) or {}
        kwargs = {}
        if "columnLabel" in options:
            kwargs["column_label"] = options["columnLabel"]
        if "timeQuantum" in options:
            kwargs["time_quantum"] = options["timeQuantum"]
        if self.holder.index(index) is not None:
            return Response.error("index already exists", 409)
        try:
            idx = self.holder.create_index(index, **kwargs)
        except ValueError as e:
            return Response.error(str(e), 400)
        self._broadcast(
            wire.CreateIndexMessage(
                Index=index,
                Meta=wire.IndexMeta(
                    ColumnLabel=idx.column_label, TimeQuantum=idx.time_quantum
                ),
            )
        )
        return Response.json({})

    def handle_delete_index(self, req: Request, index: str) -> Response:
        self.holder.delete_index(index)
        self._broadcast(wire.DeleteIndexMessage(Index=index))
        return Response.json({})

    def handle_patch_index_time_quantum(self, req: Request, index: str) -> Response:
        try:
            payload = json.loads(req.body)
        except json.JSONDecodeError as e:
            return Response.error(str(e), 400)
        try:
            q = tq.parse_time_quantum(payload.get("timeQuantum", ""))
        except ValueError:
            return Response.error("invalid time quantum", 400)
        idx = self.holder.index(index)
        if idx is None:
            return Response.error("index not found", 404)
        idx.set_time_quantum(q)
        return Response.json({})

    def handle_post_index_attr_diff(self, req: Request, index: str) -> Response:
        idx = self.holder.index(index)
        if idx is None:
            return Response.error("index not found", 404)
        return self._attr_diff(req, idx.column_attr_store)

    # ------------------------------------------------------------------
    # frame CRUD
    # ------------------------------------------------------------------

    def handle_post_frame(self, req: Request, index: str, frame: str) -> Response:
        idx = self.holder.index(index)
        if idx is None:
            return Response.error("index not found", 404)
        options = {}
        if req.body:
            try:
                payload = json.loads(req.body)
            except json.JSONDecodeError as e:
                return Response.error(str(e), 400)
            options = payload.get("options", {}) or {}
        kwargs = {}
        for json_key, py_key in (
            ("rowLabel", "row_label"),
            ("inverseEnabled", "inverse_enabled"),
            ("cacheType", "cache_type"),
            ("cacheSize", "cache_size"),
            ("timeQuantum", "time_quantum"),
            ("rangeEnabled", "range_enabled"),
            ("retentionAgeS", "retention_age_s"),
            ("retentionDeleteS", "retention_delete_s"),
        ):
            if json_key in options:
                kwargs[py_key] = options[json_key]
        if idx.frame(frame) is not None:
            return Response.error("frame already exists", 409)
        try:
            f = idx.create_frame(frame, **kwargs)
        except (ValueError, RuntimeError) as e:
            return Response.error(str(e), 400)
        self._broadcast(
            wire.CreateFrameMessage(
                Index=index, Frame=frame, Meta=_frame_meta_proto(f)
            )
        )
        return Response.json({})

    def handle_delete_frame(self, req: Request, index: str, frame: str) -> Response:
        idx = self.holder.index(index)
        if idx is None:
            return Response.error("index not found", 404)
        idx.delete_frame(frame)
        self._broadcast(wire.DeleteFrameMessage(Index=index, Frame=frame))
        return Response.json({})

    def handle_patch_frame_time_quantum(
        self, req: Request, index: str, frame: str
    ) -> Response:
        try:
            payload = json.loads(req.body)
        except json.JSONDecodeError as e:
            return Response.error(str(e), 400)
        try:
            q = tq.parse_time_quantum(payload.get("timeQuantum", ""))
        except ValueError:
            return Response.error("invalid time quantum", 400)
        f = self.holder.frame(index, frame)
        if f is None:
            return Response.error("frame not found", 404)
        f.set_time_quantum(q)
        return Response.json({})

    def handle_get_frame_views(self, req: Request, index: str, frame: str) -> Response:
        f = self.holder.frame(index, frame)
        if f is None:
            return Response.error("frame not found", 404)
        return Response.json({"views": sorted(f.views().keys())})

    def handle_post_frame_attr_diff(
        self, req: Request, index: str, frame: str
    ) -> Response:
        f = self.holder.frame(index, frame)
        if f is None:
            return Response.error("frame not found", 404)
        return self._attr_diff(req, f.row_attr_store)

    def handle_post_frame_restore(
        self, req: Request, index: str, frame: str
    ) -> Response:
        """Pull every slice of a frame from a remote cluster
        (reference: handler.go:1253-1341)."""
        host = req.query.get("host")
        if not host:
            return Response.error("host required", 400)
        f = self.holder.frame(index, frame)
        if f is None:
            return Response.error("frame not found", 404)
        if self.client_factory is None:
            return Response.error("no client", 500)
        client = self.client_factory(host)
        max_slices = client.max_slice_by_index()
        max_inverse = client.max_slice_by_index(inverse=True)
        for view_name in client.frame_views(index, frame):
            from pilosa_tpu.core.view import is_inverse_view

            ms = (
                max_inverse.get(index, 0)
                if is_inverse_view(view_name)
                else max_slices.get(index, 0)
            )
            for slice_i in range(ms + 1):
                view = f.create_view_if_not_exists(view_name)
                frag = view.create_fragment_if_not_exists(slice_i)
                # Stream the remote archive straight into the fragment
                # instead of materializing it first.
                src = client.stream_backup_slice(index, frame, view_name, slice_i)
                if src is None:
                    continue
                with src:
                    frag.read_from(src)
        return Response.json({})

    # ------------------------------------------------------------------
    # BSI integer fields (pilosa_tpu/bsi)
    # ------------------------------------------------------------------
    #
    # Field schema rides JSON endpoints (a pilosa_tpu extension): the
    # protobuf FrameMeta broadcast reproduces the reference wire
    # contract exactly, which predates BSI — so field create/delete fan
    # out as plain HTTP to every peer instead (``?remote=true`` marks
    # the relayed leg).  Field metadata persists in each node's frame
    # .meta and is served by /schema, so restarts recover it locally.

    def handle_get_frame_fields(self, req: Request, index: str, frame: str) -> Response:
        f = self.holder.frame(index, frame)
        if f is None:
            return Response.error("frame not found", 404)
        return Response.json(
            {"fields": [fld.to_dict() for fld in f.bsi_fields()]}
        )

    def handle_post_frame_field(
        self, req: Request, index: str, frame: str, fld: str
    ) -> Response:
        from pilosa_tpu import bsi

        f = self.holder.frame(index, frame)
        if f is None:
            return Response.error("frame not found", 404)
        try:
            payload = json.loads(req.body) if req.body else {}
        except json.JSONDecodeError as e:
            return Response.error(str(e), 400)
        try:
            lo = int(payload.get("min", 0))
            hi = int(payload.get("max", 0))
        except (TypeError, ValueError):
            return Response.error("min/max must be integers", 400)
        remote = req.query.get("remote") == "true"
        if remote and not f.range_enabled:
            # The relayed leg implies range support: the coordinator
            # validated the operator-facing schema rules.
            f.set_options(range_enabled=True)
        try:
            f.create_field(fld, lo, hi)
        except bsi.BSIError as e:
            return Response.error(str(e), 400)
        except Exception as e:  # noqa: BLE001 — duplicate / not range-enabled
            return Response.error(str(e), 409)
        if not remote:
            self._fanout_field(
                "POST",
                f"/index/{index}/frame/{frame}/field/{fld}",
                json.dumps({"min": lo, "max": hi}).encode(),
            )
        return Response.json({})

    def handle_delete_frame_field(
        self, req: Request, index: str, frame: str, fld: str
    ) -> Response:
        f = self.holder.frame(index, frame)
        if f is None:
            return Response.error("frame not found", 404)
        try:
            f.delete_field(fld)
        except Exception as e:  # noqa: BLE001 — unknown field
            return Response.error(str(e), 404)
        if req.query.get("remote") != "true":
            self._fanout_field(
                "DELETE", f"/index/{index}/frame/{frame}/field/{fld}", b""
            )
        return Response.json({})

    def _fanout_field(self, method: str, path: str, body: bytes) -> None:
        """Relay a field schema change to every other node.  Collected
        errors surface as one exception AFTER every reachable peer got
        the change — a dead peer re-converges via its own retry, not by
        aborting the survivors."""
        if self.cluster is None or self.client_factory is None:
            return
        me = getattr(self.executor, "host", None)
        errs = []
        for node in self.cluster.nodes:
            if node.host == me:
                continue
            try:
                client = self.client_factory(node.host)
                status, data = client._request(
                    method, path, query={"remote": "true"}, body=body
                )
                client._check(status, data)
            except Exception as e:  # noqa: BLE001 — collect per-host
                errs.append(f"{node.host}: {e}")
        if errs:
            raise RuntimeError("field fanout: " + "; ".join(errs))

    def handle_post_import_value(self, req: Request) -> Response:
        """Columnar integer import (JSON, a pilosa_tpu extension):
        ``{"index","frame","field","slice","columnIDs":[],"values":[]}``
        — one value per column, written as vectorized plane set+clear
        passes through Frame.import_value.  Ownership-guarded like
        /import; the client fans a slice's payload to every replica."""
        ticket, shed = self._admit(adm.CLASS_WRITE, req)
        if shed is not None:
            return shed
        try:
            return self._handle_post_import_value(req)
        finally:
            if ticket is not None:
                ticket.release()

    def _handle_post_import_value(self, req: Request) -> Response:
        try:
            payload = json.loads(req.body)
        except json.JSONDecodeError as e:
            return Response.error(str(e), 400)
        index = payload.get("index", "")
        frame = payload.get("frame", "")
        field_name = payload.get("field", "")
        slice_i = payload.get("slice", 0)
        cols = payload.get("columnIDs", [])
        vals = payload.get("values", [])
        if not isinstance(cols, list) or not isinstance(vals, list) or len(
            cols
        ) != len(vals):
            return Response.error("columnIDs/values must be equal-length lists", 400)
        if self.cluster is not None and self.executor is not None:
            # Write-ownership guard: during a rebalance transition the
            # new ring's owners accept imports too (dual-write cutover).
            if not self.cluster.is_write_owner(
                self.executor.host, index, slice_i
            ):
                return Response.error(
                    f"host does not own slice {self.executor.host}"
                    f" slice={slice_i}",
                    412,
                )
        f = self.holder.frame(index, frame)
        if f is None:
            return Response.error("frame not found", 404)
        try:
            f.import_value(field_name, cols, vals)
        except Exception as e:  # noqa: BLE001 — unknown field / out of range
            return Response.error(str(e), 400)
        return Response.json({})

    # ------------------------------------------------------------------
    # query
    # ------------------------------------------------------------------

    def handle_post_query(self, req: Request, index: str) -> Response:
        """Traced query entry: the root span opens here (continuing a
        propagated trace on the remote leg of a fan-out), the body runs
        under it, and the finalized trace feeds the ring buffer, the
        remote span export header, and the structured slow-query log."""
        in_trace = req.header(trace.TRACE_HEADER)
        root = self.tracer.start_trace(
            "query",
            trace_id=in_trace or None,
            parent_span_id=req.header(trace.SPAN_HEADER) or None,
            index=index,
            node=getattr(self.executor, "host", ""),
        )
        # Deadline: the request's X-Deadline-Ms (the remote leg of a
        # fan-out, or an external per-request override) wins over the
        # configured [net] query-timeout-ms default.  The scope rides
        # a contextvar, so every remote leg, retry sleep, and coalesce
        # wait under execute() derives its timeout from what's left.
        dl = None
        if self.resilience is not None:
            dl = self.resilience.query_deadline(req.header(rz.DEADLINE_HEADER))
        else:
            dl = rz.Deadline.from_header(req.header(rz.DEADLINE_HEADER))
        token = root.activate()
        t0 = time.monotonic()
        try:
            with rz.deadline_scope(dl):
                resp = self._handle_post_query(req, index, root)
        finally:
            root.deactivate(token)
            record = self.tracer.finish_root(root)
            # Native per-class latency histogram + SLO accounting —
            # measured here (not from the trace record, which a full
            # ring may drop) so every query observes exactly once.
            try:
                self.latency.observe_query(
                    str(root.tags.get("cost_class") or "unclassified"),
                    (time.monotonic() - t0) * 1e3,
                    tenant=str(root.tags.get("tenant") or ""),
                )
            except Exception:  # noqa: BLE001 — metrics never drop a response
                pass
        if record is not None:
            if in_trace:
                # Remote leg: ship this node's spans back to the
                # coordinator, which absorbs them into the one trace.
                resp.headers[trace.SPANS_HEADER] = self.tracer.export_payload(
                    record
                )
            elif (
                self.slow_query_ms > 0
                and record["duration_ms"] >= self.slow_query_ms
            ):
                try:
                    self._log_slow_query(index, root, record)
                except Exception:  # noqa: BLE001 — logging never drops a response
                    pass
        return resp

    def _log_slow_query(self, index: str, root, record: dict) -> None:
        """Exactly one structured line per slow coordinator query."""
        line = {
            "ms": record["duration_ms"],
            "index": index,
            "query": root.tags.get("query", ""),
            "slices": root.tags.get("slices", "all"),
            "trace_id": record["trace_id"],
            "stages": trace.stage_breakdown(record),
        }
        co = _coalesce_batch_stats(record)
        if co is not None:
            line["coalesce"] = co
        fu = _fuse_batch_stats(record)
        if fu is not None:
            line["fuse"] = fu
        self.logger("slow query " + json.dumps(line, sort_keys=True))

    def _handle_post_query(self, req: Request, index: str, root) -> Response:
        try:
            qreq = self._read_query_request(req)
        except ValueError as e:
            return self._query_error(req, str(e), 400)
        root.annotate(
            query=qreq["query"][:512],
            slices=qreq["slices"] if qreq["slices"] is not None else "all",
            remote=qreq["remote"],
        )
        try:
            with self.tracer.span("parse"):
                q = parse_string(qreq["query"])
        except Exception as e:  # parser error
            return self._query_error(req, str(e), 400)
        # Per-request consistency overrides (pilosa_tpu/replicate):
        # header wins over query param; junk is a 400, not a silent
        # default.
        try:
            write_consistency = _consistency_arg(
                req, "X-Write-Consistency", "writeConsistency"
            )
            read_consistency = _consistency_arg(
                req, "X-Read-Consistency", "readConsistency"
            )
        except ValueError as e:
            return self._query_error(req, str(e), 400)
        # Internal-lane verification (net/admission.py TenantRegistry):
        # the Remote flag earns the internal priority lane only with
        # the cluster's token (when one is configured) — a client
        # spoofing Remote is classified and charged like any other
        # client request.  The coordinator forwards the ORIGIN tenant
        # as X-Tenant on its map legs, so the fan-out is charged to
        # whoever sent the query, on every node it touches.
        internal = qreq["remote"] and self._internal_ok(req)
        tenant = self._resolve_tenant(req, internal)
        opt = ExecOptions(
            remote=qreq["remote"],
            allow_partial=(
                req.query.get("allowPartial") == "true"
                or req.header("X-Allow-Partial") in ("1", "true")
            ),
            write_consistency=write_consistency,
            read_consistency=read_consistency,
            tenant=tenant,
        )
        # Remote write legs carry the quorum coordinator's per-slice
        # version stamp (taken at the PRIMARY after its local apply).
        # Versions are pure local write counts — comparable across
        # replicas because every replica applies the same stream — so
        # the stamp is NOT merged into the clock (that would double-
        # count this very write); it is the replica's self-staleness
        # probe: applying this write should land the local counter AT
        # the stamp, and landing short means earlier writes were missed
        # (surfaced as cluster.replication.staleSelf before read-repair
        # or hint replay ever looks).
        stale_probe = None
        if qreq["remote"] and self.replication is not None:
            stamp = req.header(replicate_mod.WRITE_VERSION_HEADER)
            if stamp:
                try:
                    slice_s, _, ver_s = stamp.partition(":")
                    stale_probe = (int(slice_s), int(ver_s))
                except (TypeError, ValueError):
                    pass  # malformed stamp must not fail the write
        # Admission gate: classify from the parsed plan (remote map
        # legs ride the internal priority lane — a saturated node must
        # never starve another coordinator's fan-out behind its own
        # client queue), then admit or shed 429 BEFORE the executor,
        # coalescer, or device see the query.
        # Classified unconditionally (not only under admission): the
        # class keys the native query-latency histogram and the SLO
        # burn rate, which exist with or without admission gates.
        cls = (
            adm.CLASS_INTERNAL
            if internal
            else plan_mod.cost_class(q.calls)
        )
        root.annotate(cost_class=cls)
        if tenant:
            root.annotate(tenant=tenant)
        ticket = None
        if self.admission is not None:
            try:
                with self.tracer.span("admission", cost_class=cls) as sp:
                    ticket = self.admission.acquire(
                        cls,
                        tenant=tenant,
                        nbytes=len(req.body or b""),
                    )
                    sp.annotate(wait_ms=round(ticket.wait_ms, 3))
            except rz.ShedError as e:
                root.annotate(shed=True)
                return self._shed_response(req, e)
        try:
            rz.check_deadline("before execute")
            with self.tracer.span("execute"):
                results = self.executor.execute(index, q, qreq["slices"], opt)
        except TooManyWritesError as e:
            return self._query_error(req, str(e), 413)
        except rz.DeadlineExceeded as e:
            # 504 carries the trace id: the retained trace shows where
            # the budget went.
            root.annotate(error="DeadlineExceeded")
            trace_id = getattr(root, "trace_id", "") or "none"
            return self._query_error(req, f"{e} [trace {trace_id}]", 504)
        except Exception as e:  # noqa: BLE001 — executor boundary
            return self._query_error(req, str(e), 500)
        finally:
            if ticket is not None:
                ticket.release()

        if stale_probe is not None:
            probe_slice, probe_ver = stale_probe
            if self.replication.versions.get(index, probe_slice) < probe_ver:
                self.replication.stats.count(
                    "cluster.replication.staleSelf"
                )
                root.annotate(stale_self=True)

        column_attr_sets = None
        if qreq["column_attrs"]:
            idx = self.holder.index(index)
            column_ids: list[int] = []
            for r in results:
                if isinstance(r, RowBitmap):
                    bits = codec.bitmap_to_json(r)["bits"]
                    column_ids = sorted(set(column_ids) | set(bits))
            column_attr_sets = []
            if idx is not None:
                for cid in column_ids:
                    attrs = idx.column_attr_store.attrs(cid)
                    if attrs:
                        column_attr_sets.append((cid, attrs))

        if PROTOBUF in req.header("Accept"):
            resp = Response.proto(
                codec.response_to_proto(results, column_attr_sets)
            )
            if opt.missing_slices:
                # The wire protobuf has no partial field (reference
                # parity); internal callers read the marker off this
                # header instead.
                resp.headers["X-Missing-Slices"] = ",".join(
                    str(s) for s in opt.missing_slices
                )
            return resp
        payload = codec.response_to_json(results, column_attr_sets)
        if opt.missing_slices:
            payload["partial"] = True
            payload["missingSlices"] = opt.missing_slices
        return Response.json(payload)

    def _read_query_request(self, req: Request) -> dict:
        """reference: handler.go:863-944.

        ``time_granularity`` / ``QueryRequest.Quantum`` is VALIDATED
        (invalid values are a 400) and carried on the wire, but — by
        exact reference parity — never consumed by execution: the
        reference parses it (handler.go:913-926), decodes it from
        protobuf (handler.go:1396-1408), and then no code path reads
        ``QueryRequest.Quantum`` again; remote exec re-marshals without
        it (executor.go:1048-1052) and Range() always uses the frame's
        own quantum (executor.go:572-573).  We reproduce that contract
        verbatim rather than invent semantics the reference lacks."""
        if req.header("Content-Type") == PROTOBUF:
            pb = wire.QueryRequest()
            pb.ParseFromString(req.body)
            return {
                "query": pb.Query,
                "slices": list(pb.Slices) or None,
                "column_attrs": pb.ColumnAttrs,
                "quantum": pb.Quantum or "YMDH",
                "remote": pb.Remote,
            }
        valid = {
            "slices",
            "columnAttrs",
            "time_granularity",
            "allowPartial",
            "writeConsistency",
            "readConsistency",
        }
        for key in req.query:
            if key not in valid:
                raise ValueError("invalid query params")
        slices = None
        if req.query.get("slices"):
            try:
                slices = [int(s) for s in req.query["slices"].split(",")]
            except ValueError:
                raise ValueError("invalid slice argument") from None
        quantum = "YMDH"
        if req.query.get("time_granularity"):
            try:
                quantum = tq.parse_time_quantum(req.query["time_granularity"])
            except ValueError:
                raise ValueError("invalid time granularity") from None
        return {
            "query": req.body.decode(),
            "slices": slices,
            "column_attrs": req.query.get("columnAttrs") == "true",
            "quantum": quantum,
            "remote": False,
        }

    def _query_error(self, req: Request, message: str, status: int) -> Response:
        if PROTOBUF in req.header("Accept"):
            return Response.proto(wire.QueryResponse(Err=message), status=status)
        return Response.json({"error": message}, status=status)

    def _internal_ok(self, req: Request) -> bool:
        """May this request claim the internal lane?  Open when no
        registry / no token is configured (trusted network, every
        pre-tenant deployment); token-gated otherwise, so tenants
        cannot spoof X-Internal-Lane or the Remote flag past QoS."""
        if self.tenants is None:
            return True
        return self.tenants.internal_ok(req.header("X-Internal-Token"))

    def _resolve_tenant(self, req: Request, internal: bool = False) -> str:
        """The tenant this request is charged to.  Client traffic:
        X-Api-Key via the registry (a bare X-Tenant only for configured
        tenants).  Verified internal traffic: the coordinator's
        forwarded X-Tenant verbatim — the origin already paid admission
        at its front door and map legs must charge the same account."""
        if self.tenants is None:
            return ""
        if internal:
            return req.header("X-Tenant") or self.tenants.default_tenant
        return self.tenants.resolve(
            req.header("X-Api-Key"), req.header("X-Tenant")
        )

    def _shed_response(self, req: Request, e: rz.ShedError) -> Response:
        """429 + Retry-After: the node is healthy but at capacity, and
        the request was answered before any executor/device work.  The
        header carries whole seconds (HTTP contract, floored at 1);
        the JSON body carries the precise millisecond hint.  Quota
        sheds additionally carry X-Quota-Limit / X-Quota-Remaining so
        a well-behaved client can pace itself instead of retrying into
        the same empty bucket."""
        import math

        if PROTOBUF in req.header("Accept"):
            resp = Response.proto(wire.QueryResponse(Err=str(e)), status=429)
        else:
            body = {
                "error": str(e),
                "retryAfterMs": round(e.retry_after_s * 1000.0, 1),
            }
            if isinstance(e, adm.QuotaError):
                body["quota"] = {
                    "tenant": e.tenant,
                    "kind": e.quota_kind,
                    "limit": e.quota_limit,
                    "remaining": round(e.quota_remaining, 3),
                }
            resp = Response.json(body, status=429)
        resp.headers["Retry-After"] = str(max(1, math.ceil(e.retry_after_s)))
        if isinstance(e, adm.QuotaError):
            resp.headers["X-Quota-Limit"] = f"{e.quota_limit:g}"
            resp.headers["X-Quota-Remaining"] = f"{max(0.0, e.quota_remaining):g}"
        return resp

    def _admit(self, cls: str, req: Request):
        """Admission for non-query routes (imports, repair pushes):
        returns ``(ticket, None)`` or ``(None, 429 response)``.  The
        deadline comes straight off the request header — these routes
        run outside the query path's deadline scope.

        ``X-Internal-Lane`` reclasses the request onto the internal
        priority lane: hint replays push queued /import payloads
        through the client write route, and cluster-internal traffic
        must never starve behind (or be shed as) a client storm.  The
        reclass is token-gated like the query path's Remote flag."""
        if self.admission is None:
            return None, None
        internal = False
        if req.header("X-Internal-Lane") in ("1", "true") and (
            self._internal_ok(req)
        ):
            cls = adm.CLASS_INTERNAL
            internal = True
        tenant = self._resolve_tenant(req, internal)
        dl = rz.Deadline.from_header(req.header(rz.DEADLINE_HEADER))
        try:
            return (
                self.admission.acquire(
                    cls,
                    deadline=dl,
                    tenant=tenant,
                    nbytes=len(req.body or b""),
                ),
                None,
            )
        except rz.ShedError as e:
            return None, self._shed_response(req, e)

    # ------------------------------------------------------------------
    # import / export
    # ------------------------------------------------------------------

    def handle_post_import(self, req: Request) -> Response:
        """reference: handler.go:969-1046"""
        ticket, shed = self._admit(adm.CLASS_WRITE, req)
        if shed is not None:
            return shed
        try:
            return self._handle_post_import(req)
        finally:
            if ticket is not None:
                ticket.release()

    def _handle_post_import(self, req: Request) -> Response:
        pb = wire.ImportRequest()
        try:
            pb.ParseFromString(req.body)
        except Exception as e:  # noqa: BLE001
            return Response.error(str(e), 400)
        # Ownership guard (reference: handler.go:1004) — write-ring
        # aware: a migration target accepts imports before its cutover.
        if self.cluster is not None and self.executor is not None:
            if not self.cluster.is_write_owner(
                self.executor.host, pb.Index, pb.Slice
            ):
                return Response.error(
                    f"host does not own slice {self.executor.host}"
                    f" slice={pb.Slice}",
                    412,
                )
        f = self.holder.frame(pb.Index, pb.Frame)
        if f is None:
            return Response.error("frame not found", 404)
        timestamps = [
            None if ts == 0 else _dt_from_unix(ts) for ts in pb.Timestamps
        ] if pb.Timestamps else None
        try:
            f.import_bulk(
                np.asarray(pb.RowIDs, dtype=np.int64),
                np.asarray(pb.ColumnIDs, dtype=np.int64),
                timestamps,
            )
        except Exception as e:  # noqa: BLE001
            return Response.proto(wire.ImportResponse(Err=str(e)), status=500)
        return Response.proto(wire.ImportResponse())

    def handle_post_import_view(self, req: Request) -> Response:
        """View-scoped raw sets/clears — the anti-entropy repair path
        for derived (inverse/time) views, which the PQL write fan-out
        cannot target individually (pilosa_tpu extension; the reference
        only repairs the standard view, fragment.go:1443).  Rides the
        internal admission lane: anti-entropy repair is cluster-internal
        traffic and must not starve behind a client-write storm."""
        ticket, shed = self._admit(adm.CLASS_INTERNAL, req)
        if shed is not None:
            return shed
        try:
            return self._handle_post_import_view(req)
        finally:
            if ticket is not None:
                ticket.release()

    def _handle_post_import_view(self, req: Request) -> Response:
        pb = wire.ImportViewRequest()
        try:
            pb.ParseFromString(req.body)
        except Exception as e:  # noqa: BLE001
            return Response.error(str(e), 400)
        if self.cluster is not None and self.executor is not None:
            # Write-ring aware: delta-log replay pushes land on the
            # migration target before (and after) its cutover.
            if not self.cluster.is_write_owner(
                self.executor.host, pb.Index, pb.Slice
            ):
                return Response.error(
                    f"host does not own slice {self.executor.host}"
                    f" slice={pb.Slice}",
                    412,
                )
        f = self.holder.frame(pb.Index, pb.Frame)
        if f is None:
            return Response.error("frame not found", 404)
        if len(pb.RowIDs) != len(pb.ColumnIDs) or len(pb.ClearRowIDs) != len(
            pb.ClearColumnIDs
        ):
            # zip would silently truncate a malformed pair list — reject
            # like Fragment.merge_block does on the read side.
            return Response.error("row/column id length mismatch", 400)
        try:
            view = f.create_view_if_not_exists(pb.View)
            frag = view.create_fragment_if_not_exists(pb.Slice)
            for r, c in zip(pb.RowIDs, pb.ColumnIDs):
                frag.set_bit(int(r), int(c))
            for r, c in zip(pb.ClearRowIDs, pb.ClearColumnIDs):
                frag.clear_bit(int(r), int(c))
        except Exception as e:  # noqa: BLE001
            return Response.proto(wire.ImportResponse(Err=str(e)), status=500)
        return Response.proto(wire.ImportResponse())

    def handle_get_export(self, req: Request) -> Response:
        """CSV export of one fragment (reference: handler.go:1049-1098)."""
        if "text/csv" not in req.header("Accept"):
            return Response.error("not acceptable", 406)
        index = req.query.get("index", "")
        frame = req.query.get("frame", "")
        view = req.query.get("view", "")
        try:
            slice_i = int(req.query.get("slice", ""))
        except ValueError:
            return Response.error("invalid slice", 400)
        if self.cluster is not None and self.executor is not None:
            owners = {n.host for n in self.cluster.fragment_nodes(index, slice_i)}
            if self.executor.host not in owners:
                return Response.error("host does not own slice", 412)
        frag = self.holder.fragment(index, frame, view, slice_i)
        if frag is None:
            return Response.error("fragment not found", 404)
        # Stream the CSV: csv_chunks is a row-block generator and the
        # adapter moves constant-size chunks, so the response never
        # materializes (reference: handler.go:1049-1098 writes rows
        # straight to the ResponseWriter).
        return Response.stream(
            frag.csv_chunks(), "text/csv", chunk_bytes=self.stream_chunk_bytes
        )

    # ------------------------------------------------------------------
    # fragment internals (sync/backup data plane)
    # ------------------------------------------------------------------

    def handle_get_fragment_nodes(self, req: Request) -> Response:
        """Owners of a slice.  ``?write=true`` answers the WRITE target
        set instead — during a rebalance transition that is both rings'
        owners, so import fan-outs dual-write migrating slices."""
        index = req.query.get("index", "")
        try:
            slice_i = int(req.query.get("slice", ""))
        except ValueError:
            return Response.error("invalid slice", 400)
        if req.query.get("write") == "true":
            nodes = self.cluster.write_nodes(index, slice_i)
        else:
            nodes = self.cluster.fragment_nodes(index, slice_i)
        return Response.json([n.to_dict() for n in nodes])

    def _fragment_from_query(self, req: Request):
        index = req.query.get("index", "")
        frame = req.query.get("frame", "")
        view = req.query.get("view", "")
        slice_s = req.query.get("slice", "")
        if not slice_s.isdigit():
            return None, Response.error("slice required", 400)
        frag = self.holder.fragment(index, frame, view, int(slice_s))
        if frag is None:
            return None, Response.error("fragment not found", 404)
        return frag, None

    def handle_get_fragment_data(self, req: Request) -> Response:
        frag, err = self._fragment_from_query(req)
        if err:
            return err
        # Chunked tar stream (reference: handler.go:1102-1123 hands the
        # ResponseWriter to Fragment.WriteTo).
        return Response.stream(
            frag.tar_chunks(chunk_bytes=self.stream_chunk_bytes),
            "application/octet-stream",
            chunk_bytes=self.stream_chunk_bytes,
        )

    @stream_body
    def handle_post_fragment_data(self, req: Request) -> Response:
        """Fragment restore — operator backup/restore AND the rebalance
        bulk-copy arrival path.  Rides the internal admission lane
        (cluster data-plane traffic must not starve behind a client
        write storm); ``?stage=true`` (migration arrivals) hands the
        restored fragment to the HBM staging lane so its mirror
        registers with the PlanePool in the background."""
        ticket, shed = self._admit(adm.CLASS_INTERNAL, req)
        if shed is not None:
            return shed
        try:
            index = req.query.get("index", "")
            frame = req.query.get("frame", "")
            view = req.query.get("view", "")
            slice_s = req.query.get("slice", "")
            if not slice_s.isdigit():
                return Response.error("slice required", 400)
            f = self.holder.frame(index, frame)
            if f is None:
                return Response.error("frame not found", 404)
            from pilosa_tpu.core.fragment import ArchiveChecksumError

            vw = f.create_view_if_not_exists(view)
            frag = vw.create_fragment_if_not_exists(int(slice_s))
            # The tar reader pulls straight off the request body stream;
            # payloads verify against the archive's embedded checksums
            # before anything installs (core/fragment.read_from).
            try:
                frag.read_from(req.body_reader())
            except ArchiveChecksumError as e:
                # Torn bytes rejected with a NAMED failure — the sender
                # must not believe a corrupt restore succeeded.
                return Response.error(str(e), 422)
            if req.query.get("stage") == "true" and self.prefetcher is not None:
                self.prefetcher.stage([frag])
            return Response.json({})
        finally:
            if ticket is not None:
                ticket.release()

    def handle_get_fragment_blocks(self, req: Request) -> Response:
        frag, err = self._fragment_from_query(req)
        if err:
            return err
        blocks = [
            {"id": bid, "checksum": base64.b64encode(chk).decode()}
            for bid, chk in frag.blocks()
        ]
        return Response.json({"blocks": blocks})

    def handle_get_fragment_block_data(self, req: Request) -> Response:
        """protobuf in/out (reference: handler.go:1213-1246)."""
        pb = wire.BlockDataRequest()
        try:
            pb.ParseFromString(req.body)
        except Exception as e:  # noqa: BLE001
            return Response.error(str(e), 400)
        frag = self.holder.fragment(pb.Index, pb.Frame, pb.View, pb.Slice)
        if frag is None:
            return Response.error("fragment not found", 404)
        ps = frag.block_data(pb.Block)
        out = wire.BlockDataResponse()
        out.RowIDs.extend(int(r) for r in ps.row_ids)
        out.ColumnIDs.extend(int(c) for c in ps.column_ids)
        return Response.proto(out)

    # ------------------------------------------------------------------
    # elastic cluster: resize / topology events / migration data plane
    # ------------------------------------------------------------------

    def handle_post_resize(self, req: Request) -> Response:
        """Operator entry: start (or resume) a live resize.  Body:
        ``{"hosts": ["h1:p", "h2:p", ...]}`` — the COMPLETE target host
        list (grow = current + new, drain = current - leaving).  The
        receiving node becomes the migration coordinator; progress at
        GET /debug/rebalance."""
        if self.rebalance is None:
            return Response.error("rebalance not configured", 501)
        try:
            payload = json.loads(req.body or b"{}")
        except json.JSONDecodeError as e:
            return Response.error(str(e), 400)
        hosts = payload.get("hosts")
        if not isinstance(hosts, list) or not all(
            isinstance(h, str) and h for h in hosts
        ):
            return Response.error("hosts must be a non-empty string list", 400)
        try:
            return Response.json(self.rebalance.start_resize(hosts))
        except Exception as e:  # noqa: BLE001 — operator boundary
            return Response.error(str(e), 409)

    def handle_post_resize_abort(self, req: Request) -> Response:
        if self.rebalance is None:
            return Response.error("rebalance not configured", 501)
        try:
            return Response.json(self.rebalance.abort())
        except Exception as e:  # noqa: BLE001 — operator boundary
            return Response.error(str(e), 409)

    def handle_post_topology(self, req: Request) -> Response:
        """Internal fan-out target for topology events (begin / flip /
        unflip / commit / abort) — rides the internal admission lane so
        cutover control can never starve behind client traffic."""
        if self.rebalance is None:
            return Response.error("rebalance not configured", 501)
        ticket, shed = self._admit(adm.CLASS_INTERNAL, req)
        if shed is not None:
            return shed
        try:
            payload = json.loads(req.body or b"{}")
            return Response.json(self.rebalance.apply_event(payload))
        except Exception as e:  # noqa: BLE001 — peer boundary
            return Response.error(str(e), 400)
        finally:
            if ticket is not None:
                ticket.release()

    def handle_post_rebalance_delta(self, req: Request) -> Response:
        """Internal migration control on a SOURCE (or checksum on any
        node): start/stop the slice's delta log, bulk-copy the slice's
        fragments to a target, replay the drained log, or report
        per-view checksums.  Internal admission lane."""
        if self.rebalance is None:
            return Response.error("rebalance not configured", 501)
        ticket, shed = self._admit(adm.CLASS_INTERNAL, req)
        if shed is not None:
            return shed
        try:
            payload = json.loads(req.body or b"{}")
            return Response.json(self.rebalance.delta_action(payload))
        except Exception as e:  # noqa: BLE001 — peer boundary
            return Response.error(str(e), 400)
        finally:
            if ticket is not None:
                ticket.release()

    def handle_post_rebalance_release(self, req: Request) -> Response:
        """Internal: drop a migrated-away slice's fragments (HBM + disk
        returned).  Refused while this node still owns the slice."""
        if self.rebalance is None:
            return Response.error("rebalance not configured", 501)
        ticket, shed = self._admit(adm.CLASS_INTERNAL, req)
        if shed is not None:
            return shed
        try:
            payload = json.loads(req.body or b"{}")
            return Response.json(
                self.rebalance.release_slice(
                    str(payload.get("index", "")), int(payload.get("slice", 0))
                )
            )
        except Exception as e:  # noqa: BLE001 — peer boundary
            return Response.error(str(e), 409)
        finally:
            if ticket is not None:
                ticket.release()

    def handle_post_tier_restore(self, req: Request) -> Response:
        """Store-riding rebalance bulk copy, target side: restore one
        fragment from THIS node's configured object store instead of a
        peer stream (the source verified the store copy's checksum is
        fresh first).  Internal admission lane; 501 without a
        configured tier so the source falls back to streaming."""
        if self.tier is None:
            return Response.error("tier not configured", 501)
        ticket, shed = self._admit(adm.CLASS_INTERNAL, req)
        if shed is not None:
            return shed
        try:
            payload = json.loads(req.body or b"{}")
            nbytes = self.tier.restore_from_store(
                str(payload.get("index", "")),
                str(payload.get("frame", "")),
                str(payload.get("view", "")),
                int(payload.get("slice", 0)),
            )
            if self.prefetcher is not None:
                frag = self.holder.fragment(
                    str(payload.get("index", "")),
                    str(payload.get("frame", "")),
                    str(payload.get("view", "")),
                    int(payload.get("slice", 0)),
                )
                if frag is not None:
                    self.prefetcher.stage([frag])
            return Response.json({"bytes": nbytes})
        except Exception as e:  # noqa: BLE001 — peer boundary
            return Response.error(str(e), 409)
        finally:
            if ticket is not None:
                ticket.release()

    def handle_get_tier(self, req: Request) -> Response:
        """Tiered-storage observability: per-fragment state (+ the
        cold→hydrating→hot transition history), counts by state, disk
        usage vs budget, retention config, and the store client's
        health."""
        if self.tier is None:
            return Response.json(
                {"fragments": {}, "note": "tier not configured"}
            )
        return Response.json(self.tier.snapshot())

    def handle_get_ingest(self, req: Request) -> Response:
        """Durable-ingest observability: WAL group-commit state (per-
        fragment segment sizes, buffered ops, last fsync latency and
        batch size), replay history, and the device delta-scatter
        counters (launches / updates applied / fallback invalidations)."""
        from pilosa_tpu.ingest import scatter as ingest_scatter

        doc = {
            "scatter": dict(ingest_scatter.counters()),
            "scatterEnabled": bool(ingest_scatter.ENABLED),
        }
        if self.ingest is None:
            doc["wal"] = {"walEnabled": False, "note": "ingest WAL not configured"}
        else:
            doc["wal"] = self.ingest.snapshot()
        return Response.json(doc)

    # ------------------------------------------------------------------
    # quorum replication: versions / hints / replay
    # ------------------------------------------------------------------

    def handle_post_replicate_versions(self, req: Request) -> Response:
        """Per-slice write versions — the read path's staleness probe.
        Body ``{"index", "slices": [...]}`` answers the versions map;
        ``{"action": "observe", "index", "slice", "version"}`` stamps
        the slice's version forward (max-merge, post-repair marker).
        Internal admission lane (replication control traffic)."""
        if self.replication is None:
            return Response.error("replication not configured", 501)
        ticket, shed = self._admit(adm.CLASS_INTERNAL, req)
        if shed is not None:
            return shed
        try:
            payload = json.loads(req.body or b"{}")
            index = str(payload.get("index", ""))
            if payload.get("action") == "observe":
                v = self.replication.versions.observe(
                    index,
                    int(payload.get("slice", 0)),
                    int(payload.get("version", 0)),
                )
                return Response.json({"ok": True, "version": v})
            slices = payload.get("slices") or []
            return Response.json(
                {
                    "versions": {
                        str(s): v
                        for s, v in self.replication.versions.get_many(
                            index, slices
                        ).items()
                    }
                }
            )
        except (TypeError, ValueError, json.JSONDecodeError) as e:
            return Response.error(str(e), 400)
        finally:
            if ticket is not None:
                ticket.release()

    def handle_post_replicate_hint(self, req: Request) -> Response:
        """Queue a write payload on THIS node as a hint destined for
        an unreachable replica (hinted handoff; the client-side import
        fan-out posts here when a replica is down).  Body ``{"target",
        "index", "slice", "kind": import|import-value|pql,
        "payload"(b64)|"query", "rows"}``.  Internal lane."""
        if self.replication is None:
            return Response.error("replication not configured", 501)
        ticket, shed = self._admit(adm.CLASS_INTERNAL, req)
        if shed is not None:
            return shed
        try:
            payload = json.loads(req.body or b"{}")
            target = str(payload.get("target", ""))
            index = str(payload.get("index", ""))
            slice_i = int(payload.get("slice", 0))
            kind = str(payload.get("kind", ""))
            if not target or not index:
                return Response.error("target and index required", 400)
            if kind == "pql":
                queued = self.replication.hints.queue_pql(
                    target, index, slice_i, str(payload.get("query", ""))
                )
            else:
                queued = self.replication.hints.queue_payload(
                    target,
                    index,
                    slice_i,
                    kind,
                    base64.b64decode(payload.get("payload", "")),
                    int(payload.get("rows", 1)),
                )
            if queued:
                self.replication.stats.count(
                    "cluster.replication.hintsQueued"
                )
            return Response.json({"queued": bool(queued)})
        except (TypeError, ValueError, json.JSONDecodeError) as e:
            return Response.error(str(e), 400)
        finally:
            if ticket is not None:
                ticket.release()

    def handle_post_replicate_replay(self, req: Request) -> Response:
        """Force a synchronous hint replay (ops/test convenience —
        the background replayer normally triggers off the target's
        breaker transition).  Body ``{"target"?: host}``; answers the
        per-target replayed-entry counts.  Internal lane."""
        if self.replication is None:
            return Response.error("replication not configured", 501)
        ticket, shed = self._admit(adm.CLASS_INTERNAL, req)
        if shed is not None:
            return shed
        try:
            payload = json.loads(req.body or b"{}")
            return Response.json(
                {
                    "replayed": self.replication.replay_now(
                        str(payload.get("target", "")) or None
                    )
                }
            )
        except (TypeError, ValueError, json.JSONDecodeError) as e:
            return Response.error(str(e), 400)
        finally:
            if ticket is not None:
                ticket.release()

    # ------------------------------------------------------------------
    # standing queries (pilosa_tpu/subscribe)
    # ------------------------------------------------------------------

    def handle_post_subscribe(self, req: Request) -> Response:
        """Register a standing query.  Body: JSON ``{"index": ...,
        "query": "Subscribe(Count(...))"}``.  Returns the subscription
        id plus the registration snapshot (version 1) — clients then
        stream or long-poll from that version.  The registration
        evaluation rides the dedicated subscribe admission lane."""
        if self.subscribe is None:
            return Response.error("subscribe not configured", 501)
        ticket, shed = self._admit(adm.CLASS_SUBSCRIBE, req)
        if shed is not None:
            return shed
        try:
            try:
                payload = json.loads(req.body or b"{}")
            except ValueError as e:
                return Response.error(f"bad request body: {e}", 400)
            if not isinstance(payload, dict):
                return Response.error("bad request body: expected object", 400)
            index = payload.get("index") or req.query.get("index", "")
            query = payload.get("query", "")
            if not index or not query:
                return Response.error("index and query required", 400)
            try:
                sub = self.subscribe.register(index, query)
            except (
                subscribe_reg.SubscribeError,
                ParseError,
                plan_mod.PlanError,
                ExecutorError,
            ) as e:
                # Registration compiles AND snapshot-evaluates the
                # expression, so executor-level rejections (unknown
                # field, bad Range bounds) are client errors here.
                return Response.error(str(e), 400)
            return Response.json(
                {
                    "id": sub.id,
                    "index": sub.index,
                    "kind": sub.kind,
                    "version": sub.version,
                    "epoch": sub.epoch,
                    "value": sub.value_json,
                },
                status=201,
            )
        finally:
            if ticket is not None:
                ticket.release()

    def _subscription_for(self, sid: str):
        if self.subscribe is None:
            return None, Response.error("subscribe not configured", 501)
        sub = self.subscribe.get(sid)
        if sub is None:
            return None, Response.error(f"no such subscription: {sid}", 404)
        return sub, None

    def handle_get_subscribe_stream(self, req: Request, sid: str) -> Response:
        """SSE delivery: every retained update newer than ``?after=``
        (version-monotonic, at-least-once), then live updates as
        notification batches publish them; keepalive comments while
        idle.  The wait itself holds no admission slot — evaluation
        already paid on the notifier's lane."""
        sub, err = self._subscription_for(sid)
        if err is not None:
            return err
        try:
            after = int(req.query.get("after", "0"))
        except ValueError:
            return Response.error("invalid after", 400)
        gen = sse_mod.event_stream(self.subscribe, sub, after)
        return Response(
            body_iter=sse_mod.EventBody(gen),
            content_type=sse_mod.CONTENT_TYPE,
        )

    def handle_get_subscribe_poll(self, req: Request, sid: str) -> Response:
        """Long-poll delivery: block until the subscription moves past
        ``?after=`` or ``?timeout_ms=`` elapses (bounded).  A timeout
        answers 200 with ``"timeout": true`` so clients distinguish
        quiet from gone (410 = unsubscribed mid-wait)."""
        sub, err = self._subscription_for(sid)
        if err is not None:
            return err
        try:
            after = int(req.query.get("after", "0"))
            timeout_ms = float(req.query.get("timeout_ms", "30000"))
        except ValueError:
            return Response.error("invalid after/timeout_ms", 400)
        timeout_ms = max(0.0, min(timeout_ms, 120_000.0))
        upd = self.subscribe.wait_update(sub, after, timeout=timeout_ms / 1000.0)
        if upd is None:
            if sub.closed:
                return Response.error("subscription closed", 410)
            return Response.json(
                {"id": sub.id, "version": after, "timeout": True}
            )
        return Response.json(upd)

    def handle_delete_subscribe(self, req: Request, sid: str) -> Response:
        if self.subscribe is None:
            return Response.error("subscribe not configured", 501)
        if not self.subscribe.unregister(sid):
            return Response.error(f"no such subscription: {sid}", 404)
        return Response.json({"unsubscribed": sid})

    def handle_get_subscriptions(self, req: Request) -> Response:
        """Standing-query observability: registry size, pending delta
        backlog, notification lag percentiles, lifetime counters, and
        the first page of subscriptions."""
        if self.subscribe is None:
            return Response.json(
                {"count": 0, "note": "subscribe not configured"}
            )
        return Response.json(self.subscribe.snapshot())

    def handle_get_replication(self, req: Request) -> Response:
        """Replication observability: consistency defaults, per-replica
        hint backlog (entries/bits/slices, last replay outcome), local
        per-slice write versions, and the replayer's state."""
        if self.replication is None:
            return Response.json(
                {"hints": {}, "note": "replication not configured"}
            )
        return Response.json(self.replication.snapshot())

    def handle_get_rebalance(self, req: Request) -> Response:
        """Migration observability: topology epoch + transition, the
        coordinator's per-slice state machine, delta-log occupancy, and
        gossip join candidates."""
        if self.rebalance is None:
            return Response.json(
                {
                    "transition": None,
                    "running": False,
                    "note": "rebalance not configured",
                }
            )
        return Response.json(self.rebalance.snapshot())

    # ------------------------------------------------------------------
    # debug
    # ------------------------------------------------------------------

    def handle_get_vars(self, req: Request) -> Response:
        """expvar equivalent (reference: handler.go:1360-1374)."""
        payload: dict[str, Any] = {
            "uptime_seconds": time.time() - self._start_time,
            "version": self.version,
            "threads": threading.active_count(),
        }
        if self.stats is not None and hasattr(self.stats, "snapshot"):
            payload["stats"] = self.stats.snapshot()
        return Response.json(payload)

    def handle_get_health(self, req: Request) -> Response:
        """Cluster-resilience view of this node: per-host circuit
        breaker states (closed/open/half-open, consecutive failures,
        opens), the retry policy, the default query deadline, and the
        membership-level node states."""
        out: dict[str, Any] = {"node": getattr(self.executor, "host", "")}
        if self.cluster is not None:
            out["nodes"] = [
                {"host": h, "state": s}
                for h, s in sorted(self.cluster.node_states().items())
            ]
        if self.resilience is not None:
            out.update(self.resilience.snapshot())
        if self.admission is not None:
            # Per-class gate state: concurrency/queue bounds, live
            # occupancy, EWMA service time, admitted/shed totals.
            out["admission"] = self.admission.snapshot()
        dh = getattr(self.executor, "device_health", None)
        if dh is not None:
            # Device-health state machine (device/health.py): per-path
            # healthy/suspect/quarantined, watchdog trips, and the
            # node-level degraded flag peers see via gossip.
            out["device"] = dh.snapshot()
        return Response.json(out)

    def handle_get_tenants(self, req: Request) -> Response:
        """The per-tenant QoS table (net/admission.py TenantRegistry):
        weight, admitted/shed/quota-shed counters, queue-wait EWMA, and
        live quota headroom per tenant, plus the per-class queue split
        when any gate has tenants backlogged.  The operator's first
        stop during a noisy-neighbor incident."""
        if self.tenants is None:
            return Response.json({"tenants": {}})
        out: dict = {
            "defaultTenant": self.tenants.default_tenant,
            "tenants": self.tenants.snapshot(),
        }
        if self.admission is not None:
            queued = {}
            for cls, snap in self.admission.snapshot().items():
                by = snap.get("queuedByTenant")
                if by:
                    queued[cls] = by
            if queued:
                out["queuedByClass"] = queued
        return Response.json(out)

    def handle_get_hbm(self, req: Request) -> Response:
        """HBM residency (device/pool.py): per-device budget / resident
        / pinned / high-water bytes with each device's LRU-ordered
        entries, a per-fragment residency table, and the eviction /
        prefetch counters."""
        from pilosa_tpu import device as device_mod

        return Response.json(device_mod.pool().snapshot())

    def handle_get_traces(self, req: Request) -> Response:
        """The tracer's retained query traces as JSON; ``?min_ms=``
        filters on trace (root span) duration."""
        try:
            min_ms = float(req.query.get("min_ms", "0"))
        except ValueError:
            return Response.error("invalid min_ms", 400)
        return Response.json({"traces": self.tracer.traces(min_ms=min_ms)})

    def handle_get_metrics(self, req: Request) -> Response:
        """Prometheus text exposition of the Expvar store plus process
        gauges (obs/prom.py)."""
        snap: dict = {}
        if self.stats is not None and hasattr(self.stats, "snapshot"):
            try:
                snap = self.stats.snapshot()
            except Exception:  # noqa: BLE001 — stats must not fail the scrape
                snap = {}
        self._inject_program_cache_gauges(snap)
        if self.admission is not None:
            # Scrape-time admission gauges (active/queued/concurrency/
            # EWMA per class) — like the program-cache gauges, they
            # must render even without a stats backend.
            try:
                snap.setdefault("gauges", {}).update(self.admission.gauges())
            except Exception:  # noqa: BLE001 — stats must not fail the scrape
                pass
        dh = getattr(self.executor, "device_health", None)
        if dh is not None:
            # Scrape-time device-health gauges (device.health.state per
            # path, device.health.degraded, device.watchdogTrips).
            try:
                snap.setdefault("gauges", {}).update(dh.gauges())
            except Exception:  # noqa: BLE001 — stats must not fail the scrape
                pass
        if self.subscribe is not None:
            # Scrape-time standing-query gauges (active subscriptions,
            # pending delta bits).
            try:
                snap.setdefault("gauges", {}).update(self.subscribe.gauges())
            except Exception:  # noqa: BLE001 — stats must not fail the scrape
                pass
        # Scrape-time launch-telemetry gauges (per-site GB/s, % of the
        # probed stream floor) — injected like the program-cache ones.
        try:
            snap.setdefault("gauges", {}).update(perf_mod.registry().gauges())
        except Exception:  # noqa: BLE001 — stats must not fail the scrape
            pass
        body = prom.render(
            snap,
            extra_gauges={
                "uptime_seconds": time.time() - self._start_time,
                "threads": threading.active_count(),
            },
        )
        # Native histogram families (query latency per class, HTTP
        # latency per route) + SLO gauges render their own exposition
        # block — bucketed cumulative counters, not summaries.
        try:
            body += self.latency.render()
        except Exception:  # noqa: BLE001 — stats must not fail the scrape
            pass
        return Response(body=body.encode(), content_type=prom.CONTENT_TYPE)

    @staticmethod
    def _inject_program_cache_gauges(snap: dict) -> None:
        """Scrape-time ``exec.programCache.entries`` gauge — total plus
        one ``cache:<family>`` label per jit wrapper family (exec/plan.py
        program_cache_stats): the observability prerequisite for capping
        compiled-program cardinality (ROADMAP 2a).  Injected into the
        snapshot (not the stats store), so it renders on every scrape
        even when the node runs without a stats backend.  Same-depth-
        bucket BSI queries sharing one program per op kind is asserted
        against exactly this gauge."""
        try:
            from pilosa_tpu.exec import plan as plan_mod

            stats = plan_mod.program_cache_stats()
            gauges = snap.setdefault("gauges", {})
            gauges["exec.programCache.entries"] = stats.pop("total")
            for family, n in stats.items():
                gauges[f"exec.programCache.entries[cache:{family}]"] = n
            # Hard per-family cardinality bounds implied by the pow2
            # bucket grids (entries <= bound is an invariant; a breach
            # means a caller stopped canonicalizing its compile key).
            bounds = plan_mod.program_cache_bounds()
            gauges["exec.programCache.bound"] = sum(bounds.values())
            for family, n in bounds.items():
                gauges[f"exec.programCache.bound[cache:{family}]"] = n
            # Cumulative compile-bearing first-call wall ms per family:
            # how much of this process's life went to XLA compilation.
            for family, ms in plan_mod.program_cache_compile_ms().items():
                gauges[f"exec.programCache.compileMs[cache:{family}]"] = ms
        except Exception:  # noqa: BLE001 — stats must not fail the scrape
            pass

    def handle_get_perf(self, req: Request) -> Response:
        """The launch-telemetry roofline table (obs/perf.py): per-site
        launches, logical bytes streamed, achieved GB/s, % of the
        probed stream floor, p50/p99 launch ms, batch occupancy — plus
        the slowest recent launches with their trace ids (feed one to
        ``/debug/traces`` for the full span breakdown) and cumulative
        per-family compile ms."""
        snap = perf_mod.registry().snapshot()
        try:
            snap["compile_ms"] = plan_mod.program_cache_compile_ms()
        except Exception:  # noqa: BLE001 — introspection must not fail
            snap["compile_ms"] = {}
        return Response.json(snap)

    def handle_get_stacks(self, req: Request) -> Response:
        """All thread stacks via ``sys._current_frames`` — the
        wedge-diagnosis companion to the PR-15 launch watchdog: when a
        device call hangs, this shows WHERE every thread is stuck
        without attaching a debugger.  (Alias of the pprof "goroutine"
        dump under a first-class route.)"""
        frames = sys._current_frames()
        out = io.StringIO()
        out.write(f"{len(frames)} threads\n\n")
        for t in threading.enumerate():
            out.write(f"thread {t.name} id={t.ident} (daemon={t.daemon})\n")
            fr = frames.get(t.ident)
            if fr is not None:
                out.write("".join(traceback.format_stack(fr)))
            out.write("\n")
        return Response(body=out.getvalue().encode(), content_type="text/plain")

    def handle_get_profile(self, req: Request) -> Response:
        """On-demand device profile: wraps ``jax.profiler.trace`` for
        ``?seconds=N`` (clamped to 60), tars the trace directory under
        the data dir, and returns its path.  Single-flight — a second
        concurrent request answers 409; a runtime without the profiler
        answers 501 (the capture is optional, the endpoint is not)."""
        try:
            seconds = max(0.05, min(float(req.query.get("seconds", "3")), 60.0))
        except ValueError:
            return Response.error("invalid seconds", 400)
        profiler = _jax_profiler()
        if profiler is None:
            return Response.error("jax profiler unavailable", 501)
        if not self._profile_mu.acquire(blocking=False):
            return Response.error("profile already in flight", 409)
        try:
            base = self.profile_dir or tempfile.mkdtemp(
                prefix="pilosa-profile-"
            )
            trace_dir = os.path.join(
                base, "profiles",
                time.strftime("trace-%Y%m%d-%H%M%S"),
            )
            os.makedirs(trace_dir, exist_ok=True)
            try:
                with profiler.trace(trace_dir):
                    time.sleep(seconds)
            except Exception as e:  # noqa: BLE001 — backend without xprof
                shutil.rmtree(trace_dir, ignore_errors=True)
                return Response.error(f"jax profiler unavailable: {e}", 501)
            tar_path = trace_dir + ".tar.gz"
            with tarfile.open(tar_path, "w:gz") as tf:
                tf.add(trace_dir, arcname=os.path.basename(trace_dir))
            shutil.rmtree(trace_dir, ignore_errors=True)
            return Response.json(
                {
                    "seconds": seconds,
                    "trace": tar_path,
                    "bytes": os.path.getsize(tar_path),
                }
            )
        finally:
            self._profile_mu.release()

    def handle_get_pprof(self, req: Request, rest: str | None = None) -> Response:
        """Profiling endpoints — the Python analog of the reference's
        net/http/pprof mount (reference: handler.go:111-112):

        * ``/debug/pprof`` or ``/goroutine`` — live thread-stack dump;
        * ``/debug/pprof/profile?seconds=N`` — statistical CPU profile:
          samples every thread's stack at ~100 Hz for N seconds (default
          5, max 60) and returns folded stacks ("f1;f2;f3 count"), the
          flamegraph-ready equivalent of the pprof CPU profile;
        * ``/debug/pprof/heap`` — tracemalloc top allocations
          (``?start=1`` begins tracing, ``?stop=1`` ends it).
        """
        kind = (rest or "/").strip("/") or "goroutine"
        if kind == "goroutine":
            frames = sys._current_frames()
            out = io.StringIO()
            for t in threading.enumerate():
                out.write(f"thread {t.name} (daemon={t.daemon})\n")
                fr = frames.get(t.ident)
                if fr is not None:
                    out.write("".join(traceback.format_stack(fr)))
                out.write("\n")
            return Response(body=out.getvalue().encode(), content_type="text/plain")
        if kind == "profile":
            try:
                seconds = min(float(req.query.get("seconds", "5")), 60.0)
            except ValueError:
                return Response.error("invalid seconds", 400)
            folded = _sample_cpu_profile(seconds)
            return Response(body=folded.encode(), content_type="text/plain")
        if kind == "heap":
            import tracemalloc

            if req.query.get("start"):
                tracemalloc.start(16)
                return Response(body=b"tracemalloc started\n",
                                content_type="text/plain")
            if req.query.get("stop"):
                tracemalloc.stop()
                return Response(body=b"tracemalloc stopped\n",
                                content_type="text/plain")
            if not tracemalloc.is_tracing():
                return Response(
                    body=b"tracemalloc not tracing; GET ?start=1 first\n",
                    content_type="text/plain",
                )
            snap = tracemalloc.take_snapshot()
            out = io.StringIO()
            for stat in snap.statistics("lineno")[:50]:
                out.write(f"{stat}\n")
            return Response(body=out.getvalue().encode(), content_type="text/plain")
        return Response.error(f"unknown profile: {kind}", 404)

    # ------------------------------------------------------------------
    # helpers
    # ------------------------------------------------------------------

    def _attr_diff(self, req: Request, store) -> Response:
        """Shared column/row attr-diff logic (reference:
        handler.go:514-570, 782-838)."""
        try:
            payload = json.loads(req.body)
        except json.JSONDecodeError as e:
            return Response.error(str(e), 400)
        remote_blocks = [
            (b["id"], base64.b64decode(b["checksum"]))
            for b in payload.get("blocks", [])
        ]
        local_blocks = store.blocks()
        diff_ids = attr_mod.diff_blocks(local_blocks, remote_blocks)
        attrs: dict[str, dict] = {}
        for bid in diff_ids:
            for id_, a in store.block_data(bid).items():
                attrs[str(id_)] = a
        return Response.json({"attrs": attrs})

    def _broadcast(self, msg) -> None:
        if self.broadcaster is not None:
            try:
                self.broadcaster.send_sync(msg)
            except Exception as e:  # noqa: BLE001 — broadcast is best-effort
                self.logger(f"broadcast error: {e}")


def _jax_profiler():
    """Resolve ``jax.profiler`` (None when absent or without ``trace``)
    — separated out so the /debug/profile 501 path is testable by
    monkeypatching."""
    try:
        from jax import profiler
    except Exception:  # noqa: BLE001 — stub/absent jax
        return None
    return profiler if hasattr(profiler, "trace") else None


def _consistency_arg(req: Request, header: str, param: str) -> str:
    """A per-request consistency override: the header wins over the
    query param; "" means the server default; anything else must be a
    valid level (raises ValueError -> 400)."""
    raw = req.header(header) or req.query.get(param, "")
    if not raw:
        return ""
    return replicate_mod.validate_level(raw, param)


def _coalesce_batch_stats(record: dict) -> dict | None:
    """Aggregate the coalescer's batch stats from a trace's ``coalesce``
    spans (exec/coalesce.py annotates each with its launch's occupancy)
    — the slow-query line's evidence of whether a slow query rode a
    shared launch and how full it was.  None when the query never hit
    the coalescer."""
    spans = [s for s in record.get("spans", ()) if s.get("name") == "coalesce"]
    occ = [
        s["tags"]["batch_queries"]
        for s in spans
        if isinstance(s.get("tags", {}).get("batch_queries"), (int, float))
    ]
    if not spans:
        return None
    out: dict = {"launches": len(spans)}
    if occ:
        out["mean_occupancy"] = round(sum(occ) / len(occ), 2)
        out["max_occupancy"] = max(occ)
    return out


def _fuse_batch_stats(record: dict) -> dict | None:
    """Aggregate multi-query-fusion composition from a trace's ``fuse``
    spans (executor._coalesce_eval emits one per fused launch the query
    rode, tagged with tree count / op count / subtree-dedup hits) —
    the slow-query line's evidence that a slow query shared an
    interpreter pass, and with how many distinct trees.  None when the
    query never fused."""
    spans = [s for s in record.get("spans", ()) if s.get("name") == "fuse"]
    if not spans:
        return None
    out: dict = {"launches": len(spans)}
    for tag, label in (
        ("batch_queries", "mean_fused_queries"),
        ("programs", "mean_programs"),
        ("ops", "mean_ops"),
        ("dedup_hits", "mean_dedup_hits"),
    ):
        vals = [
            s["tags"][tag]
            for s in spans
            if isinstance(s.get("tags", {}).get(tag), (int, float))
        ]
        if vals:
            out[label] = round(sum(vals) / len(vals), 2)
    return out


def _sample_cpu_counts(
    seconds: float,
    hz: float = 100.0,
    stop: "threading.Event | None" = None,
    counts: "dict[str, int] | None" = None,
) -> dict[str, int]:
    """Sample every thread's stack at ``hz`` for up to ``seconds``
    (``stop`` cuts the run short), accumulating folded-stack sample
    counts into ``counts`` in place so a caller on another thread can
    snapshot mid-run."""
    if counts is None:
        counts = {}
    me = threading.get_ident()
    deadline = time.monotonic() + seconds
    interval = 1.0 / hz
    while time.monotonic() < deadline and not (stop is not None and stop.is_set()):
        for tid, frame in sys._current_frames().items():
            if tid == me:
                continue  # don't profile the profiler
            parts = []
            f = frame
            while f is not None:
                code = f.f_code
                parts.append(f"{code.co_name} ({code.co_filename}:{f.f_lineno})")
                f = f.f_back
            stack = ";".join(reversed(parts)) or "<idle>"
            counts[stack] = counts.get(stack, 0) + 1
        time.sleep(interval)
    return counts


def _fold_counts(counts: dict[str, int]) -> str:
    lines = [
        f"{stack} {n}"
        for stack, n in sorted(counts.items(), key=lambda kv: -kv[1])
    ]
    return "\n".join(lines) + ("\n" if lines else "")


def _sample_cpu_profile(seconds: float, hz: float = 100.0) -> str:
    """Statistical whole-process CPU profile: sample for ``seconds`` and
    fold identical stacks into "frame1;frame2;... count" lines
    (most-sampled first) — the flamegraph-collapsed equivalent of the
    reference's pprof CPU profile endpoint."""
    return _fold_counts(_sample_cpu_counts(seconds, hz))


def _frame_meta_proto(f) -> wire.FrameMeta:
    return wire.FrameMeta(
        RowLabel=f.row_label,
        InverseEnabled=f.inverse_enabled,
        CacheType=f.cache_type,
        CacheSize=f.cache_size,
        TimeQuantum=f.time_quantum,
    )


def _dt_from_unix(ts: int):
    """ImportRequest timestamps are Unix *nanoseconds* (reference:
    ctl/import.go:157 stores t.UnixNano())."""
    from datetime import datetime, timezone

    return datetime.fromtimestamp(ts / 1e9, tz=timezone.utc).replace(tzinfo=None)


# ---------------------------------------------------------------------------
# stdlib HTTP adapter
# ---------------------------------------------------------------------------


def make_http_server(handler: Handler, host: str = "127.0.0.1", port: int = 0):
    """Mount a Handler on a ThreadingHTTPServer; returns the server
    (call .serve_forever() in a thread; .server_address has the bound
    port when port=0).

    Bodies stream in both directions: chunked (or Content-Length)
    request bodies reach streaming routes as an incremental reader, and
    a Response.body_iter goes out with chunked transfer encoding in
    constant-size writes — no large body is ever held whole.
    """

    class _Adapter(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def _run(self):
            parsed = urllib.parse.urlsplit(self.path)
            query = dict(urllib.parse.parse_qsl(parsed.query))
            te = (self.headers.get("Transfer-Encoding") or "").lower()
            if "chunked" in te:
                body_stream = stream_mod.ChunkedBodyReader(self.rfile)
            else:
                length = int(self.headers.get("Content-Length") or 0)
                body_stream = stream_mod.LengthBodyReader(self.rfile, length)
            req = Request(
                method=self.command,
                path=parsed.path,
                query=query,
                headers={k.lower(): v for k, v in self.headers.items()},
                stream=body_stream,
            )
            resp = handler.dispatch(req)
            # Unread request bytes must leave the socket before the
            # response for keep-alive framing to survive; a huge
            # abandoned body drops the connection instead.
            try:
                if not body_stream.drain():
                    self.close_connection = True
            except (OSError, ValueError):
                self.close_connection = True
            # Streamed request bodies count toward the bytes-moved
            # surface (reads already happened inside the route).
            received = getattr(body_stream, "bytes_read", 0)
            if received:
                self._count_stream_bytes("stream.bytesReceived", received)
            if resp.body_iter is not None:
                self._send_stream(resp)
            else:
                self.send_response(resp.status)
                self.send_header("Content-Type", resp.content_type)
                for k, v in resp.headers.items():
                    self.send_header(k, v)
                self.send_header("Content-Length", str(len(resp.body)))
                self.end_headers()
                self.wfile.write(resp.body)

        def _count_stream_bytes(self, name: str, n: int) -> None:
            if handler.stats is None or n <= 0:
                return
            try:
                handler.stats.count(name, n)
            except Exception:  # noqa: BLE001 — stats never break transport
                pass

        def _send_stream(self, resp: Response) -> None:
            self.send_response(resp.status)
            self.send_header("Content-Type", resp.content_type)
            for k, v in resp.headers.items():
                self.send_header(k, v)
            self.send_header("Transfer-Encoding", "chunked")
            self.end_headers()
            sent = 0
            try:
                for chunk in resp.body_iter:
                    if chunk:
                        self.wfile.write(stream_mod.encode_chunk(chunk))
                        sent += len(chunk)
                self.wfile.write(stream_mod.CHUNK_TERMINATOR)
            except (BrokenPipeError, ConnectionResetError):
                self.close_connection = True
            except Exception as e:  # noqa: BLE001 — mid-stream producer error
                # Headers are gone; all we can do is truncate the
                # chunked body (no terminator => client sees an error)
                # and log.
                handler.logger(f"stream error {self.path}: {e}")
                self.close_connection = True
            finally:
                self._count_stream_bytes("stream.bytesSent", sent)
                close = getattr(resp.body_iter, "close", None)
                if close is not None:
                    close()

        do_GET = do_POST = do_DELETE = do_PATCH = _run

        def log_message(self, fmt, *args):  # quiet
            pass

    return ThreadingHTTPServer((host, port), _Adapter)
