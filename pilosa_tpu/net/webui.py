"""Embedded web console.

A single-page admin console served from `/` — the counterpart of the
reference's statik-embedded WebUI (reference: webui/index.html,
webui/assets/main.js, handler.go:169-182), re-written from scratch with
the same feature surface:

* **Console pane** — a PQL REPL over POST /index/<i>/query with command
  history (Up/Down, preserving the edit buffer), Enter-to-run
  (Shift+Enter for newline), Tab completion of PQL keywords plus
  schema-derived index/frame names, per-result cards (input, source,
  status, latency, pretty JSON), and getting-started hints on
  index/frame-not-found errors.
* **Meta commands** — ``:create index <name> [opt=v ...]``,
  ``:create frame <name> [opt=v ...]``, ``:delete index|frame <name>``,
  ``:use <index>``, ``:help`` — driving the REST schema endpoints
  (reference: parse_query/parse_options in webui/assets/main.js).
* **Cluster pane** — node table (host, state) from /status and a
  per-index schema browser (frames with rowLabel / cacheType /
  cacheSize / inverseEnabled / timeQuantum) with hash-based tab
  routing (#console / #cluster).
"""

INDEX_HTML = """<!DOCTYPE html>
<html>
<head>
<meta charset="utf-8">
<title>pilosa-tpu console</title>
<link rel="stylesheet" href="/assets/main.css">
</head>
<body>
<header>
  <h1>pilosa-tpu</h1>
  <nav>
    <a id="nav-console" href="#console" class="nav-active">Console</a>
    <a id="nav-cluster" href="#cluster">Cluster</a>
  </nav>
  <span id="version" title="server version"></span>
</header>

<main>
  <section id="pane-console" class="pane pane-active">
    <div class="row">
      <label for="index-dropdown">index</label>
      <select id="index-dropdown"></select>
      <button id="run" title="Enter">Run &#9654;</button>
    </div>
    <textarea id="query" rows="3" spellcheck="false"
      placeholder="Count(Bitmap(frame='f', rowID=1))   &mdash;   :help for meta commands"></textarea>
    <div id="complete-hint"></div>
    <div id="outputs"></div>
  </section>

  <section id="pane-cluster" class="pane">
    <h2>Nodes</h2>
    <div id="status-nodes"></div>
    <h2>Schema</h2>
    <div id="status-indexes"></div>
  </section>
</main>
<script src="/assets/main.js"></script>
</body>
</html>
"""

MAIN_JS = """'use strict';

/* ---------------------------------------------------------------- utils */

const $ = (id) => document.getElementById(id);

function getJSON(url) {
  return fetch(url).then((r) => r.json());
}

function esc(s) {
  const d = document.createElement('div');
  d.textContent = String(s);
  return d.innerHTML;
}

function prettyMaybeJSON(text) {
  try { return JSON.stringify(JSON.parse(text), null, 2); }
  catch (e) { return text; }
}

/* ------------------------------------------------------------ nav panes */

function activatePane(name) {
  document.querySelectorAll('nav a').forEach((a) =>
    a.classList.toggle('nav-active', a.id === 'nav-' + name));
  document.querySelectorAll('.pane').forEach((p) =>
    p.classList.toggle('pane-active', p.id === 'pane-' + name));
  if (name === 'cluster') refreshCluster();
}

window.addEventListener('hashchange', () => {
  const name = window.location.hash.substring(1);
  if (name === 'console' || name === 'cluster') activatePane(name);
});

/* -------------------------------------------------------- cluster pane */

function tableOf(caption, headers, rows) {
  const h = headers.map((x) => `<th>${esc(x)}</th>`).join('');
  const body = rows.map((r) =>
    '<tr>' + r.map((c) => `<td>${esc(c)}</td>`).join('') + '</tr>').join('');
  return `<table><caption>${esc(caption)}</caption>` +
         `<tr>${h}</tr>${body}</table>`;
}

function refreshCluster() {
  getJSON('/status').then((s) => {
    const nodes = (s.status && s.status.Nodes) || [];
    $('status-nodes').innerHTML = tableOf(
      `${nodes.length} node(s)`, ['Host', 'State'],
      nodes.map((n) => [n.Host, n.State]));
  }).catch(() => { $('status-nodes').textContent = 'status unavailable'; });
  getJSON('/schema').then((s) => {
    const div = $('status-indexes');
    const tables = (s.indexes || []).map((idx) => tableOf(
      `${idx.name} (columnLabel: ${idx.columnLabel}` +
        (idx.timeQuantum ? `, timeQuantum: ${idx.timeQuantum}` : '') + ')',
      ['Frame', 'Row Label', 'Cache Type', 'Cache Size', 'Inverse', 'Time Quantum'],
      (idx.frames || []).map((f) =>
        [f.name, f.rowLabel, f.cacheType, f.cacheSize,
         f.inverseEnabled, f.timeQuantum || '-'])));
    if (tables.length) div.innerHTML = tables.join('');
    else div.textContent = 'no indexes';
  }).catch(() => {});
}

/* -------------------------------------------------- schema + completion */

const PQL_KEYWORDS = [
  'SetBit()', 'ClearBit()', 'SetRowAttrs()', 'SetColumnAttrs()',
  'Bitmap()', 'Union()', 'Intersect()', 'Difference()', 'Xor()',
  'Count()', 'Range()', 'TopN()', 'frame=', 'rowID=', 'columnID=',
];
let dynamicKeywords = [];

function refreshSchema() {
  return getJSON('/schema').then((s) => {
    const sel = $('index-dropdown');
    const current = sel.value;
    sel.innerHTML = '';
    dynamicKeywords = [];
    (s.indexes || []).forEach((idx) => {
      const opt = document.createElement('option');
      opt.value = opt.textContent = idx.name;
      sel.appendChild(opt);
      dynamicKeywords.push(idx.name);
      (idx.frames || []).forEach((f) => dynamicKeywords.push(f.name));
    });
    if (current) sel.value = current;
  }).catch(() => {});
}

function completeAtCursor(input) {
  // The word fragment runs from the last non-alphanumeric character
  // before the cursor to the cursor.
  const pos = input.selectionEnd;
  let start = pos;
  while (start > 0 && /[A-Za-z0-9_]/.test(input.value[start - 1])) start--;
  const frag = input.value.substring(start, pos);
  if (!frag) return;
  const all = PQL_KEYWORDS.concat(dynamicKeywords);
  const matches = all.filter((k) => k.startsWith(frag) && k !== frag);
  const hint = $('complete-hint');
  if (matches.length === 1) {
    const add = matches[0].substring(frag.length);
    input.value = input.value.substring(0, pos) + add + input.value.substring(pos);
    // land inside the parens of keyword() completions
    const newPos = pos + add.length - (matches[0].endsWith(')') ? 1 : 0);
    input.setSelectionRange(newPos, newPos);
    hint.textContent = '';
  } else {
    hint.textContent = matches.length ? matches.join('   ') : '';
  }
}

/* ------------------------------------------------------- meta commands */

const HELP_TEXT = [
  ':create index <name> [columnLabel=x] [timeQuantum=YMDH]',
  ':create frame <name> [rowLabel=x] [cacheType=ranked|lru] ' +
    '[cacheSize=n] [inverseEnabled=true] [timeQuantum=YMDH]',
  ':delete index <name>',
  ':delete frame <name>',
  ':use <index>',
  ':help',
].join('\\n');

function parseOptions(parts) {
  const ints = ['cacheSize'];
  const bools = ['inverseEnabled'];
  const out = {};
  parts.forEach((p) => {
    const [k, v] = p.split('=');
    if (!k || v === undefined) return;
    if (ints.includes(k)) out[k] = Number(v);
    else if (bools.includes(k)) out[k] = v === 'true';
    else out[k] = v;
  });
  return out;
}

// :command -> {url, method, body} | {use: name} | {help: true} | null
function parseMeta(query, indexName) {
  const parts = query.trim().replace(/\\s+/g, ' ').split(' ');
  const cmd = parts[0];
  if (cmd === ':help') return { help: true };
  if (cmd === ':use') return parts[1] ? { use: parts[1] } : null;
  const kind = parts[1], name = parts[2];
  if (!name) return null;
  const url = kind === 'index' ? `/index/${encodeURIComponent(name)}`
    : kind === 'frame'
      ? `/index/${encodeURIComponent(indexName)}/frame/${encodeURIComponent(name)}`
      : null;
  if (url === null) return null;
  if (cmd === ':create') {
    const opts = parseOptions(parts.slice(3));
    return {
      url, method: 'POST',
      body: Object.keys(opts).length ? JSON.stringify({ options: opts }) : '',
    };
  }
  if (cmd === ':delete') return { url, method: 'DELETE', body: '' };
  return null;
}

/* ---------------------------------------------------------------- REPL */

const GETTING_STARTED = [
  'Just getting started?  Try:',
  '  :create index test',
  '  :use test',
  '  :create frame foo',
  "  SetBit(frame='foo', rowID=0, columnID=0)",
].join('\\n');

class Repl {
  constructor(input, outputs) {
    this.input = input;
    this.outputs = outputs;
    this.history = [];
    this.cursor = 0;      // index into history while browsing
    this.stash = '';      // the in-progress edit, restored on Down
  }

  historyUp() {
    if (this.cursor === 0) return;
    if (this.cursor === this.history.length) this.stash = this.input.value;
    this.cursor--;
    this.setValue(this.history[this.cursor]);
  }

  historyDown() {
    if (this.cursor === this.history.length) return;
    this.cursor++;
    this.setValue(this.cursor === this.history.length
      ? this.stash : this.history[this.cursor]);
  }

  setValue(v) {
    this.input.value = v;
    this.input.setSelectionRange(v.length, v.length);
  }

  submit() {
    const query = this.input.value.trim();
    if (!query) return;
    this.history.push(query);
    this.cursor = this.history.length;
    this.stash = '';
    this.input.value = '';
    this.run(query);
  }

  run(query) {
    const indexName = $('index-dropdown').value;
    if (query.startsWith(':')) {
      const meta = parseMeta(query, indexName);
      if (meta === null) {
        this.card(query, indexName, 'invalid meta command\\n' + HELP_TEXT, 400, 0);
      } else if (meta.help) {
        this.card(query, indexName, HELP_TEXT, 200, 0);
      } else if (meta.use) {
        const sel = $('index-dropdown');
        const known = Array.from(sel.options).some((o) => o.value === meta.use);
        if (known) {
          sel.value = meta.use;
          this.card(query, meta.use, 'using ' + meta.use, 200, 0);
        } else {
          this.card(query, indexName, 'no such index: ' + meta.use, 404, 0);
        }
      } else {
        this.request(query, indexName, meta.url, meta.method, meta.body)
          .then(refreshSchema);
      }
      return;
    }
    this.request(query, indexName,
                 `/index/${encodeURIComponent(indexName)}/query`, 'POST', query);
  }

  request(query, indexName, url, method, body) {
    const t0 = performance.now();
    return fetch(url, { method, body }).then((r) =>
      r.text().then((text) => {
        this.card(query, indexName, text, r.status,
                  Math.round(performance.now() - t0));
      })
    ).catch((e) => {
      this.card(query, indexName, String(e), 0, 0);
    });
  }

  card(input, indexName, outputText, status, ms) {
    const err = status !== 200;
    let body = prettyMaybeJSON(outputText);
    if (err && /index not found|frame not found/.test(outputText)) {
      body += '\\n\\n' + GETTING_STARTED;
    }
    const node = document.createElement('div');
    node.className = 'card' + (err ? ' card-error' : '');
    node.innerHTML =
      `<div class="card-head"><span class="badge">${esc(indexName || '-')}` +
      `</span><code>${esc(input)}</code>` +
      `<em>${err ? 'http ' + status : ms + ' ms'}</em></div>` +
      `<pre>${esc(body)}</pre>`;
    this.outputs.insertBefore(node, this.outputs.firstChild);
  }
}

/* ---------------------------------------------------------------- init */

const repl = new Repl($('query'), $('outputs'));

$('query').addEventListener('keydown', (e) => {
  const atFirstLine =
    !$('query').value.substring(0, $('query').selectionStart).includes('\\n');
  const atLastLine =
    !$('query').value.substring($('query').selectionEnd).includes('\\n');
  if (e.key === 'Enter' && !e.shiftKey) {
    e.preventDefault();
    repl.submit();
  } else if (e.key === 'ArrowUp' && atFirstLine) {
    e.preventDefault();
    repl.historyUp();
  } else if (e.key === 'ArrowDown' && atLastLine) {
    e.preventDefault();
    repl.historyDown();
  } else if (e.key === 'Tab') {
    e.preventDefault();
    completeAtCursor($('query'));
  }
});

$('run').addEventListener('click', () => repl.submit());

getJSON('/version').then((v) => {
  $('version').textContent = 'v' + v.version;
}).catch(() => {});

refreshSchema().then(() => {
  const name = window.location.hash.substring(1);
  if (name === 'cluster') activatePane('cluster');
});
$('query').focus();
"""

MAIN_CSS = """body { font-family: monospace; margin: 0; background: #111;
  color: #dcdcdc; }
header { padding: 0.6rem 1rem; background: #222; display: flex;
  align-items: baseline; gap: 1.5rem; }
h1 { font-size: 1.1rem; margin: 0; color: #7fd4ff; }
h2 { font-size: 0.95rem; color: #9fe89f; }
nav { display: flex; gap: 1rem; }
nav a { color: #888; text-decoration: none; padding-bottom: 2px; }
nav a.nav-active { color: #dcdcdc; border-bottom: 2px solid #7fd4ff; }
#version { margin-left: auto; color: #666; }
main { padding: 1rem; max-width: 64rem; }
.pane { display: none; }
.pane-active { display: block; }
.row { display: flex; gap: 0.5rem; margin-bottom: 0.5rem;
  align-items: center; }
label { color: #888; }
select, input, textarea { background: #1b1b1b; color: #dcdcdc;
  border: 1px solid #333; padding: 0.4rem; font-family: inherit; }
textarea { width: 100%; box-sizing: border-box; }
button { background: #245; color: #cfe; border: 1px solid #368;
  padding: 0.4rem 1rem; cursor: pointer; }
button:hover { background: #356; }
#complete-hint { color: #887a33; min-height: 1.1rem;
  white-space: pre; overflow-x: auto; }
.card { border: 1px solid #333; margin: 0.6rem 0; background: #1b1b1b; }
.card-error { border-color: #844; }
.card-head { display: flex; gap: 0.8rem; align-items: baseline;
  padding: 0.3rem 0.6rem; background: #232323; }
.card-head em { margin-left: auto; color: #666; }
.card-error .card-head { background: #2a1a1a; }
.badge { background: #245; color: #cfe; padding: 0 0.4rem;
  border-radius: 2px; }
.card pre { margin: 0; padding: 0.6rem; max-height: 18rem;
  overflow: auto; }
pre { background: #1b1b1b; border: 0; }
table { border-collapse: collapse; margin: 0.6rem 0; }
caption { text-align: left; color: #9fe89f; padding-bottom: 0.2rem; }
th, td { border: 1px solid #333; padding: 0.25rem 0.6rem;
  text-align: left; }
th { background: #232323; }
"""

ASSETS = {
    "main.js": (MAIN_JS, "application/javascript"),
    "main.css": (MAIN_CSS, "text/css"),
}
