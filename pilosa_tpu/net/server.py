"""Node runtime — wires Holder + Executor + Handler + Cluster + loops.

The counterpart of the reference's root Server (reference:
server.go:44-172): open the holder, start the broadcast receiver and
node set, build the executor, serve HTTP, and run three background
loops — anti-entropy, max-slice polling, and runtime metrics (here the
cache flusher keeps the reference's holder flush loop as well,
reference: holder.go:318-352).
"""

from __future__ import annotations

import threading
import time

from pilosa_tpu import __version__
from pilosa_tpu.cluster import broadcast as bc
from pilosa_tpu.cluster.topology import Cluster, Node
from pilosa_tpu.core.holder import Holder
from pilosa_tpu.exec.executor import Executor
from pilosa_tpu.exec import warmup
from pilosa_tpu.net import resilience as rz
from pilosa_tpu.net import wire_pb2 as wire
from pilosa_tpu.net.client import InternalClient
from pilosa_tpu.net.handler import Handler, make_http_server
from pilosa_tpu.obs.trace import Tracer
from pilosa_tpu.testing import faults

# reference: server.go:38-40
DEFAULT_ANTI_ENTROPY_INTERVAL = 600.0
DEFAULT_POLLING_INTERVAL = 60.0
# reference: holder.go:30-31
DEFAULT_CACHE_FLUSH_INTERVAL = 60.0


class Server:
    """One node of the cluster."""

    def __init__(
        self,
        data_dir: str,
        host: str = "127.0.0.1:0",
        cluster: Cluster | None = None,
        broadcaster=None,
        broadcast_receiver=None,
        anti_entropy_interval: float = DEFAULT_ANTI_ENTROPY_INTERVAL,
        polling_interval: float = DEFAULT_POLLING_INTERVAL,
        cache_flush_interval: float = DEFAULT_CACHE_FLUSH_INTERVAL,
        max_writes_per_request: int | None = None,
        logger=None,
        stats=None,
        compilation_cache_dir: str | None = None,
        prewarm: bool = False,
        stream_chunk_bytes: int = 0,
        slow_query_ms: float = 0.0,
        trace_ring: int = 64,
        mesh_devices: int = 0,
        hbm_budget_bytes: int = 0,
        device_prefetch: bool = True,
        device_stage: bool = True,
        stage_throttle_ms: float = 0.0,
        launch_watchdog_ms: float = 60_000.0,
        quarantine_threshold: int = 3,
        quarantine_open_ms: float = 10_000.0,
        quarantine_probe_successes: int = 1,
        plane_format: str = "auto",
        plane_sparse_max_bytes: int = 65536,
        plane_rle_max_bytes: int = 65536,
        coalesce: bool = True,
        coalesce_max_batch: int = 64,
        coalesce_max_wait_us: int = 0,
        fuse: bool = True,
        fuse_max_programs: int = 16,
        query_timeout_ms: float = 60_000.0,
        broadcast_timeout_ms: float = 5_000.0,
        retry_attempts: int = 3,
        retry_backoff_ms: float = 100.0,
        breaker_failure_threshold: int = 5,
        breaker_open_ms: float = 10_000.0,
        admission: bool = True,
        admission_point_concurrency: int = 32,
        admission_heavy_concurrency: int = 8,
        admission_write_concurrency: int = 16,
        admission_internal_concurrency: int = 128,
        admission_queue_depth: int = 64,
        rebalance_throttle_mbps: float = 0.0,
        rebalance_verify_rounds: int = 3,
        rebalance_delta_cap: int = 50_000,
        rebalance_release_delay_ms: float = 200.0,
        rebalance_on_join: bool = False,
        write_consistency: str = "quorum",
        read_consistency: str = "one",
        hint_cap: int = 10_000,
        hint_replay_throttle_mbps: float = 0.0,
        tier_store: str = "",
        tier_hydrate_throttle_mbps: float = 0.0,
        tier_disk_budget_bytes: int = 0,
        tier_retention_age_s: float = 0.0,
        tier_retention_delete_s: float = 0.0,
        tier_sweep_interval_s: float = 60.0,
        subscribe_enabled: bool = True,
        subscribe_max_subscriptions: int = 10_000,
        subscribe_queue_cap: int = 256,
        subscribe_delta_cap: int = 50_000,
        subscribe_coalesce_ms: float = 5.0,
        subscribe_refresh_ms: float = 500.0,
        ingest_wal: bool = True,
        ingest_group_commit_ms: float = 2.0,
        ingest_group_commit_max: int = 128,
        ingest_scatter: bool = True,
        ingest_wal_segment_bytes: int = 4 << 20,
        admission_subscribe_concurrency: int = 4,
        tenants=None,
        tenant_keys=None,
        tenant_default: str = "default",
        tenant_internal_token: str = "",
        latency_buckets_ms=None,
        slo_ms: float = 0.0,
        slo_objective: float = 0.999,
        floor_probe: bool = True,
    ):
        self.data_dir = data_dir
        self.host = host
        self.cluster = cluster or Cluster()
        self.broadcaster = broadcaster or bc.NopBroadcaster()
        self.broadcast_receiver = broadcast_receiver or bc.NopBroadcastReceiver()
        self.anti_entropy_interval = anti_entropy_interval
        self.polling_interval = polling_interval
        self.cache_flush_interval = cache_flush_interval
        self.max_writes_per_request = max_writes_per_request
        self.logger = logger or (lambda m: None)
        self.stats = stats
        self.compilation_cache_dir = compilation_cache_dir
        self.prewarm = prewarm
        # Chunk size for streamed HTTP bodies (export/backup data
        # plane); 0 = stream.DEFAULT_CHUNK_BYTES.
        self.stream_chunk_bytes = stream_chunk_bytes
        # Always-on query tracing (Dapper model): every query gets a
        # trace; the last trace_ring traces are retained and served at
        # GET /debug/traces.  slow_query_ms > 0 additionally emits one
        # structured slow-query log line per over-threshold query.
        self.tracer = Tracer(capacity=trace_ring)
        self.slow_query_ms = slow_query_ms
        # Mesh data plane ([device] mesh-devices): devices participating
        # in slice placement and the sharded data plane.  0 = all
        # visible (sharded execution engages by default with >1 device),
        # 1 = force single-device, N = cap.  Placement is process-global
        # (ops/bitplane), so this is applied at open().
        self.mesh_devices = mesh_devices
        # HBM residency manager ([device] config): per-device budget for
        # pool-registered device memory (0 = auto), plus the async
        # cold-mirror prefetcher toggle.
        self.hbm_budget_bytes = hbm_budget_bytes
        self.device_prefetch = device_prefetch
        # Lazy overlapped cold staging ([device] stage): a restarted
        # node starts serving immediately while its fragment mirrors
        # stream into HBM in the background — gossip-hot slices first,
        # then the pre-restart residency order.  stage_throttle_ms
        # rate-limits the background lane (0 = full speed).
        self.device_stage = device_stage
        self.stage_throttle_ms = stage_throttle_ms
        self.staging_job = None
        # Compressed device planes ([device] plane-format / plane-*-max-
        # bytes, ops/bitplane.encode_row): per-row container format
        # selection on the device.  Process-global, applied at open().
        self.plane_format = plane_format
        self.plane_sparse_max_bytes = plane_sparse_max_bytes
        self.plane_rle_max_bytes = plane_rle_max_bytes
        # Device-fault tolerance ([device] launch-watchdog-ms /
        # quarantine-*, device/health.py): per-device + collective-path
        # quarantine state machine with half-open probes, and the
        # hung-collective launch watchdog.  Shared by the executor and
        # the coalescer; state changes flip the local node's degraded
        # flag (and, with gossip, every peer's view), and a HEAL kicks
        # the staging lane to re-materialize HBM mirrors.
        from pilosa_tpu.device.health import DeviceHealth

        self.device_health = DeviceHealth(
            quarantine_threshold=quarantine_threshold,
            open_ms=quarantine_open_ms,
            probe_successes=quarantine_probe_successes,
            watchdog_ms=launch_watchdog_ms,
            stats=stats,
            logger=self.logger,
            on_state_change=self._on_device_health_change,
        )
        # Cross-query coalescing ([exec] config): concurrent queries
        # sharing a compile key ride one fused launch (exec/coalesce.py).
        self.coalesce = coalesce
        self.coalesce_max_batch = coalesce_max_batch
        self.coalesce_max_wait_us = coalesce_max_wait_us
        # Plane-major multi-query fusion ([exec] fuse): distinct trees
        # sharing a program key evaluate in one interpreter pass.
        self.fuse = fuse
        self.fuse_max_programs = fuse_max_programs
        self.coalescer = None
        # Cluster resilience ([net] config, net/resilience.py): the
        # retry policy and per-host circuit breakers every client this
        # server hands out shares, plus the default query deadline.
        # Deadlines flow per request (X-Deadline-Ms); breakers make a
        # down host fail in microseconds instead of a socket timeout.
        self.broadcast_timeout_ms = broadcast_timeout_ms
        self.resilience = rz.Resilience(
            retry=rz.RetryPolicy(
                attempts=retry_attempts,
                backoff=retry_backoff_ms / 1000.0,
                stats=stats,
            ),
            breakers=rz.BreakerRegistry(
                failure_threshold=breaker_failure_threshold,
                open_s=breaker_open_ms / 1000.0,
                stats=stats,
            ),
            query_timeout_ms=query_timeout_ms,
        )
        # Admission control ([net] admission-*, net/admission.py):
        # per-cost-class concurrency gates + bounded queues in front of
        # the executor, shedding 429 + Retry-After when predicted queue
        # wait exceeds the request's remaining deadline.  Remote map
        # legs ride a separate internal priority lane so a saturated
        # cluster cannot distributed-livelock.
        # Tenant QoS ([net] tenants/tenant-keys, net/admission.py
        # TenantRegistry): API-key -> tenant resolution, WFQ weights,
        # and quota buckets.  Built even when admission is off so the
        # internal-lane token check and /debug/tenants still work.
        from pilosa_tpu.net.admission import TenantRegistry

        self.tenants = TenantRegistry(
            tenants=tenants,
            keys=tenant_keys,
            default_tenant=tenant_default,
            internal_token=tenant_internal_token,
            stats=stats,
        )
        self.admission = None
        if admission:
            from pilosa_tpu.net.admission import AdmissionController

            self.admission = AdmissionController(
                point_concurrency=admission_point_concurrency,
                heavy_concurrency=admission_heavy_concurrency,
                write_concurrency=admission_write_concurrency,
                internal_concurrency=admission_internal_concurrency,
                subscribe_concurrency=admission_subscribe_concurrency,
                queue_depth=admission_queue_depth,
                stats=stats,
                tenants=self.tenants,
            )

        self.holder = Holder(data_dir)
        # Elastic-cluster rebalancer ([cluster] rebalance-*,
        # pilosa_tpu/rebalance): applies fanned-out topology events on
        # every node and coordinates background slice migration on the
        # node that receives POST /cluster/resize.  The bandwidth
        # throttle keeps bulk copies from starving client traffic; the
        # release delay lets in-flight old-ring reads drain before a
        # migrated-away slice's data goes.
        self.rebalance_throttle_mbps = rebalance_throttle_mbps
        self.rebalance_verify_rounds = rebalance_verify_rounds
        self.rebalance_delta_cap = rebalance_delta_cap
        self.rebalance_release_delay_ms = rebalance_release_delay_ms
        self.rebalance_on_join = rebalance_on_join
        from pilosa_tpu.rebalance import Rebalancer

        self.rebalance = Rebalancer(self)
        # Quorum replication ([cluster] write-consistency /
        # read-consistency, pilosa_tpu/replicate): per-slice monotonic
        # write versions, W-of-N write acknowledgement with hinted
        # handoff for unreachable replicas, version-checked reads with
        # read-repair.  The hint replayer triggers off the shared
        # per-host breakers (open -> half-open = the recovery signal)
        # and its repair pushes ride the rebalancer's delta machinery.
        from pilosa_tpu.replicate import Replication

        self.replication = Replication(
            host=self.host,
            cluster=self.cluster,
            holder=self.holder,
            client_factory=self._client_factory,
            breakers=self.resilience.breakers,
            rebalancer=self.rebalance,
            tracer=self.tracer,
            stats=self.holder.stats,
            logger=self.logger,
            data_dir=data_dir,
            write_consistency=write_consistency,
            read_consistency=read_consistency,
            hint_cap=hint_cap,
            hint_replay_throttle_mbps=hint_replay_throttle_mbps,
        )
        # Tiered storage ([tier] config, pilosa_tpu/tier): the shared
        # object-store cold tier.  Built at open() (the store client
        # shares the server's retry/breaker wiring); None when no
        # store is configured.
        self.tier_store = tier_store
        self.tier_hydrate_throttle_mbps = tier_hydrate_throttle_mbps
        self.tier_disk_budget_bytes = tier_disk_budget_bytes
        self.tier_retention_age_s = tier_retention_age_s
        self.tier_retention_delete_s = tier_retention_delete_s
        self.tier_sweep_interval_s = tier_sweep_interval_s
        self.tier = None
        # Standing queries ([subscribe] config, pilosa_tpu/subscribe):
        # built at open() AFTER the executor exists (the delta engine
        # pulls through it on overflow/TopN/topology change); None when
        # disabled.
        self.subscribe_enabled = subscribe_enabled
        self.subscribe_max_subscriptions = subscribe_max_subscriptions
        self.subscribe_queue_cap = subscribe_queue_cap
        self.subscribe_delta_cap = subscribe_delta_cap
        self.subscribe_coalesce_ms = subscribe_coalesce_ms
        self.subscribe_refresh_ms = subscribe_refresh_ms
        self.subscribe = None
        # Durable ingest ([ingest] config, pilosa_tpu/ingest): the WAL
        # manager is built at open() BEFORE holder.open() — fragments
        # replay their WAL tails as they open and attach writers via
        # the module registry.  None when the WAL is disabled.
        self.ingest_wal = ingest_wal
        self.ingest_group_commit_ms = ingest_group_commit_ms
        self.ingest_group_commit_max = ingest_group_commit_max
        self.ingest_scatter = ingest_scatter
        self.ingest_wal_segment_bytes = ingest_wal_segment_bytes
        self.ingest = None
        # Performance observability ([obs] latency-buckets-ms / slo-* /
        # floor-probe, obs/perf.py + device/floorprobe.py): native
        # fixed-bucket latency histograms + SLO burn gauges live on the
        # Handler; the one-shot stream-floor probe runs at open() and
        # anchors the /debug/perf roofline denominators.
        self.latency_buckets_ms = latency_buckets_ms
        self.slo_ms = slo_ms
        self.slo_objective = slo_objective
        self.floor_probe = floor_probe
        self.executor: Executor | None = None
        self.handler: Handler | None = None
        self._http = None
        self._http_thread = None
        self._closing = threading.Event()
        self._loops: list[threading.Thread] = []
        self._ae_ticks = 0

    def _client_factory(self, node) -> InternalClient:
        """Inter-node clients carrying this server's resilience wiring:
        shared retry policy, shared per-host breakers, and (via the
        deadline contextvar) the active query's remaining budget."""
        host = node if isinstance(node, str) else node.host
        return InternalClient(
            host,
            retry=self.resilience.retry,
            breakers=self.resilience.breakers,
            internal_token=self.tenants.internal_token,
        )

    # ------------------------------------------------------------------
    # lifecycle (reference: server.go:99-198)
    # ------------------------------------------------------------------

    def open(self) -> None:
        bind_host, _, bind_port = self.host.partition(":")
        port = int(bind_port or 0)
        # Chaos layer (testing/faults.py): announce an active
        # PILOSA_FAULTS plan loudly — a soak run must be unmistakable.
        plan = faults.active()
        if plan is not None and plan.rules:
            self.logger(
                f"FAULT INJECTION ACTIVE: {len(plan.rules)} rule(s): "
                + "; ".join(
                    f"{r.stage}/{r.mode}" for r in plan.rules
                )
            )

        # Max-slice growth must reach peers before queries route there
        # (reference: view.go:236-241 broadcasts CreateSliceMessage).
        self.holder.on_create_slice = self._on_create_slice
        if self.stats is not None:
            # Root of the tag chain: indexes opened from disk (and all
            # their frames/views/fragments) pick up tagged children
            # (reference: server.go wiring of holder.Stats).
            self.holder.stats = self.stats
        # Route storage-layer notices (e.g. op-log tail repairs on
        # fragment open) through the server's configured logger.
        self.holder.logger = self.logger
        # Configure the process-global HBM residency pool before any
        # fragment opens (device mirrors register on first upload): the
        # budget bounds mirrors, paged sparse rows, and executor caches;
        # gauges/counters flow through the server's stats client and
        # evict/prefetch spans into its tracer.
        from pilosa_tpu import device as device_mod
        from pilosa_tpu.ops import bitplane as bp

        # Mesh-devices cap BEFORE any fragment opens: slice placement
        # (home_device) and the slices mesh both derive from it.  Only
        # an explicit cap is applied — the process-global default (all
        # visible devices) must survive in-process multi-server setups.
        if self.mesh_devices > 0:
            bp.configure_mesh_devices(self.mesh_devices)
        n_mesh = bp.mesh_device_count()
        if n_mesh > 1:
            self.logger(
                f"data plane: mesh-sharded over {n_mesh} devices "
                "(slice planes placed per shard, counts reduce over ICI); "
                "set [device] mesh-devices = 1 to force single-device"
            )
        device_mod.pool().configure(
            budget_bytes=self.hbm_budget_bytes,
            stats=self.stats,
            tracer=self.tracer,
        )
        # One-shot stream-floor probe ([obs] floor-probe): measures
        # per-device achievable streaming GB/s (cached process-wide AND
        # under the data dir, so restarts and in-process multi-server
        # tests pay it once) and anchors every %-of-floor figure the
        # /debug/perf roofline table reports.
        if self.floor_probe:
            from pilosa_tpu.device import floorprobe
            from pilosa_tpu.obs import perf as perf_mod

            fp = floorprobe.probe(
                artifact_dir=self.data_dir,
                stats=self.stats,
                logger=self.logger,
            )
            if fp is not None:
                perf_mod.registry().set_floor(fp["mean_gbps"])
        # Cold-start elimination (see exec/warmup.py): persistent XLA
        # compile cache so restarts deserialize programs from disk, and
        # a background pre-warm of the standard query shapes so even a
        # first boot doesn't pay compiles at query time.
        if self.compilation_cache_dir:
            if warmup.enable_compile_cache(self.compilation_cache_dir):
                # First caller in the process wins the dir — log the
                # ACTIVE one so operators never chase an empty dir.
                active = warmup.enabled_cache_dir()
                note = (
                    "" if active == self.compilation_cache_dir
                    else f" (configured {self.compilation_cache_dir})"
                )
                self.logger(f"compilation cache: {active}{note}")
            else:
                # A configured-but-broken cache dir (unwritable path,
                # JAX without the knob) must be VISIBLE: every restart
                # silently pays full recompiles otherwise.
                self.logger(
                    "compilation cache DISABLED: could not enable "
                    f"{self.compilation_cache_dir!r}; queries recompile "
                    "from scratch on every process start"
                )
        # Durable ingest: flip the module-level scatter switch and
        # register the WAL manager BEFORE holder.open() — fragments
        # replay their WAL tails as they open and attach writers
        # through the module registry (path-prefix ownership keeps
        # multiple in-process servers isolated).
        from pilosa_tpu.ingest import scatter as scatter_mod
        from pilosa_tpu.ingest import wal as wal_mod

        scatter_mod.ENABLED = bool(self.ingest_scatter)
        # Compressed device planes: flip the module-level format policy
        # before any fragment encodes a payload.
        from pilosa_tpu.ops import bitplane as bp_mod

        bp_mod.configure_plane_format(
            mode=self.plane_format,
            sparse_max_bytes=self.plane_sparse_max_bytes,
            rle_max_bytes=self.plane_rle_max_bytes,
        )
        if self.ingest_wal:
            self.ingest = wal_mod.IngestManager(
                self.data_dir,
                wal=True,
                group_commit_ms=self.ingest_group_commit_ms,
                group_commit_max=self.ingest_group_commit_max,
                wal_segment_bytes=self.ingest_wal_segment_bytes,
                stats=self.holder.stats if self.stats is not None else None,
                logger=self.logger,
                versions=self.replication.versions,
            )
            wal_mod.register_manager(self.ingest)
        self.holder.open()

        # Tiered storage: open the cold-store client (sharing the
        # server's retry policy + per-host breakers), then BOOTSTRAP —
        # restore the schema and register store-held fragments as cold
        # BEFORE the first query routes, so a node with an empty data
        # dir and only [tier] store configured serves the whole index,
        # hydrating on demand.
        if self.tier_store:
            from pilosa_tpu.tier import TierManager, open_store

            store = open_store(
                self.tier_store,
                stats=self.stats,
                retry=self.resilience.retry,
                breakers=self.resilience.breakers,
            )
            self.tier = TierManager(
                holder=self.holder,
                store=store,
                prefetcher=device_mod.prefetcher(),
                stats=self.stats,
                tracer=self.tracer,
                logger=self.logger,
                hydrate_throttle_mbps=self.tier_hydrate_throttle_mbps,
                disk_budget_bytes=self.tier_disk_budget_bytes,
                retention_age_s=self.tier_retention_age_s,
                retention_delete_s=self.tier_retention_delete_s,
            )
            boot = self.tier.bootstrap()
            self.logger(
                f"tier: cold store {store.url} attached "
                f"({boot['cold']} cold fragment(s) registered, "
                f"{boot['frames']} frame(s) restored from schema)"
            )

        if self.coalesce:
            from pilosa_tpu.exec.coalesce import CoalesceScheduler

            self.coalescer = CoalesceScheduler(
                max_batch=self.coalesce_max_batch,
                max_wait_us=self.coalesce_max_wait_us,
                stats=self.stats,
                fuse=self.fuse,
                fuse_max_programs=self.fuse_max_programs,
                health=self.device_health,
            )
        if self.prewarm:
            # With coalescing on, also compile the coalescer's
            # power-of-two bucket shapes for the common Count trees so
            # the first coalesced batch doesn't eat a cold compile.
            warmup.prewarm_async(
                logger=self.logger, coalesce=self.coalesce
            )

        # Start HTTP listener first so ":0" resolves to the real port
        # before the node self-registers (reference: server.go:109-125).
        self.handler = Handler(
            holder=self.holder,
            cluster=self.cluster,
            broadcaster=self.broadcaster,
            client_factory=self._client_factory,
            version=__version__,
            logger=self.logger,
            stats=self.stats,
            stream_chunk_bytes=self.stream_chunk_bytes,
            tracer=self.tracer,
            slow_query_ms=self.slow_query_ms,
            resilience=self.resilience,
            admission=self.admission,
            tenants=self.tenants,
            rebalance=self.rebalance,
            tier=self.tier,
            replication=self.replication,
            latency_buckets_ms=self.latency_buckets_ms,
            slo_ms=self.slo_ms,
            slo_objective=self.slo_objective,
        )
        # Profiler captures (GET /debug/profile) tar under the data dir
        # so the artifact survives the request and ships with backups.
        self.handler.profile_dir = self.data_dir
        # Migration arrivals (?stage=true restores) register their HBM
        # mirrors through the background staging lane.
        self.handler.prefetcher = device_mod.prefetcher()
        # The rebalance delta log captures the write stream of every
        # actively-migrating slice from the fragment write hook; the
        # replication listener bumps per-slice write versions and feeds
        # the quorum coordinator's hint-capture scope on the same hook.
        from pilosa_tpu.core import fragment as fragment_mod

        fragment_mod.register_write_listener(self.rebalance.delta_log.record)
        if self.stats is not None:
            self.replication.stats = self.holder.stats
            self.replication.versions.stats = self.holder.stats
            self.replication.hints.stats = self.holder.stats
        fragment_mod.register_write_listener(self.replication.on_local_write)
        # ONE provider feeds both /state (the stream fallback's pull
        # endpoint, any cluster type) and gossip's piggybacked state —
        # the digest gossip advertises must be of the exact blob /state
        # serves.
        state_provider = lambda: self.local_status().SerializeToString()  # noqa: E731
        self.handler.state_provider = state_provider
        self._http = make_http_server(self.handler, bind_host or "127.0.0.1", port)
        addr = self._http.server_address
        # Keep the *configured* host string as the node identity — it must
        # string-match the cluster.hosts entries or placement forks per
        # node; only a ":0" port is replaced with the bound one.
        if port == 0:
            self.host = f"{bind_host or addr[0]}:{addr[1]}"

        # Self-register in the cluster (reference: server.go:117-125) —
        # UNLESS a ring is already configured that this host is not
        # part of: that is a JOINING node (it would fork placement if
        # it inserted itself), which receives ownership only through a
        # rebalance transition (POST /cluster/resize).
        if self.cluster.node_by_host(self.host) is None:
            if self.cluster.nodes:
                self.logger(
                    f"host {self.host} is not in the configured ring "
                    f"({len(self.cluster.nodes)} nodes); joining — slice "
                    "ownership arrives via /cluster/resize"
                )
            else:
                self.cluster.add_node(self.host)

        # Crash recovery: a persisted in-flight topology transition
        # (both rings + flipped slices) restores BEFORE the first query
        # routes; migration resumes when the operator re-issues the
        # resize.
        self.rebalance.resume_from_disk()

        # Replication opens AFTER the node identity is final (a ":0"
        # port just resolved): persisted write versions restore and the
        # hint replayer starts watching the shared breakers.
        self.replication.host = self.host
        self.replication.open()

        self.broadcast_receiver.start(self)
        ns = getattr(self.cluster, "node_set", None)
        if ns is not None:
            # Gossip backends piggyback node state on probes and surface
            # membership changes (reference: gossip.go:191-222 LocalState/
            # MergeRemoteState, cluster.go:161-173 node states).
            if hasattr(ns, "state_provider") and ns.state_provider is None:
                ns.state_provider = state_provider
            if hasattr(ns, "state_merger") and ns.state_merger is None:

                def _merge(blob: bytes) -> None:
                    st = wire.NodeStatus()
                    st.ParseFromString(blob)
                    self.handle_remote_status(st)

                ns.state_merger = _merge
            if hasattr(ns, "hot_provider") and ns.hot_provider is None:
                # Announce this node's hottest resident slices on every
                # ping/ack, so restarting peers stage what the cluster
                # is being asked about FIRST.
                ns.hot_provider = self.holder.hot_slices
            if hasattr(ns, "health_provider") and ns.health_provider is None:
                # Device-health piggyback: the degraded flag rides
                # every ping/ack; receivers deprioritize this node as a
                # replica while its accelerator is quarantined.
                ns.health_provider = self.device_health.degraded
                ns.on_peer_health = self.cluster.note_degraded
            if hasattr(ns, "on_membership_change"):
                ns.on_membership_change = self._on_membership_change
            ns.open()

        kwargs = {}
        if self.max_writes_per_request is not None:
            kwargs["max_writes_per_request"] = self.max_writes_per_request
        self.executor = Executor(
            holder=self.holder,
            host=self.host,
            cluster=self.cluster,
            client_factory=self._client_factory,
            tracer=self.tracer,
            prefetcher=(
                device_mod.prefetcher() if self.device_prefetch else None
            ),
            coalescer=self.coalescer,
            replication=self.replication,
            device_health=self.device_health,
            **kwargs,
        )
        # Log-before-ack: point-write acks through this executor wait
        # on the WAL group commit (no-op when the WAL is disabled).
        self.executor.ingest = self.ingest
        self.handler.executor = self.executor
        self.handler.ingest = self.ingest

        # Standing queries ([subscribe], pilosa_tpu/subscribe): the
        # manager registers its own fragment write/close listeners and
        # runs the notifier thread; built after the executor because
        # overflow/TopN/topology-change evaluation pulls through it.
        if self.subscribe_enabled:
            from pilosa_tpu.subscribe import SubscriptionManager

            self.subscribe = SubscriptionManager(
                executor=self.executor,
                cluster=self.cluster,
                stats=self.stats,
                tracer=self.tracer,
                admission=self.admission,
                data_dir=self.data_dir,
                logger=self.logger,
                max_subscriptions=self.subscribe_max_subscriptions,
                queue_cap=self.subscribe_queue_cap,
                delta_cap=self.subscribe_delta_cap,
                coalesce_ms=self.subscribe_coalesce_ms,
                refresh_interval_ms=self.subscribe_refresh_ms,
            )
            self.subscribe.open()
            self.handler.subscribe = self.subscribe

        # Lazy overlapped cold staging: serving starts NOW; fragment
        # mirrors stream into HBM behind it — gossip-announced hot
        # slices first, then the pre-restart residency table (MRU
        # first), then everything else.  A query landing on a still-
        # cold slice stages exactly its own planes through the query
        # path/prefetcher and jumps this backlog.  The eager
        # warm_device_mirrors loop this replaces serialized the whole
        # mirror set (~254 MB, cold e2e 4.79 s) before the first
        # answer.
        if self.device_stage:
            self.staging_job = self.holder.stage_device_mirrors(
                device_mod.prefetcher(),
                hot_slices=self._gossip_hot_slices(),
                throttle_s=self.stage_throttle_ms / 1000.0,
                tracer=self.tracer,
            )
            if self.staging_job.total:
                self.logger(
                    f"staging {self.staging_job.total} fragment mirrors "
                    "in the background (device.stage.* / /debug/hbm)"
                )

        self._http_thread = threading.Thread(
            target=self._http.serve_forever, daemon=True, name=f"http:{self.host}"
        )
        self._http_thread.start()

        # Background loops (reference: server.go:166-169).
        loops = [
            ("anti-entropy", self._tick_anti_entropy, self.anti_entropy_interval),
            ("max-slices", self._tick_max_slices, self.polling_interval),
            ("cache-flush", self._tick_cache_flush, self.cache_flush_interval),
            ("runtime", self._tick_runtime, self.polling_interval),
        ]
        if self.tier is not None:
            # Retention aging/deletion + disk-budget LRU demotion.
            loops.append(
                ("tier-sweep", self.tier.sweep, self.tier_sweep_interval_s)
            )
        for name, fn, interval in loops:
            t = threading.Thread(
                target=self._loop,
                args=(fn, interval),
                daemon=True,
                name=f"{name}:{self.host}",
            )
            t.start()
            self._loops.append(t)

    def close(self) -> None:
        self._closing.set()
        # Stop push delivery first: the notifier must not evaluate
        # against a holder/executor that is mid-teardown.
        if self.subscribe is not None:
            self.subscribe.close()
        self.rebalance.close()
        # Stops the hint replayer and persists the per-slice write
        # versions (.replication.json) so a clean restart compares.
        self.replication.close()
        from pilosa_tpu.core import fragment as fragment_mod

        fragment_mod.unregister_write_listener(
            self.rebalance.delta_log.record
        )
        fragment_mod.unregister_write_listener(self.replication.on_local_write)
        if self._http is not None:
            self._http.shutdown()
            self._http.server_close()
        if hasattr(self.broadcast_receiver, "close"):
            self.broadcast_receiver.close()
        if self.executor is not None:
            self.executor.close()
        if self.coalescer is not None:
            # After the executor: in-flight queries fall back to the
            # direct launch path when submit() raises CoalesceClosed.
            self.coalescer.close()
        self.device_health.close()
        self.holder.close()
        # After holder.close(): fragments detached their WAL writers
        # (final commit each) during close; now stop the committer and
        # drop the registry entry so a later in-process server on the
        # same data dir attaches fresh.
        if self.ingest is not None:
            from pilosa_tpu.ingest import wal as wal_mod

            wal_mod.unregister_manager(self.ingest)
            self.ingest.close()
            self.ingest = None
        # Release stats transports (the StatsD UDP socket) last: the
        # close path above may still observe.
        if self.stats is not None:
            close = getattr(self.stats, "close", None)
            if close is not None:
                close()

    def __enter__(self):
        self.open()
        return self

    def __exit__(self, *exc):
        self.close()

    # ------------------------------------------------------------------
    # background loops (reference: server.go:200-274, holder.go:318-352)
    # ------------------------------------------------------------------

    def _loop(self, fn, interval: float) -> None:
        while not self._closing.wait(interval):
            try:
                fn()
            except Exception as e:  # noqa: BLE001 — loops must survive
                self.logger(f"background loop error: {e}")

    # Every Nth anti-entropy tick ignores the version-agreement fast
    # path and walks full block checksums — the backstop for the
    # (crash-reset, equal-but-wrong) version edge cases.
    FULL_SYNC_EVERY = 4

    def _tick_anti_entropy(self) -> None:
        from pilosa_tpu.sync.syncer import HolderSyncer

        self._ae_ticks += 1
        HolderSyncer(
            holder=self.holder,
            host=self.host,
            cluster=self.cluster,
            closing=self._closing,
            replication=self.replication,
            full=(self._ae_ticks % self.FULL_SYNC_EVERY == 0),
        ).sync_holder()

    def _tick_max_slices(self) -> None:
        """Poll peers' max slices so remote-only slices are queryable
        (reference: server.go:238-274).  The timeout is the configured
        ``[net] broadcast-timeout-ms`` (once hardcoded 5.0 here), and
        the GETs ride the shared retry policy + breakers."""
        for node in self.cluster.nodes:
            if node.host == self.host:
                continue
            try:
                client = InternalClient(
                    node.host,
                    timeout=self.broadcast_timeout_ms / 1000.0,
                    retry=self.resilience.retry,
                    breakers=self.resilience.breakers,
                    internal_token=self.tenants.internal_token,
                )
                for index_name, max_slice in client.max_slice_by_index().items():
                    idx = self.holder.index(index_name)
                    if idx is not None:
                        idx.set_remote_max_slice(max_slice)
                for index_name, max_slice in client.max_slice_by_index(
                    inverse=True
                ).items():
                    idx = self.holder.index(index_name)
                    if idx is not None:
                        idx.set_remote_max_inverse_slice(max_slice)
            except Exception:  # noqa: BLE001 — peer may be down
                continue

    def _tick_cache_flush(self) -> None:
        self.holder.flush_caches()

    def _tick_runtime(self) -> None:
        """Runtime gauges — the analog of the reference's goroutine gauge
        + GC notifications (reference: server.go:459-488)."""
        if self.stats is None:
            return
        import gc

        self.stats.gauge("threads", threading.active_count())
        counts = gc.get_count()
        self.stats.gauge("gc.gen0_pending", counts[0])
        try:
            from pilosa_tpu.ingest import scatter as scatter_mod

            scatter_mod.publish_stats(self.stats)
        except Exception:  # noqa: BLE001 — stats are best-effort
            pass
        try:
            import jax

            for i, dev in enumerate(jax.local_devices()):
                ms = getattr(dev, "memory_stats", None)
                mem = ms() if callable(ms) else None
                if mem and "bytes_in_use" in mem:
                    self.stats.gauge(
                        f"device.{i}.hbm_bytes_in_use", mem["bytes_in_use"]
                    )
        except Exception:  # noqa: BLE001 — device stats are best-effort
            pass

    def _on_device_health_change(self, path: str, state: str) -> None:
        """Device-health transitions (quarantine/heal) from the health
        manager: mirror the node's degraded flag into the local routing
        table (gossip carries it to peers), and on a DEVICE-path heal
        re-materialize HBM mirrors through the staging lane — the mesh
        re-resolves to the healthy device set on the next launch
        (parallel/mesh.default_slices_mesh is derived per call), and
        staging restores the plane mirrors host-fallback service never
        touched."""
        try:
            self.cluster.note_degraded(self.host, self.device_health.degraded())
        except Exception as e:  # noqa: BLE001 — advisory path
            self.logger(f"degraded-flag routing update error: {e}")
        from pilosa_tpu.device.health import STATE_HEALTHY

        if (
            state == STATE_HEALTHY
            and path.startswith("device:")
            and self.device_stage
            and self.holder is not None
        ):
            from pilosa_tpu import device as device_mod

            try:
                job = self.holder.stage_device_mirrors(
                    device_mod.prefetcher(),
                    throttle_s=self.stage_throttle_ms / 1000.0,
                    tracer=self.tracer,
                )
                if job.total:
                    self.logger(
                        f"device health: {path} healed — re-materializing "
                        f"{job.total} fragment mirrors via the staging lane"
                    )
            except Exception as e:  # noqa: BLE001 — staging is best-effort
                self.logger(f"post-heal staging error: {e}")

    def _gossip_hot_slices(self) -> dict[str, list[int]]:
        """Peers' fresh hot-slice announcements (union), when the
        cluster runs a gossip node set; {} otherwise."""
        ns = getattr(self.cluster, "node_set", None)
        fn = getattr(ns, "remote_hot_slices", None)
        if fn is None:
            return {}
        try:
            return fn()
        except Exception:  # noqa: BLE001 — staging order is best-effort
            return {}

    def _on_membership_change(self, items) -> None:
        """Merge NodeSet membership into cluster node *states*.  The
        node list itself never reshards on liveness flaps (reference:
        cluster.go:161-173) — placement changes ONLY through the
        versioned rebalance transition.  A gossip-announced host that
        is not in the ring is surfaced as a JOIN CANDIDATE (and, with
        [cluster] rebalance-on-join, auto-admitted via resize)."""
        for host, state in items:
            node = self.cluster.node_by_host(host)
            if node is not None:
                node.set_state(state)
            else:
                try:
                    self.rebalance.note_membership(host, state)
                except Exception as e:  # noqa: BLE001 — advisory path
                    self.logger(f"join-candidate tracking error: {e}")

    def _on_create_slice(self, index: str, view_name: str, slice_i: int) -> None:
        from pilosa_tpu.core.view import is_inverse_view

        try:
            self.broadcaster.send_async(
                wire.CreateSliceMessage(
                    Index=index, Slice=slice_i, IsInverse=is_inverse_view(view_name)
                )
            )
        except Exception as e:  # noqa: BLE001 — broadcast is best-effort
            self.logger(f"create-slice broadcast error: {e}")

    # ------------------------------------------------------------------
    # BroadcastHandler (reference: server.go:277-325)
    # ------------------------------------------------------------------

    def receive_message(self, msg) -> None:
        if isinstance(msg, wire.CreateSliceMessage):
            idx = self.holder.index(msg.Index)
            if idx is None:
                raise RuntimeError("index not found")
            if msg.IsInverse:
                idx.set_remote_max_inverse_slice(msg.Slice)
            else:
                idx.set_remote_max_slice(msg.Slice)
        elif isinstance(msg, wire.CreateIndexMessage):
            opts = {}
            if msg.Meta.ColumnLabel:
                opts["column_label"] = msg.Meta.ColumnLabel
            if msg.Meta.TimeQuantum:
                opts["time_quantum"] = msg.Meta.TimeQuantum
            self.holder.create_index_if_not_exists(msg.Index, **opts)
        elif isinstance(msg, wire.DeleteIndexMessage):
            self.holder.delete_index(msg.Index)
        elif isinstance(msg, wire.CreateFrameMessage):
            idx = self.holder.index(msg.Index)
            if idx is None:
                raise RuntimeError("index not found")
            opts = {}
            if msg.Meta.RowLabel:
                opts["row_label"] = msg.Meta.RowLabel
            if msg.Meta.InverseEnabled:
                opts["inverse_enabled"] = True
            if msg.Meta.CacheType:
                opts["cache_type"] = msg.Meta.CacheType
            if msg.Meta.CacheSize:
                opts["cache_size"] = msg.Meta.CacheSize
            if msg.Meta.TimeQuantum:
                opts["time_quantum"] = msg.Meta.TimeQuantum
            idx.create_frame_if_not_exists(msg.Frame, **opts)
        elif isinstance(msg, wire.DeleteFrameMessage):
            idx = self.holder.index(msg.Index)
            if idx is not None:
                idx.delete_frame(msg.Frame)
        else:
            raise ValueError(f"unknown message type: {type(msg).__name__}")

    # ------------------------------------------------------------------
    # status (reference: server.go:331-412)
    # ------------------------------------------------------------------

    def local_status(self) -> wire.NodeStatus:
        pb = wire.NodeStatus(Host=self.host, State="UP")
        for idx in self.holder.indexes().values():
            pb_idx = wire.Index(
                Name=idx.name,
                Meta=wire.IndexMeta(
                    ColumnLabel=idx.column_label, TimeQuantum=idx.time_quantum
                ),
                MaxSlice=idx.max_slice(),
            )
            for f in idx.frames().values():
                pb_idx.Frames.append(
                    wire.Frame(
                        Name=f.name,
                        Meta=wire.FrameMeta(
                            RowLabel=f.row_label,
                            InverseEnabled=f.inverse_enabled,
                            CacheType=f.cache_type,
                            CacheSize=f.cache_size,
                            TimeQuantum=f.time_quantum,
                        ),
                    )
                )
            pb.Indexes.append(pb_idx)
        return pb

    def handle_remote_status(self, status: wire.NodeStatus) -> None:
        """Merge a peer's schema into ours (reference:
        server.go:382-412) — creates missing indexes/frames and adopts
        remote max slices."""
        for pb_idx in status.Indexes:
            opts = {}
            if pb_idx.Meta.ColumnLabel:
                opts["column_label"] = pb_idx.Meta.ColumnLabel
            if pb_idx.Meta.TimeQuantum:
                opts["time_quantum"] = pb_idx.Meta.TimeQuantum
            idx = self.holder.create_index_if_not_exists(pb_idx.Name, **opts)
            idx.set_remote_max_slice(pb_idx.MaxSlice)
            for pb_f in pb_idx.Frames:
                fopts = {}
                if pb_f.Meta.RowLabel:
                    fopts["row_label"] = pb_f.Meta.RowLabel
                if pb_f.Meta.InverseEnabled:
                    fopts["inverse_enabled"] = True
                if pb_f.Meta.CacheType:
                    fopts["cache_type"] = pb_f.Meta.CacheType
                if pb_f.Meta.CacheSize:
                    fopts["cache_size"] = pb_f.Meta.CacheSize
                if pb_f.Meta.TimeQuantum:
                    fopts["time_quantum"] = pb_f.Meta.TimeQuantum
                idx.create_frame_if_not_exists(pb_f.Name, **fopts)
