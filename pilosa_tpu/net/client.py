"""Internal HTTP client — the inter-node data plane.

The counterpart of the reference's root client (reference:
client.go:39-1010): query fan-out, slice-targeted bulk import with
replica fan-out, CSV export with node redirect, per-slice tar
backup/restore, schema ops, and the sync endpoints (fragment blocks /
block data / attr diffs).  Wire format is HTTP/1.1 + protobuf, matching
the handler's route table.
"""

from __future__ import annotations

import base64
import http.client
import io
import json
import tempfile

import numpy as np
import urllib.parse
from typing import Any

from pilosa_tpu import stream as stream_mod
from pilosa_tpu.net import codec
from pilosa_tpu.net import resilience
from pilosa_tpu.net import wire_pb2 as wire
from pilosa_tpu.testing import faults

PROTOBUF = "application/x-protobuf"


class ClientError(RuntimeError):
    def __init__(self, status: int, message: str):
        super().__init__(f"http {status}: {message}")
        self.status = status


class PreconditionFailedError(ClientError):
    def __init__(self, message: str = "precondition failed"):
        super().__init__(412, message)


class InternalClient:
    """HTTP client pinned to one host ("host:port")."""

    # The executor checks these before passing trace/resilience kwargs,
    # so injected test doubles with the bare execute_query signature
    # keep working.
    supports_trace = True
    supports_resilience = True

    def __init__(
        self,
        host: str,
        timeout: float = 30.0,
        retry: "resilience.RetryPolicy | None" = None,
        breakers: "resilience.BreakerRegistry | None" = None,
        internal_token: str = "",
    ):
        self.host = host
        self.timeout = timeout
        # Proof of internal-lane membership (net/admission.py
        # TenantRegistry.internal_ok): attached to every outbound
        # request so map legs / imports / repair keep their lane when
        # the server pins it behind a token.  Empty = trusted network.
        self.internal_token = internal_token
        # Resilience wiring (net/resilience.py), shared across every
        # client a Server hands out: ``retry`` backs off over transport
        # failures on IDEMPOTENT calls (GETs, and POSTs explicitly
        # marked idempotent); ``breakers`` fast-fails hosts whose
        # circuit is open and records every unary outcome.  Both are
        # optional — a bare client keeps the original single-shot
        # behavior.
        self.retry = retry
        self.breakers = breakers
        # Streamed-GET open retries (see stream/client.py); mid-stream
        # failures always propagate.
        self.stream_retries = 3
        self.stream_backoff = 0.1
        self.chunk_bytes = stream_mod.DEFAULT_CHUNK_BYTES

    def _peer(self, host: str) -> "InternalClient":
        """A client for another node carrying THIS client's resilience
        wiring (replica fan-out, export redirects)."""
        if host == self.host:
            return self
        return InternalClient(
            host,
            self.timeout,
            retry=self.retry,
            breakers=self.breakers,
            internal_token=self.internal_token,
        )

    # ------------------------------------------------------------------
    # plumbing
    # ------------------------------------------------------------------

    def _request(
        self,
        method: str,
        path: str,
        query: dict[str, Any] | None = None,
        body: bytes = b"",
        headers: dict[str, str] | None = None,
        idempotent: bool | None = None,
    ) -> tuple[int, bytes]:
        status, data, _ = self._request_meta(
            method, path, query=query, body=body, headers=headers,
            idempotent=idempotent,
        )
        return status, data

    def _request_meta(
        self,
        method: str,
        path: str,
        query: dict[str, Any] | None = None,
        body: bytes = b"",
        headers: dict[str, str] | None = None,
        idempotent: bool | None = None,
    ) -> tuple[int, bytes, dict[str, str]]:
        """Like :meth:`_request` but also returns the response headers
        (lower-cased keys) — the trace span export rides one.

        ``idempotent`` gates the retry policy: None infers it from the
        method (GET retries, everything else is single-shot); callers
        with better knowledge (e.g. the executor's read-only map legs)
        pass it explicitly."""
        bare = path
        if query:
            path = path + "?" + urllib.parse.urlencode(query)
        if idempotent is None:
            idempotent = method in ("GET", "HEAD")

        def attempt():
            return self._attempt(method, bare, path, body, headers)

        if idempotent and self.retry is not None:
            # Sheds (429) retry alongside transport failures: the policy
            # honors the server's Retry-After hint, and a shed that
            # cannot be waited out within the deadline propagates so the
            # executor can fail over to a replica (net/resilience.py).
            return self.retry.call(
                attempt,
                retryable=resilience.TRANSPORT_ERRORS
                + (resilience.ShedError,),
            )
        return attempt()

    def _attempt(
        self, method: str, bare: str, path: str, body, headers
    ) -> tuple[int, bytes, dict[str, str]]:
        """One wire attempt: breaker gate, deadline-derived socket
        timeout + X-Deadline-Ms export, fault-injection point, and
        breaker outcome recording."""
        timeout, hdrs = self._prepare(method, bare, headers)
        conn = None
        try:
            try:
                # Inside the recorded region: an injected rpc.send
                # fault counts against the breaker exactly like a real
                # transport failure.
                faults.check("rpc.send", host=self.host, path=bare)
                conn = http.client.HTTPConnection(self.host, timeout=timeout)
                conn.request(method, path, body=body, headers=hdrs)
                resp = conn.getresponse()
                data = resp.read()
            except resilience.TRANSPORT_ERRORS:
                self._record_breaker(False)
                raise
            resp_headers = {k.lower(): v for k, v in resp.getheaders()}
            # A 5xx means the node answered but is unhealthy — count it
            # against the breaker like a transport failure.  A 429 shed
            # is the opposite: a healthy-but-busy node answering fast
            # and deliberately — it must NOT trip the breaker open.
            self._record_breaker(resp.status < 500)
            if resp.status == 429:
                raise _shed_error(self.host, data, resp_headers)
            return resp.status, data, resp_headers
        finally:
            if conn is not None:
                conn.close()

    def _prepare(
        self, method: str, bare: str, headers
    ) -> tuple[float, dict[str, str]]:
        """Shared per-attempt gating for every outbound request: raise
        DeadlineExceeded on an exhausted budget (before spending any
        socket work), fail fast on an open breaker, derive the socket
        timeout from the remaining budget, and export the budget as
        X-Deadline-Ms.  (The rpc.send fault hook fires in the caller's
        breaker-recorded region, not here.)"""
        dl = resilience.current_deadline()
        if dl is not None and dl.expired:
            raise resilience.DeadlineExceeded(
                f"deadline exceeded before {method} {bare} to {self.host}"
            )
        if self.breakers is not None:
            self.breakers.check(self.host)
        hdrs = dict(headers or {})
        if self.internal_token:
            hdrs.setdefault("X-Internal-Token", self.internal_token)
        timeout = self.timeout
        if dl is not None:
            timeout = min(timeout, max(dl.remaining(), 0.001))
            hdrs[resilience.DEADLINE_HEADER] = dl.header_value()
        return timeout, hdrs

    def _record_breaker(self, ok: bool) -> None:
        if self.breakers is not None:
            self.breakers.record(self.host, ok)

    def _request_chunked(
        self,
        method: str,
        path: str,
        reader,
        query: dict[str, Any] | None = None,
        headers: dict[str, str] | None = None,
    ) -> tuple[int, bytes]:
        """Issue a request whose body streams off ``reader`` with
        chunked transfer encoding — constant-size writes, no payload
        materialization.  Single-shot (the reader can't be rewound), but
        still rides the breaker/deadline gates."""
        bare = path
        if query:
            path = path + "?" + urllib.parse.urlencode(query)

        def chunks():
            while True:
                data = reader.read(self.chunk_bytes)
                if not data:
                    return
                yield data

        timeout, hdrs = self._prepare(method, bare, headers)
        conn = None
        try:
            try:
                faults.check("rpc.send", host=self.host, path=bare)
                conn = http.client.HTTPConnection(self.host, timeout=timeout)
                conn.request(
                    method,
                    path,
                    body=chunks(),
                    headers={**hdrs, "Transfer-Encoding": "chunked"},
                    encode_chunked=True,
                )
                resp = conn.getresponse()
                data = resp.read()
            except resilience.TRANSPORT_ERRORS:
                self._record_breaker(False)
                raise
            self._record_breaker(resp.status < 500)
            if resp.status == 429:
                raise _shed_error(
                    self.host, data, {k.lower(): v for k, v in resp.getheaders()}
                )
            return resp.status, data
        finally:
            if conn is not None:
                conn.close()

    def _open_stream(
        self,
        method: str,
        path: str,
        query: dict[str, Any] | None = None,
        headers: dict[str, str] | None = None,
    ) -> stream_mod.HTTPBodyStream:
        """Open an error-checked body stream; the connection dial (and
        the status-line read) retries with backoff, the returned stream
        does not.  Caller owns close()."""
        bare = path
        if query:
            path = path + "?" + urllib.parse.urlencode(query)

        def _open():
            timeout, hdrs = self._prepare(method, bare, headers)
            faults.check("rpc.send", host=self.host, path=bare)
            conn = http.client.HTTPConnection(self.host, timeout=timeout)
            try:
                conn.request(method, path, headers=hdrs)
                resp = conn.getresponse()
            except BaseException:
                conn.close()
                raise
            return stream_mod.HTTPBodyStream(resp, conn, self.chunk_bytes)

        s = stream_mod.open_with_retry(
            _open, attempts=self.stream_retries, backoff=self.stream_backoff
        )
        if s.status >= 400:
            with s:
                data = s.read()
            if s.status == 412:
                raise PreconditionFailedError(_err_text(data))
            if s.status == 504:
                raise resilience.DeadlineExceeded(_err_text(data))
            raise ClientError(s.status, _err_text(data))
        return s

    def _check(self, status: int, data: bytes) -> bytes:
        if status == 412:
            raise PreconditionFailedError(_err_text(data))
        if status == 429:
            # Paths that didn't go through _attempt (stream opens);
            # headers are gone here, so the hint defaults.
            raise resilience.ShedError(_err_text(data))
        if status == 504:
            # The peer's deadline expired — surface it as a deadline
            # failure so the coordinator 504s too instead of treating
            # the exhausted budget as a node failure to fail over.
            raise resilience.DeadlineExceeded(_err_text(data))
        if status >= 400:
            raise ClientError(status, _err_text(data))
        return data

    # ------------------------------------------------------------------
    # queries (reference: client.go:223-311)
    # ------------------------------------------------------------------

    def execute_query(
        self,
        index: str,
        query: str,
        slices: list[int] | None = None,
        remote: bool = False,
        column_attrs: bool = False,
        trace_headers: dict[str, str] | None = None,
        tracer=None,
        idempotent: bool = False,
        allow_partial: bool = False,
    ) -> list:
        """``trace_headers`` (X-Trace-Id/X-Span-Id) continue the caller's
        trace on the peer; the peer's spans come back in an
        X-Trace-Spans response header and are absorbed into ``tracer``.

        ``idempotent`` opts this call into the transport retry policy —
        the executor sets it on read-only map legs; write fan-out stays
        single-shot.  ``allow_partial`` asks the peer to answer with the
        surviving slices (plus a missing-slice marker) instead of
        failing the whole query when replicas are down."""
        pb = wire.QueryRequest(
            Query=query,
            Slices=slices or [],
            Remote=remote,
            ColumnAttrs=column_attrs,
        )
        headers = {"Content-Type": PROTOBUF, "Accept": PROTOBUF}
        if trace_headers:
            headers.update(trace_headers)
        status, data, resp_headers = self._request_meta(
            "POST",
            f"/index/{index}/query",
            query={"allowPartial": "true"} if allow_partial else None,
            body=pb.SerializeToString(),
            headers=headers,
            idempotent=idempotent,
        )
        if tracer is not None:
            payload = resp_headers.get("x-trace-spans")
            if payload:
                tracer.absorb(payload)
        resp = wire.QueryResponse()
        resp.ParseFromString(self._check(status, data))
        if resp.Err:
            raise ClientError(status, resp.Err)
        return [codec.result_from_proto(r) for r in resp.Results]

    def execute_pql(self, index: str, query: str) -> Any:
        """Single-call convenience (reference: client.go:258-281)."""
        results = self.execute_query(index, query)
        if not results:
            raise ClientError(200, "empty response")
        return results[0]

    # ------------------------------------------------------------------
    # schema (reference: client.go:63-220, 704-826)
    # ------------------------------------------------------------------

    def schema(self) -> list[dict]:
        status, data = self._request("GET", "/schema")
        return json.loads(self._check(status, data))["indexes"]

    def max_slice_by_index(self, inverse: bool = False) -> dict[str, int]:
        query = {"inverse": "true"} if inverse else None
        status, data = self._request("GET", "/slices/max", query=query)
        return json.loads(self._check(status, data))["maxSlices"]

    def create_index(self, index: str, options: dict | None = None) -> None:
        body = json.dumps({"options": options or {}}).encode()
        status, data = self._request("POST", f"/index/{index}", body=body)
        if status == 409:
            raise ClientError(409, "index already exists")
        self._check(status, data)

    def delete_index(self, index: str) -> None:
        status, data = self._request("DELETE", f"/index/{index}")
        self._check(status, data)

    def create_frame(
        self, index: str, frame: str, options: dict | None = None
    ) -> None:
        body = json.dumps({"options": options or {}}).encode()
        status, data = self._request(
            "POST", f"/index/{index}/frame/{frame}", body=body
        )
        if status == 409:
            raise ClientError(409, "frame already exists")
        self._check(status, data)

    def frame_views(self, index: str, frame: str) -> list[str]:
        status, data = self._request(
            "GET", f"/index/{index}/frame/{frame}/views"
        )
        return json.loads(self._check(status, data))["views"]

    # --- BSI integer fields (pilosa_tpu extension, JSON endpoints) ---

    def create_field(
        self, index: str, frame: str, field: str, min: int, max: int
    ) -> None:
        body = json.dumps({"min": int(min), "max": int(max)}).encode()
        status, data = self._request(
            "POST", f"/index/{index}/frame/{frame}/field/{field}", body=body
        )
        self._check(status, data)

    def delete_field(self, index: str, frame: str, field: str) -> None:
        status, data = self._request(
            "DELETE", f"/index/{index}/frame/{frame}/field/{field}"
        )
        self._check(status, data)

    def frame_fields(self, index: str, frame: str) -> list[dict]:
        status, data = self._request(
            "GET", f"/index/{index}/frame/{frame}/fields"
        )
        return json.loads(self._check(status, data))["fields"]

    def fragment_nodes(
        self, index: str, slice_i: int, write: bool = False
    ) -> list[dict]:
        """Owners of a slice; ``write=True`` asks for the WRITE target
        set — during a rebalance transition that includes the new
        ring's owners, so import fan-outs dual-write migrating
        slices."""
        query: dict = {"index": index, "slice": slice_i}
        if write:
            query["write"] = "true"
        status, data = self._request("GET", "/fragment/nodes", query=query)
        return json.loads(self._check(status, data))

    # ------------------------------------------------------------------
    # import / export (reference: client.go:314-476)
    # ------------------------------------------------------------------

    def import_bits(
        self,
        index: str,
        frame: str,
        slice_i: int,
        bits,
        consistency: str = "quorum",
    ) -> None:
        """POST one slice's bits to every replica node (reference:
        client.go:314-401) with W-of-N acknowledgement.

        ``bits``: either a list of ``(row, col[, ts])`` tuples, or the
        vectorized form — a tuple of parallel numpy arrays ``(rows,
        cols[, timestamps])`` (discriminated by the ndarray element, so
        a tuple-of-bit-tuples is still treated as bit tuples).

        ``consistency`` (one|quorum|all) sets W over the slice's write
        owners: a sub-W ack count FAILS loudly naming the dead hosts —
        never "success because someone acked" — and every unreachable
        replica's payload is queued as a hint on the first acked node
        (``POST /replicate/hint``) so it converges on recovery without
        waiting for anti-entropy."""
        pb = wire.ImportRequest(Index=index, Frame=frame, Slice=slice_i)
        if (
            isinstance(bits, tuple)
            and len(bits) in (2, 3)
            and isinstance(bits[0], np.ndarray)
        ):
            # Vectorized form: (rows, cols[, timestamps]) parallel
            # arrays — no per-bit Python objects anywhere on the path.
            rows, cols = bits[0], bits[1]
            ts = bits[2] if len(bits) > 2 else None
            pb.RowIDs.extend(np.asarray(rows, dtype=np.uint64).tolist())
            pb.ColumnIDs.extend(np.asarray(cols, dtype=np.uint64).tolist())
            if ts is not None and np.any(ts):
                pb.Timestamps.extend(np.asarray(ts, dtype=np.int64).tolist())
        else:
            has_ts = any(len(b) > 2 and b[2] for b in bits)
            # Bulk extend: one C-level copy per field, not a Python
            # append per bit.
            pb.RowIDs.extend([b[0] for b in bits])
            pb.ColumnIDs.extend([b[1] for b in bits])
            if has_ts:
                pb.Timestamps.extend(
                    [b[2] if len(b) > 2 and b[2] else 0 for b in bits]
                )
        payload = pb.SerializeToString()

        def _post(client) -> None:
            status, data = client._request(
                "POST",
                "/import",
                body=payload,
                headers={"Content-Type": PROTOBUF, "Accept": PROTOBUF},
            )
            resp = wire.ImportResponse()
            resp.ParseFromString(client._check(status, data))
            if resp.Err:
                raise ClientError(500, resp.Err)

        self._fanout_write(
            index, slice_i, _post, consistency, "import", payload,
            rows=len(pb.RowIDs),
        )

    def import_value(
        self,
        index: str,
        frame: str,
        field: str,
        slice_i: int,
        columns,
        values,
        consistency: str = "quorum",
    ) -> None:
        """POST one slice's field values to every replica node — the
        columnar BSI import leg, with the same W-of-N acknowledgement +
        hinted-handoff contract as :meth:`import_bits`."""
        payload = json.dumps(
            {
                "index": index,
                "frame": frame,
                "field": field,
                "slice": int(slice_i),
                "columnIDs": np.asarray(columns, dtype=np.int64).tolist(),
                "values": np.asarray(values, dtype=np.int64).tolist(),
            }
        ).encode()

        def _post(client) -> None:
            status, data = client._request(
                "POST", "/import-value", body=payload
            )
            client._check(status, data)

        self._fanout_write(
            index, slice_i, _post, consistency, "import-value", payload,
            rows=len(np.asarray(columns)),
        )

    def _fanout_write(
        self,
        index: str,
        slice_i: int,
        post_fn,
        consistency: str,
        hint_kind: str,
        payload: bytes,
        rows: int,
    ) -> None:
        """Shared import fan-out: every write owner receives the
        payload, acks tally against W = required_acks(consistency, N),
        failed replicas' payloads queue as hints on the first acked
        node, and a sub-W outcome raises with every failing host named."""
        from pilosa_tpu.replicate.quorum import required_acks, validate_level

        validate_level(consistency)
        nodes = self.fragment_nodes(index, slice_i, write=True)
        if not nodes:
            raise ClientError(500, f"no nodes for slice {slice_i}")
        acked: list[str] = []
        errs: list[str] = []
        failed_hosts: list[str] = []
        for node in nodes:
            # One dead replica must not abort the fan-out: transport
            # failures (and open breakers) collect alongside HTTP
            # errors, each prefixed with the failing HOST, and every
            # surviving replica still receives the import.
            try:
                post_fn(self._peer(node["host"]))
                acked.append(node["host"])
            except (
                (ClientError, resilience.BreakerOpenError,
                 resilience.ShedError)
                + resilience.TRANSPORT_ERRORS
            ) as e:
                errs.append(f"{node['host']}: {e}")
                failed_hosts.append(node["host"])
        hint_errs: list[str] = []
        if failed_hosts and acked:
            holder = self._peer(acked[0])
            for host in failed_hosts:
                try:
                    holder.queue_hint(
                        host, index, slice_i, hint_kind, payload, rows
                    )
                except (
                    (ClientError, resilience.BreakerOpenError,
                     resilience.ShedError)
                    + resilience.TRANSPORT_ERRORS
                ) as e:
                    hint_errs.append(f"{host}: {e}")
        need = required_acks(consistency, len(nodes))
        if len(acked) < need:
            raise ClientError(
                500,
                f"import acknowledged by {len(acked)} of {len(nodes)} "
                f"replicas (need {need} at consistency={consistency}): "
                + "; ".join(errs),
            )
        if hint_errs:
            # W was met but the dead replicas' hints could not queue:
            # convergence falls back to anti-entropy — fail loudly so
            # the caller knows the handoff guarantee did not attach.
            raise ClientError(
                500, "import acked but hint queue failed: " + "; ".join(hint_errs)
            )

    # ------------------------------------------------------------------
    # replication (pilosa_tpu/replicate)
    # ------------------------------------------------------------------

    def queue_hint(
        self, target: str, index: str, slice_i: int, kind: str,
        payload: bytes, rows: int,
    ) -> None:
        """Queue a write payload on THIS node as a hint destined for
        ``target`` (hinted handoff: any live node may hold hints for a
        dead replica)."""
        body = json.dumps(
            {
                "target": target,
                "index": index,
                "slice": int(slice_i),
                "kind": kind,
                "payload": base64.b64encode(payload).decode(),
                "rows": int(rows),
            }
        ).encode()
        status, data = self._request("POST", "/replicate/hint", body=body)
        self._check(status, data)

    def replicate_versions(self, index: str, slices) -> dict[int, int]:
        """The node's per-slice write versions for ``slices`` — the
        read path's staleness probe (one call covers many slices)."""
        body = json.dumps(
            {"index": index, "slices": [int(s) for s in slices]}
        ).encode()
        # A pure read in POST shape (slice lists outgrow a query
        # string) — idempotent, so it rides the retry policy.
        status, data = self._request(
            "POST", "/replicate/versions", body=body, idempotent=True
        )
        versions = json.loads(self._check(status, data))["versions"]
        return {int(k): int(v) for k, v in versions.items()}

    def observe_version(self, index: str, slice_i: int, version: int) -> None:
        """Stamp the node's slice version forward (max-merge) — the
        post-repair/post-replay convergence marker."""
        body = json.dumps(
            {
                "index": index,
                "slice": int(slice_i),
                "version": int(version),
                "action": "observe",
            }
        ).encode()
        status, data = self._request(
            "POST", "/replicate/versions", body=body, idempotent=True
        )
        self._check(status, data)

    def import_raw(self, payload: bytes) -> None:
        """Replay a queued /import payload verbatim on THIS node, on the
        internal admission lane (hint replay must never starve behind a
        client write storm)."""
        status, data = self._request(
            "POST",
            "/import",
            body=payload,
            headers={
                "Content-Type": PROTOBUF,
                "Accept": PROTOBUF,
                "X-Internal-Lane": "1",
            },
        )
        resp = wire.ImportResponse()
        resp.ParseFromString(self._check(status, data))
        if resp.Err:
            raise ClientError(500, resp.Err)

    def import_value_raw(self, payload: bytes) -> None:
        """Replay a queued /import-value payload verbatim (internal
        lane)."""
        status, data = self._request(
            "POST",
            "/import-value",
            body=payload,
            headers={"X-Internal-Lane": "1"},
        )
        self._check(status, data)

    def export_csv(self, index: str, frame: str, view: str, slice_i: int) -> str:
        """Whole-export convenience over :meth:`export_to`."""
        buf = io.BytesIO()
        self.export_to(buf, index, frame, view, slice_i)
        return buf.getvalue().decode()

    def export_to(self, w, index: str, frame: str, view: str, slice_i: int) -> None:
        """Stream one fragment's CSV into ``w`` in constant-size
        chunks, redirecting to the owning node on 412 (reference:
        client.go:403-476).  The redirect decision happens on the
        status line, before any body moves."""
        try:
            src = self._export_stream(index, frame, view, slice_i)
        except PreconditionFailedError:
            src = None
            for node in self.fragment_nodes(index, slice_i):
                if node["host"] == self.host:
                    continue
                try:
                    src = self._peer(node["host"])._export_stream(
                        index, frame, view, slice_i
                    )
                    break
                except PreconditionFailedError:
                    continue
            if src is None:
                raise
        with src:
            for chunk in src:
                w.write(chunk)

    def _export_stream(
        self, index: str, frame: str, view: str, slice_i: int
    ) -> stream_mod.HTTPBodyStream:
        return self._open_stream(
            "GET",
            "/export",
            query={"index": index, "frame": frame, "view": view, "slice": slice_i},
            headers={"Accept": "text/csv"},
        )

    # ------------------------------------------------------------------
    # backup / restore (reference: client.go:478-702)
    # ------------------------------------------------------------------

    def stream_backup_slice(
        self, index: str, frame: str, view: str, slice_i: int
    ) -> stream_mod.HTTPBodyStream | None:
        """Open one fragment's tar archive as a body stream; None if
        the fragment does not exist (reference: client.go:590-648
        returns a ReadCloser).  Caller owns close()."""
        try:
            return self._open_stream(
                "GET",
                "/fragment/data",
                query={
                    "index": index,
                    "frame": frame,
                    "view": view,
                    "slice": slice_i,
                },
            )
        except ClientError as e:
            if e.status == 404:
                return None
            raise

    def backup_slice(
        self, index: str, frame: str, view: str, slice_i: int
    ) -> bytes | None:
        """Whole-archive convenience over :meth:`stream_backup_slice`."""
        src = self.stream_backup_slice(index, frame, view, slice_i)
        if src is None:
            return None
        with src:
            return src.read()

    def restore_slice_from(
        self, index: str, frame: str, view: str, slice_i: int, reader,
        stage: bool = False,
    ) -> None:
        """POST one fragment archive off ``reader`` with a chunked body
        — constant memory on both ends.  ``stage=True`` (the rebalance
        bulk-copy path) asks the receiver to register the restored
        fragment's HBM mirror through its background staging lane."""
        query: dict = {
            "index": index, "frame": frame, "view": view, "slice": slice_i,
        }
        if stage:
            query["stage"] = "true"
        status, data = self._request_chunked(
            "POST", "/fragment/data", reader, query=query
        )
        self._check(status, data)

    def restore_slice(
        self, index: str, frame: str, view: str, slice_i: int, payload: bytes
    ) -> None:
        self.restore_slice_from(index, frame, view, slice_i, io.BytesIO(payload))

    def backup_to(self, w, index: str, frame: str, view: str) -> None:
        """Stream every slice's archive into one tar-of-tars keyed by
        slice id (reference: client.go:478-560 writes a single tar with
        numbered entries).

        Tar entry headers need sizes up front but a chunked response
        has none, so each slice spools through a SpooledTemporaryFile
        (disk past a few chunks) — peak MEMORY stays at chunk scale no
        matter the fragment size (the reference spools the same way,
        client.go:529-545)."""
        import tarfile
        import time as _time

        from pilosa_tpu.core.view import VIEW_INVERSE, is_valid_view

        # Whole-frame backup addresses the two base views only, like
        # the reference (client.go:491-497 ErrInvalidView); derived
        # (time) views move via the per-view frame-restore protocol.
        if not is_valid_view(view):
            raise ClientError(400, "invalid view")
        inverse = view == VIEW_INVERSE
        tw = tarfile.open(fileobj=w, mode="w|")
        max_slices = self.max_slice_by_index(inverse=inverse)
        for slice_i in range(max_slices.get(index, 0) + 1):
            src = self.stream_backup_slice(index, frame, view, slice_i)
            if src is None:
                continue
            with src, tempfile.SpooledTemporaryFile(
                max_size=4 * self.chunk_bytes
            ) as spool:
                for chunk in src:
                    spool.write(chunk)
                size = spool.tell()
                spool.seek(0)
                info = tarfile.TarInfo(str(slice_i))
                info.size = size
                info.mtime = int(_time.time())
                tw.addfile(info, spool)
        tw.close()

    def restore_from(self, r, index: str, frame: str, view: str) -> None:
        """Restore a tar-of-tars, streaming each member straight from
        the archive reader into a chunked POST (reference:
        client.go:562-588)."""
        import tarfile

        tr = tarfile.open(fileobj=r, mode="r|")
        for member in tr:
            slice_i = int(member.name)
            self.restore_slice_from(
                index, frame, view, slice_i, tr.extractfile(member)
            )
        tr.close()

    def tier_restore(
        self, index: str, frame: str, view: str, slice_i: int
    ) -> int:
        """Ask the node to restore one fragment from ITS configured
        object store (the store-riding rebalance bulk-copy path).
        Returns the restored byte count; raises ClientError 501 when
        the node has no tier configured — callers fall back to peer
        streaming."""
        payload = json.dumps(
            {
                "index": index,
                "frame": frame,
                "view": view,
                "slice": int(slice_i),
            }
        ).encode()
        status, data = self._request("POST", "/tier/restore", body=payload)
        return int(json.loads(self._check(status, data)).get("bytes", 0))

    def restore_frame(self, host: str, index: str, frame: str) -> None:
        """Ask the server to pull a frame from another cluster
        (reference: client.go:704-738)."""
        status, data = self._request(
            "POST",
            f"/index/{index}/frame/{frame}/restore",
            query={"host": host},
        )
        self._check(status, data)

    # ------------------------------------------------------------------
    # sync endpoints (reference: client.go:828-1010)
    # ------------------------------------------------------------------

    def fragment_blocks(
        self, index: str, frame: str, view: str, slice_i: int
    ) -> list[tuple[int, bytes]]:
        status, data = self._request(
            "GET",
            "/fragment/blocks",
            query={"index": index, "frame": frame, "view": view, "slice": slice_i},
        )
        blocks = json.loads(self._check(status, data))["blocks"]
        return [(b["id"], base64.b64decode(b["checksum"])) for b in blocks]

    def block_data(
        self, index: str, frame: str, view: str, slice_i: int, block: int
    ) -> tuple[list[int], list[int]]:
        pb = wire.BlockDataRequest(
            Index=index, Frame=frame, View=view, Slice=slice_i, Block=block
        )
        status, data = self._request(
            "GET",
            "/fragment/block/data",
            body=pb.SerializeToString(),
            headers={"Content-Type": PROTOBUF, "Accept": PROTOBUF},
        )
        resp = wire.BlockDataResponse()
        resp.ParseFromString(self._check(status, data))
        return list(resp.RowIDs), list(resp.ColumnIDs)

    def import_view_bits(
        self,
        index: str,
        frame: str,
        view: str,
        slice_i: int,
        sets: tuple[list[int], list[int]],
        clears: tuple[list[int], list[int]],
    ) -> None:
        """View-scoped raw sets/clears on THIS node — the anti-entropy
        repair push for derived (inverse/time) views.  ``sets`` and
        ``clears`` are (row_ids, absolute_column_ids) pairs."""
        pb = wire.ImportViewRequest(
            Index=index,
            Frame=frame,
            View=view,
            Slice=slice_i,
            RowIDs=[int(r) for r in sets[0]],
            ColumnIDs=[int(c) for c in sets[1]],
            ClearRowIDs=[int(r) for r in clears[0]],
            ClearColumnIDs=[int(c) for c in clears[1]],
        )
        status, data = self._request(
            "POST",
            "/fragment/import-view",
            body=pb.SerializeToString(),
            headers={"Content-Type": PROTOBUF, "Accept": PROTOBUF},
        )
        resp = wire.ImportResponse()
        resp.ParseFromString(self._check(status, data))
        if resp.Err:
            raise ClientError(500, resp.Err)

    def column_attr_diff(
        self, index: str, blocks: list[tuple[int, bytes]]
    ) -> dict[int, dict]:
        return self._attr_diff(f"/index/{index}/attr/diff", blocks)

    def row_attr_diff(
        self, index: str, frame: str, blocks: list[tuple[int, bytes]]
    ) -> dict[int, dict]:
        return self._attr_diff(f"/index/{index}/frame/{frame}/attr/diff", blocks)

    def _attr_diff(self, path: str, blocks: list[tuple[int, bytes]]) -> dict[int, dict]:
        body = json.dumps(
            {
                "blocks": [
                    {"id": bid, "checksum": base64.b64encode(chk).decode()}
                    for bid, chk in blocks
                ]
            }
        ).encode()
        # POST in shape, but a pure read (checksum diff) — idempotent,
        # so the anti-entropy loop rides the retry policy.
        status, data = self._request("POST", path, body=body, idempotent=True)
        if status == 404:
            raise ClientError(404, "frame not found")
        attrs = json.loads(self._check(status, data))["attrs"]
        return {int(k): v for k, v in attrs.items()}


def _err_text(data: bytes) -> str:
    try:
        return json.loads(data).get("error", data.decode(errors="replace"))
    except (json.JSONDecodeError, AttributeError, UnicodeDecodeError):
        # UnicodeDecodeError: a non-UTF8 (e.g. protobuf) error body —
        # json.loads raises it BEFORE JSONDecodeError can.
        return data.decode(errors="replace")


def _shed_error(
    host: str, data: bytes, headers: dict[str, str]
) -> resilience.ShedError:
    """A 429 response as a ShedError carrying the server's Retry-After
    hint — the precise millisecond figure from the JSON body when
    present, else the whole-seconds header, else 1 s."""
    retry_after = 1.0
    try:
        retry_after = float(headers.get("retry-after", "") or 1.0)
    except ValueError:
        pass
    try:
        ms = json.loads(data).get("retryAfterMs")
        if ms is not None:
            retry_after = float(ms) / 1000.0
    except (json.JSONDecodeError, AttributeError, TypeError, ValueError):
        pass
    return resilience.ShedError(
        _err_text(data), retry_after_s=retry_after, host=host
    )


def client_factory(node) -> InternalClient:
    """Executor-compatible factory: node (or host string) -> client."""
    host = node if isinstance(node, str) else node.host
    return InternalClient(host)
