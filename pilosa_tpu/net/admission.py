"""Server-side admission control: cost classes, bounded queues,
deadline-aware load shedding, per-tenant weighted fairness + quotas.

The HTTP adapter (``ThreadingHTTPServer``) admits every connection
unconditionally, so under overload a node queues work it can never
finish inside its deadline and answers 504 *after* burning device time
— the classic overload failure mode (see Facebook's "Fail at Scale"
adaptive-LIFO/CoDel design).  This layer sits in FRONT of the
executor/coalescer and decides, per request, in microseconds:

* **Cost classes.**  Every query is classified from its parsed plan
  (``exec.plan.cost_class``): ``point`` (Count/Bitmap algebra),
  ``heavy`` (TopN / Sum / Min / Max / Range), ``write`` (PQL writes and
  bulk imports) — plus ``internal`` for the remote legs of another
  node's map/reduce (``QueryRequest.Remote``) and anti-entropy repair.
  Each class gets its own concurrency gate and bounded queue, so a
  storm of TopNs cannot starve point lookups and vice versa.

* **Deadline-aware shedding.**  A request that cannot be served within
  its remaining ``X-Deadline-Ms`` budget — the queue is full, or the
  predicted queue wait (queue position x EWMA service time / gate
  width) exceeds the budget — is answered ``429 + Retry-After``
  immediately, BEFORE any coalescer/device work.  The Retry-After hint
  is the predicted drain time of the queue ahead.

* **Internal priority.**  The ``internal`` lane is a separate gate:
  client traffic can never occupy its slots, so a saturated cluster
  cannot distributed-livelock (every node's client gates full, every
  node's map legs starving behind them).  The lane is still *bounded* —
  a truly saturated node sheds internal legs too, which the
  coordinator's failover treats as a node failure (try a replica, or
  degrade under ``allowPartial``) rather than a breaker trip.

* **Tenant fairness.**  Requests carry a tenant tag (``X-Api-Key`` →
  tenant via :class:`TenantRegistry`, or a configured ``X-Tenant``
  name; untagged traffic rides the default tenant).  Inside each class
  gate the queue is weighted-fair (deficit round-robin over per-tenant
  FIFOs): one hot tenant's backlog occupies only its own per-tenant
  queue slots and its weighted share of grants, so another tenant's
  point queries keep admitting with near-empty-queue latency.  The
  internal lane is exempt — remote map legs are *charged* to the
  originating tenant (the coordinator forwards ``X-Tenant``) but never
  queued behind a tenant boundary.

* **Tenant quotas.**  Optional per-tenant token buckets for request
  rate (QPS) and ingress bytes/s.  Exhaustion answers ``429`` with
  ``X-Quota-Limit`` / ``X-Quota-Remaining`` / ``Retry-After`` via
  :class:`QuotaError` (a :class:`~pilosa_tpu.net.resilience.ShedError`,
  so the existing retry/breaker algebra applies: clients back off,
  breakers never trip).

Observability: ``net.admission.admitted|shed|queueTimeout`` counters
(``class:`` tag), per-tenant ``net.admission.tenantAdmitted|tenantShed|
quotaShed`` counters (``tenant:``/``class:`` tags),
``net.admission.queueWaitMs`` histogram, scrape-time
``net.admission.active|queueDepth|ewmaServiceMs`` (+ per-tenant
``tenantQueued``/``quotaRemaining``) gauges on /metrics, the per-class
queue state on ``GET /debug/health``, the per-tenant table on
``GET /debug/tenants``, and an ``admission`` span in every query trace.
"""

from __future__ import annotations

import threading
import time
from collections import deque

from pilosa_tpu.net import resilience as rz

# Class names (the first three mirror exec.plan.COST_*; admission owns
# the internal lane, which is a transport property, not a plan one).
CLASS_POINT = "point"
CLASS_HEAVY = "heavy"
CLASS_WRITE = "write"
CLASS_INTERNAL = "internal"
# Standing-query notification batches (pilosa_tpu/subscribe): a
# dedicated bounded lane so push evaluation can never occupy a query
# slot — subscribers starve before queries do, by construction.
CLASS_SUBSCRIBE = "subscribe"

CLASSES = (
    CLASS_POINT,
    CLASS_HEAVY,
    CLASS_WRITE,
    CLASS_INTERNAL,
    CLASS_SUBSCRIBE,
)

# The tenant untagged traffic is charged to.  Always registered, weight
# 1, no quota — a single-tenant deployment behaves exactly as before
# tenants existed.
DEFAULT_TENANT = "default"

# EWMA smoothing for observed service times: new = a*obs + (1-a)*old.
_EWMA_ALPHA = 0.2
# Service-time estimate before the first observation (ms).  Deliberately
# modest: the first storm against a cold gate should shed on queue
# depth, not on a wild wait prediction.
_EWMA_INIT_MS = 25.0
# Retry-After hints are clamped to this window.
_MIN_RETRY_AFTER_S = 0.05
_MAX_RETRY_AFTER_S = 30.0


class QuotaError(rz.ShedError):
    """A tenant exhausted its configured QPS or bytes/s budget.  Still
    a shed (429, Retry-After, no breaker trip) — but carries the quota
    headers so a well-behaved client can pace itself instead of
    retry-hammering."""

    def __init__(
        self,
        message: str,
        retry_after_s: float,
        tenant: str,
        kind: str,
        limit: float,
        remaining: float,
    ):
        super().__init__(message, retry_after_s=retry_after_s)
        self.tenant = tenant
        self.quota_kind = kind  # "qps" | "bytes"
        self.quota_limit = limit
        self.quota_remaining = remaining


class Tenant:
    """One configured tenant: fair-queue weight + optional quotas.
    Spec grammar (config ``[net] tenants``): ``name:weight[:qps
    [:bytes_per_s]]`` — 0 means unlimited."""

    __slots__ = ("name", "weight", "qps", "bytes_per_s")

    def __init__(
        self,
        name: str,
        weight: int = 1,
        qps: float = 0.0,
        bytes_per_s: float = 0.0,
    ):
        if not name:
            raise ValueError("tenant name must be non-empty")
        self.name = name
        self.weight = max(1, int(weight))
        self.qps = max(0.0, float(qps))
        self.bytes_per_s = max(0.0, float(bytes_per_s))

    @classmethod
    def parse(cls, spec: str) -> "Tenant":
        parts = [p.strip() for p in spec.strip().split(":")]
        if not parts or not parts[0]:
            raise ValueError(f"bad tenant spec {spec!r}")
        name = parts[0]
        try:
            weight = int(parts[1]) if len(parts) > 1 and parts[1] else 1
            qps = float(parts[2]) if len(parts) > 2 and parts[2] else 0.0
            byps = float(parts[3]) if len(parts) > 3 and parts[3] else 0.0
        except ValueError as e:
            raise ValueError(f"bad tenant spec {spec!r}: {e}") from e
        return cls(name, weight, qps, byps)


class _TokenBucket:
    """Continuous-refill token bucket; capacity = one second of burst.
    Caller holds the registry lock."""

    __slots__ = ("rate", "capacity", "tokens", "t_last")

    def __init__(self, rate: float):
        self.rate = float(rate)
        self.capacity = max(self.rate, 1.0)
        self.tokens = self.capacity
        self.t_last = time.monotonic()

    def _refill(self) -> None:
        now = time.monotonic()
        self.tokens = min(
            self.capacity, self.tokens + (now - self.t_last) * self.rate
        )
        self.t_last = now

    def try_take(self, n: float) -> bool:
        self._refill()
        if self.tokens >= n:
            self.tokens -= n
            return True
        return False

    def retry_after_s(self, n: float) -> float:
        if self.rate <= 0:
            return _MAX_RETRY_AFTER_S
        want = min(n, self.capacity)
        return min(
            max((want - self.tokens) / self.rate, _MIN_RETRY_AFTER_S),
            _MAX_RETRY_AFTER_S,
        )


class _TenantState:
    """Registry-lock-guarded per-tenant accounting + quota buckets."""

    __slots__ = (
        "tenant",
        "qps_bucket",
        "bytes_bucket",
        "admitted",
        "shed",
        "quota_shed",
        "wait_ewma_ms",
        "by_class",
    )

    def __init__(self, tenant: Tenant):
        self.tenant = tenant
        self.qps_bucket = _TokenBucket(tenant.qps) if tenant.qps else None
        self.bytes_bucket = (
            _TokenBucket(tenant.bytes_per_s) if tenant.bytes_per_s else None
        )
        self.admitted = 0
        self.shed = 0
        self.quota_shed = 0
        self.wait_ewma_ms = 0.0
        # class -> [admitted, shed]
        self.by_class: dict[str, list[int]] = {}


class TenantRegistry:
    """API-key → tenant resolution, WFQ weights, quota buckets, and the
    per-tenant counters behind ``GET /debug/tenants``.

    Unknown tenants resolve to ``default_tenant``; unknown *names* in a
    forwarded ``X-Tenant`` on the internal lane are still recorded (the
    coordinator already authenticated the originating key), so a
    fan-out is charged to its origin on every node it touches."""

    def __init__(
        self,
        tenants: "list[str | Tenant] | None" = None,
        keys: "list[str] | None" = None,
        default_tenant: str = DEFAULT_TENANT,
        internal_token: str = "",
        stats=None,
    ):
        from pilosa_tpu.obs.stats import NopStatsClient

        self.default_tenant = default_tenant or DEFAULT_TENANT
        self.internal_token = internal_token or ""
        self.stats = stats or NopStatsClient()
        self._mu = threading.Lock()
        self._tenants: dict[str, Tenant] = {}
        self._state: dict[str, _TenantState] = {}
        for spec in tenants or ():
            t = spec if isinstance(spec, Tenant) else Tenant.parse(spec)
            self._tenants[t.name] = t
            self._state[t.name] = _TenantState(t)
        if self.default_tenant not in self._tenants:
            t = Tenant(self.default_tenant)
            self._tenants[t.name] = t
            self._state[t.name] = _TenantState(t)
        # "apikey:tenant" pairs.  Keys mapping to unconfigured tenants
        # are a config error (caught by Config.validate too).
        self._keys: dict[str, str] = {}
        for pair in keys or ():
            key, sep, tname = pair.strip().partition(":")
            if not sep or not key or not tname:
                raise ValueError(f"bad tenant key spec {pair!r}")
            self._keys[key] = tname

    # -- resolution ----------------------------------------------------

    def resolve(self, api_key: str, tenant_header: str) -> str:
        """Tenant for a client request.  API key wins; a bare
        ``X-Tenant`` is honored only for configured tenants (arbitrary
        client-chosen names would be unbounded metric cardinality and a
        free quota reset)."""
        if api_key and api_key in self._keys:
            return self._keys[api_key]
        if tenant_header and tenant_header in self._tenants:
            return tenant_header
        return self.default_tenant

    def internal_ok(self, token: str) -> bool:
        """May this request claim the internal lane?  With no token
        configured the lane is open (trusted network / tests); with one
        configured, only holders of the token — clients cannot spoof
        X-Internal-Lane or the Remote flag to dodge tenant QoS."""
        return not self.internal_token or token == self.internal_token

    def weight(self, tenant: str) -> int:
        t = self._tenants.get(tenant)
        return t.weight if t is not None else 1

    def tenant_names(self) -> list[str]:
        return sorted(self._tenants)

    # -- quotas --------------------------------------------------------

    def check_quota(self, tenant: str, cls: str, nbytes: int = 0) -> None:
        """Debit one request (+ ``nbytes`` ingress) from the tenant's
        buckets or raise :class:`QuotaError`.  The internal lane is
        exempt (callers skip it): a coordinator's map legs were already
        paid for at the coordinator's front door."""
        st = self._state.get(tenant)
        if st is None or (st.qps_bucket is None and st.bytes_bucket is None):
            return
        with self._mu:
            if st.qps_bucket is not None and not st.qps_bucket.try_take(1.0):
                err = QuotaError(
                    f"quota: tenant {tenant!r} over {st.tenant.qps:g} qps",
                    retry_after_s=st.qps_bucket.retry_after_s(1.0),
                    tenant=tenant,
                    kind="qps",
                    limit=st.tenant.qps,
                    remaining=max(0.0, st.qps_bucket.tokens),
                )
            elif st.bytes_bucket is not None and nbytes > 0 and not (
                st.bytes_bucket.try_take(float(nbytes))
            ):
                err = QuotaError(
                    f"quota: tenant {tenant!r} over "
                    f"{st.tenant.bytes_per_s:g} bytes/s",
                    retry_after_s=st.bytes_bucket.retry_after_s(
                        float(nbytes)
                    ),
                    tenant=tenant,
                    kind="bytes",
                    limit=st.tenant.bytes_per_s,
                    remaining=max(0.0, st.bytes_bucket.tokens),
                )
            else:
                return
            err.cost_class = cls
            st.quota_shed += 1
            st.shed += 1
            st.by_class.setdefault(cls, [0, 0])[1] += 1
        self.stats.count_with_custom_tags(
            "net.admission.quotaShed",
            1,
            [f"tenant:{tenant}", f"kind:{err.quota_kind}"],
        )
        raise err

    # -- accounting ----------------------------------------------------

    def note_admitted(self, tenant: str, cls: str, wait_ms: float) -> None:
        st = self._state.get(tenant)
        if st is None:  # forwarded origin tenant not configured here
            st = self._state.setdefault(
                tenant, _TenantState(Tenant(tenant))
            )
        with self._mu:
            st.admitted += 1
            st.by_class.setdefault(cls, [0, 0])[0] += 1
            st.wait_ewma_ms = (
                _EWMA_ALPHA * wait_ms + (1.0 - _EWMA_ALPHA) * st.wait_ewma_ms
            )
        self.stats.count_with_custom_tags(
            "net.admission.tenantAdmitted",
            1,
            [f"tenant:{tenant}", f"class:{cls}"],
        )

    def note_shed(self, tenant: str, cls: str) -> None:
        st = self._state.get(tenant)
        if st is None:
            st = self._state.setdefault(
                tenant, _TenantState(Tenant(tenant))
            )
        with self._mu:
            st.shed += 1
            st.by_class.setdefault(cls, [0, 0])[1] += 1
        self.stats.count_with_custom_tags(
            "net.admission.tenantShed",
            1,
            [f"tenant:{tenant}", f"class:{cls}"],
        )

    # -- introspection -------------------------------------------------

    def snapshot(self) -> dict:
        """The ``GET /debug/tenants`` table."""
        out: dict = {}
        with self._mu:
            for name in sorted(self._state):
                st = self._state[name]
                t = st.tenant
                quota: dict = {}
                if st.qps_bucket is not None:
                    st.qps_bucket._refill()
                    quota["qps"] = {
                        "limit": t.qps,
                        "remaining": round(st.qps_bucket.tokens, 3),
                    }
                if st.bytes_bucket is not None:
                    st.bytes_bucket._refill()
                    quota["bytesPerS"] = {
                        "limit": t.bytes_per_s,
                        "remaining": round(st.bytes_bucket.tokens, 3),
                    }
                out[name] = {
                    "weight": t.weight,
                    "admitted": st.admitted,
                    "shed": st.shed,
                    "quotaShed": st.quota_shed,
                    "queueWaitEwmaMs": round(st.wait_ewma_ms, 3),
                    "quota": quota,
                    "classes": {
                        cls: {"admitted": a, "shed": s}
                        for cls, (a, s) in sorted(st.by_class.items())
                    },
                }
        return out

    def gauges(self) -> dict[str, float]:
        out: dict[str, float] = {}
        with self._mu:
            for name in sorted(self._state):
                st = self._state[name]
                if st.qps_bucket is not None:
                    st.qps_bucket._refill()
                    out[
                        f"net.admission.quotaRemaining[tenant:{name},kind:qps]"
                    ] = round(st.qps_bucket.tokens, 3)
                if st.bytes_bucket is not None:
                    st.bytes_bucket._refill()
                    out[
                        f"net.admission.quotaRemaining[tenant:{name},kind:bytes]"
                    ] = round(st.bytes_bucket.tokens, 3)
        return out


class Ticket:
    """One admitted request's slot in a class gate.  ``release()``
    returns the slot and feeds the observed service time back into the
    gate's EWMA (which drives the NEXT request's wait prediction)."""

    __slots__ = ("_gate", "wait_ms", "tenant", "_t_admit", "_released")

    def __init__(self, gate: "_ClassGate", wait_ms: float, tenant: str):
        self._gate = gate
        self.wait_ms = wait_ms
        self.tenant = tenant
        self._t_admit = time.monotonic()
        self._released = False

    def release(self) -> None:
        if self._released:  # idempotent — finally blocks may race close
            return
        self._released = True
        self._gate._release(time.monotonic() - self._t_admit)


class _Waiter:
    """One queued request.  ``cv`` shares the gate lock, so the
    scheduler wakes exactly the granted waiter — no thundering herd."""

    __slots__ = ("tenant", "granted", "cv")

    def __init__(self, tenant: str, mu: threading.RLock):
        self.tenant = tenant
        self.granted = False
        self.cv = threading.Condition(mu)


class _ClassGate:
    """Concurrency gate + bounded weighted-fair queue for one cost
    class.  The queue is a deficit-round-robin scheduler over
    per-tenant FIFOs: each backlogged tenant accrues ``weight`` grants
    per rotation, so a hot tenant's 64-deep backlog delays another
    tenant's first request by at most ~one grant, not 64.  With a
    single tenant (every pre-tenant deployment) the schedule degenerates
    to the original global FIFO, byte-for-byte."""

    def __init__(
        self,
        name: str,
        concurrency: int,
        queue_depth: int,
        stats,
        weight_of=None,
    ):
        from pilosa_tpu.obs.stats import NopStatsClient

        self.name = name
        self.concurrency = max(1, int(concurrency))
        self.queue_depth = max(0, int(queue_depth))
        self.stats = stats or NopStatsClient()
        self._weight_of = weight_of or (lambda tenant: 1)
        self._mu = threading.RLock()
        self._cv = threading.Condition(self._mu)
        self._active = 0
        self._queued = 0
        # tenant -> FIFO of waiters; _rr is the DRR rotation order over
        # tenants with backlog; _deficits the per-tenant grant credit.
        self._waiting: dict[str, deque] = {}
        self._rr: deque = deque()
        self._deficits: dict[str, float] = {}
        self._ewma_ms = _EWMA_INIT_MS
        # Lifetime counters for snapshot() — kept locally so
        # /debug/health reports them even without a stats backend.
        self.admitted = 0
        self.shed = 0

    # -- prediction ----------------------------------------------------

    def _predicted_wait_ms(self, ahead: int) -> float:
        """Expected queue wait for a request with ``ahead`` requests in
        front of it: the gate drains ``concurrency`` requests per EWMA
        service time."""
        return ahead * self._ewma_ms / self.concurrency

    def _predicted_ahead_locked(self, tenant: str) -> int:
        """How many grants land before a new arrival of ``tenant``
        under the DRR schedule.  Sole-tenant: everyone queued (the
        legacy global prediction).  Multi-tenant: the tenant's own
        backlog plus each other tenant's share over the rounds ours
        needs — a victim tenant's first request predicts a short wait
        even when a hot tenant has the queue deep."""
        own_q = self._waiting.get(tenant)
        own = len(own_q) if own_q else 0
        others = len(self._waiting) - (1 if own_q else 0)
        if others <= 0:
            return self._queued
        weight = max(1, int(self._weight_of(tenant)))
        rounds = own // weight + 1
        ahead = own
        for t, dq in self._waiting.items():
            if t != tenant:
                ahead += min(
                    len(dq), rounds * max(1, int(self._weight_of(t)))
                )
        return ahead

    def _retry_after_s(self, predicted_ms: float) -> float:
        return min(
            max(predicted_ms / 1000.0, _MIN_RETRY_AFTER_S),
            _MAX_RETRY_AFTER_S,
        )

    def _shed_locked(self, predicted_ms: float, reason: str) -> "rz.ShedError":
        self.shed += 1
        return rz.ShedError(
            f"admission: {self.name} {reason} "
            f"(active={self._active}/{self.concurrency} "
            f"queued={self._queued}/{self.queue_depth} "
            f"predicted_wait_ms={predicted_ms:.0f})",
            retry_after_s=self._retry_after_s(predicted_ms),
            cost_class=self.name,
        )

    # -- admission -----------------------------------------------------

    def acquire(
        self,
        deadline: "rz.Deadline | None",
        tenant: str = DEFAULT_TENANT,
    ) -> Ticket:
        """Admit (possibly after a bounded, deadline-clamped queue wait)
        or raise :class:`ShedError` without blocking on anything but
        this gate's own lock.  Stats emit OUTSIDE the critical section
        — this lock sits on every request's path (same treatment the
        PlanePool got in PR 8)."""
        t0 = time.monotonic()
        try:
            wait_ms = self._acquire_locked(deadline, t0, tenant)
        except rz.ShedError:
            self.stats.count_with_custom_tags(
                "net.admission.shed", 1, [f"class:{self.name}"]
            )
            raise
        self.stats.count_with_custom_tags(
            "net.admission.admitted", 1, [f"class:{self.name}"]
        )
        if wait_ms > 0:
            self.stats.histogram("net.admission.queueWaitMs", wait_ms)
        return Ticket(self, wait_ms, tenant)

    def _acquire_locked(
        self, deadline: "rz.Deadline | None", t0: float, tenant: str
    ) -> float:
        """The lock-held admission decision; returns the queue wait in
        ms or raises :class:`ShedError`."""
        with self._mu:
            if self._active < self.concurrency and self._queued == 0:
                self._active += 1
                self.admitted += 1
                return 0.0
            own_q = self._waiting.get(tenant)
            own = len(own_q) if own_q else 0
            predicted_ms = self._predicted_wait_ms(
                self._predicted_ahead_locked(tenant) + 1
            )
            # The queue bound is PER TENANT: a hot tenant filling its
            # allotment cannot consume another tenant's right to queue.
            if own >= self.queue_depth:
                raise self._shed_locked(predicted_ms, "queue full")
            if (
                deadline is not None
                and deadline.remaining_ms() < predicted_ms + self._ewma_ms
            ):
                # Queuing would only produce a 504 after the fact —
                # answer 429 now, before any work happens.
                raise self._shed_locked(
                    predicted_ms, "predicted wait exceeds deadline"
                )
            w = _Waiter(tenant, self._mu)
            self._enqueue_locked(w)
            self._queued += 1
            try:
                while not w.granted:
                    timeout = None
                    if deadline is not None:
                        timeout = deadline.remaining()
                        if timeout <= 0:
                            self._remove_waiter_locked(w)
                            raise self._shed_locked(
                                self._predicted_wait_ms(self._queued),
                                "deadline expired in queue",
                            )
                    w.cv.wait(timeout)
            finally:
                self._queued -= 1
            # _active was taken on our behalf by the scheduler at grant
            # time, so the slot is never double-issued.
            self.admitted += 1
            return (time.monotonic() - t0) * 1000.0

    # -- weighted-fair queue (lock held) -------------------------------

    def _enqueue_locked(self, w: _Waiter) -> None:
        dq = self._waiting.get(w.tenant)
        if dq is None:
            dq = self._waiting[w.tenant] = deque()
            self._rr.append(w.tenant)
            # Arrive with a full round's credit: a fresh tenant is
            # servable at its first rotation slot.
            self._deficits.setdefault(
                w.tenant, float(max(1, int(self._weight_of(w.tenant))))
            )
        dq.append(w)

    def _drop_tenant_locked(self, tenant: str) -> None:
        self._waiting.pop(tenant, None)
        try:
            self._rr.remove(tenant)
        except ValueError:
            pass
        self._deficits.pop(tenant, None)

    def _remove_waiter_locked(self, w: _Waiter) -> None:
        dq = self._waiting.get(w.tenant)
        if dq is None:
            return
        try:
            dq.remove(w)
        except ValueError:
            return
        if not dq:
            self._drop_tenant_locked(w.tenant)

    def _next_waiter_locked(self) -> "_Waiter | None":
        """Deficit round-robin: serve the head tenant while it has
        credit; otherwise top its deficit up by its weight and rotate.
        Weight >= 1 guarantees progress within one full rotation, so
        the starvation bound for any backlogged tenant is one rotation
        of grants, independent of other tenants' backlog depth."""
        while self._rr:
            t = self._rr[0]
            dq = self._waiting.get(t)
            if not dq:
                self._rr.popleft()
                self._deficits.pop(t, None)
                continue
            if self._deficits.get(t, 0.0) >= 1.0:
                self._deficits[t] -= 1.0
                w = dq.popleft()
                if not dq:
                    self._drop_tenant_locked(t)
                return w
            self._deficits[t] = self._deficits.get(t, 0.0) + float(
                max(1, int(self._weight_of(t)))
            )
            self._rr.rotate(-1)
        return None

    def _schedule_locked(self) -> None:
        while self._active < self.concurrency:
            w = self._next_waiter_locked()
            if w is None:
                return
            self._active += 1
            w.granted = True
            w.cv.notify()

    def _release(self, service_s: float) -> None:
        with self._mu:
            self._active -= 1
            self._ewma_ms = (
                _EWMA_ALPHA * service_s * 1000.0
                + (1.0 - _EWMA_ALPHA) * self._ewma_ms
            )
            self._schedule_locked()

    # -- introspection -------------------------------------------------

    def snapshot(self) -> dict:
        with self._mu:
            out = {
                "concurrency": self.concurrency,
                "queueDepth": self.queue_depth,
                "active": self._active,
                "queued": self._queued,
                "ewmaServiceMs": round(self._ewma_ms, 3),
                "admitted": self.admitted,
                "shed": self.shed,
            }
            if self._waiting:
                out["queuedByTenant"] = {
                    t: len(dq) for t, dq in sorted(self._waiting.items())
                }
            return out


class AdmissionController:
    """Per-class gates behind one handle.  The Handler acquires a
    ticket per request (query routes classify from the parsed plan;
    import routes are ``write``; remote legs are ``internal``) and
    releases it when the response is computed.  With a
    :class:`TenantRegistry` attached, acquisition also debits the
    tenant's quota (client classes only) and queues through the
    weighted-fair scheduler."""

    def __init__(
        self,
        point_concurrency: int = 32,
        heavy_concurrency: int = 8,
        write_concurrency: int = 16,
        internal_concurrency: int = 128,
        subscribe_concurrency: int = 4,
        queue_depth: int = 64,
        stats=None,
        tenants: "TenantRegistry | None" = None,
    ):
        self.tenants = tenants
        weight_of = tenants.weight if tenants is not None else None
        self._gates = {
            CLASS_POINT: _ClassGate(
                CLASS_POINT, point_concurrency, queue_depth, stats, weight_of
            ),
            CLASS_HEAVY: _ClassGate(
                CLASS_HEAVY, heavy_concurrency, queue_depth, stats, weight_of
            ),
            CLASS_WRITE: _ClassGate(
                CLASS_WRITE, write_concurrency, queue_depth, stats, weight_of
            ),
            # The internal lane's queue is as wide as its gate: a map
            # leg briefly over the limit should wait (its coordinator
            # holds budget), but a pile-up twice the gate deep means
            # the node is genuinely saturated and must shed so the
            # coordinator can fail over.  No WFQ here — legs are
            # charged to their origin tenant but never queued behind a
            # tenant boundary.
            CLASS_INTERNAL: _ClassGate(
                CLASS_INTERNAL,
                internal_concurrency,
                max(1, int(internal_concurrency)),
                stats,
            ),
            # The subscribe lane gates standing-query work — the
            # registration snapshot and the notifier's batch
            # evaluation.  Narrow by design: push freshness degrades
            # under load, pull latency doesn't.
            CLASS_SUBSCRIBE: _ClassGate(
                CLASS_SUBSCRIBE, subscribe_concurrency, queue_depth, stats
            ),
        }

    def gate(self, cls: str) -> _ClassGate:
        return self._gates[cls]

    def acquire(
        self,
        cls: str,
        deadline: "rz.Deadline | None" = None,
        tenant: str = "",
        nbytes: int = 0,
    ) -> Ticket:
        """Admit a request of class ``cls`` or raise
        :class:`resilience.ShedError`.  ``deadline`` defaults to the
        contextvar-current one (the handler's deadline scope).
        ``tenant`` defaults to the registry's default tenant;
        ``nbytes`` is the request's ingress size for the bytes/s
        quota."""
        if deadline is None:
            deadline = rz.current_deadline()
        reg = self.tenants
        t = tenant or (
            reg.default_tenant if reg is not None else DEFAULT_TENANT
        )
        if reg is not None and cls != CLASS_INTERNAL:
            reg.check_quota(t, cls, nbytes)
        try:
            ticket = self._gates[cls].acquire(deadline, tenant=t)
        except QuotaError:
            raise
        except rz.ShedError as e:
            e.tenant = t
            if reg is not None:
                reg.note_shed(t, cls)
            raise
        if reg is not None:
            reg.note_admitted(t, cls, ticket.wait_ms)
        return ticket

    def snapshot(self) -> dict:
        return {name: g.snapshot() for name, g in self._gates.items()}

    def tenants_snapshot(self) -> dict:
        """The ``GET /debug/tenants`` body."""
        if self.tenants is None:
            return {}
        return self.tenants.snapshot()

    def gauges(self) -> dict[str, float]:
        """Scrape-time gauges for /metrics (net.admission.* per class,
        plus per-tenant queue depth and quota headroom)."""
        out: dict[str, float] = {}
        for name, g in self._gates.items():
            snap = g.snapshot()
            out[f"net.admission.active[class:{name}]"] = snap["active"]
            out[f"net.admission.queued[class:{name}]"] = snap["queued"]
            out[f"net.admission.concurrency[class:{name}]"] = snap[
                "concurrency"
            ]
            out[f"net.admission.ewmaServiceMs[class:{name}]"] = snap[
                "ewmaServiceMs"
            ]
            for tname, depth in snap.get("queuedByTenant", {}).items():
                out[
                    f"net.admission.tenantQueued[class:{name},tenant:{tname}]"
                ] = depth
        if self.tenants is not None:
            out.update(self.tenants.gauges())
        return out
