"""Server-side admission control: cost classes, bounded queues,
deadline-aware load shedding.

The HTTP adapter (``ThreadingHTTPServer``) admits every connection
unconditionally, so under overload a node queues work it can never
finish inside its deadline and answers 504 *after* burning device time
— the classic overload failure mode (see Facebook's "Fail at Scale"
adaptive-LIFO/CoDel design).  This layer sits in FRONT of the
executor/coalescer and decides, per request, in microseconds:

* **Cost classes.**  Every query is classified from its parsed plan
  (``exec.plan.cost_class``): ``point`` (Count/Bitmap algebra),
  ``heavy`` (TopN / Sum / Min / Max / Range), ``write`` (PQL writes and
  bulk imports) — plus ``internal`` for the remote legs of another
  node's map/reduce (``QueryRequest.Remote``) and anti-entropy repair.
  Each class gets its own concurrency gate and bounded queue, so a
  storm of TopNs cannot starve point lookups and vice versa.

* **Deadline-aware shedding.**  A request that cannot be served within
  its remaining ``X-Deadline-Ms`` budget — the queue is full, or the
  predicted queue wait (queue position x EWMA service time / gate
  width) exceeds the budget — is answered ``429 + Retry-After``
  immediately, BEFORE any coalescer/device work.  The Retry-After hint
  is the predicted drain time of the queue ahead.

* **Internal priority.**  The ``internal`` lane is a separate gate:
  client traffic can never occupy its slots, so a saturated cluster
  cannot distributed-livelock (every node's client gates full, every
  node's map legs starving behind them).  The lane is still *bounded* —
  a truly saturated node sheds internal legs too, which the
  coordinator's failover treats as a node failure (try a replica, or
  degrade under ``allowPartial``) rather than a breaker trip.

Observability: ``net.admission.admitted|shed|queueTimeout`` counters
(``class:`` tag), ``net.admission.queueWaitMs`` histogram, scrape-time
``net.admission.active|queueDepth|ewmaServiceMs`` gauges on /metrics,
the per-class queue state on ``GET /debug/health``, and an
``admission`` span in every query trace.
"""

from __future__ import annotations

import threading
import time

from pilosa_tpu.net import resilience as rz

# Class names (the first three mirror exec.plan.COST_*; admission owns
# the internal lane, which is a transport property, not a plan one).
CLASS_POINT = "point"
CLASS_HEAVY = "heavy"
CLASS_WRITE = "write"
CLASS_INTERNAL = "internal"
# Standing-query notification batches (pilosa_tpu/subscribe): a
# dedicated bounded lane so push evaluation can never occupy a query
# slot — subscribers starve before queries do, by construction.
CLASS_SUBSCRIBE = "subscribe"

CLASSES = (
    CLASS_POINT,
    CLASS_HEAVY,
    CLASS_WRITE,
    CLASS_INTERNAL,
    CLASS_SUBSCRIBE,
)

# EWMA smoothing for observed service times: new = a*obs + (1-a)*old.
_EWMA_ALPHA = 0.2
# Service-time estimate before the first observation (ms).  Deliberately
# modest: the first storm against a cold gate should shed on queue
# depth, not on a wild wait prediction.
_EWMA_INIT_MS = 25.0
# Retry-After hints are clamped to this window.
_MIN_RETRY_AFTER_S = 0.05
_MAX_RETRY_AFTER_S = 30.0


class Ticket:
    """One admitted request's slot in a class gate.  ``release()``
    returns the slot and feeds the observed service time back into the
    gate's EWMA (which drives the NEXT request's wait prediction)."""

    __slots__ = ("_gate", "wait_ms", "_t_admit", "_released")

    def __init__(self, gate: "_ClassGate", wait_ms: float):
        self._gate = gate
        self.wait_ms = wait_ms
        self._t_admit = time.monotonic()
        self._released = False

    def release(self) -> None:
        if self._released:  # idempotent — finally blocks may race close
            return
        self._released = True
        self._gate._release(time.monotonic() - self._t_admit)


class _ClassGate:
    """Concurrency gate + bounded FIFO-ish queue for one cost class."""

    def __init__(
        self,
        name: str,
        concurrency: int,
        queue_depth: int,
        stats,
    ):
        from pilosa_tpu.obs.stats import NopStatsClient

        self.name = name
        self.concurrency = max(1, int(concurrency))
        self.queue_depth = max(0, int(queue_depth))
        self.stats = stats or NopStatsClient()
        self._cv = threading.Condition()
        self._active = 0
        self._queued = 0
        self._ewma_ms = _EWMA_INIT_MS
        # Lifetime counters for snapshot() — kept locally so
        # /debug/health reports them even without a stats backend.
        self.admitted = 0
        self.shed = 0

    # -- prediction ----------------------------------------------------

    def _predicted_wait_ms(self, ahead: int) -> float:
        """Expected queue wait for a request with ``ahead`` requests in
        front of it: the gate drains ``concurrency`` requests per EWMA
        service time."""
        return ahead * self._ewma_ms / self.concurrency

    def _retry_after_s(self, predicted_ms: float) -> float:
        return min(
            max(predicted_ms / 1000.0, _MIN_RETRY_AFTER_S),
            _MAX_RETRY_AFTER_S,
        )

    def _shed_locked(self, predicted_ms: float, reason: str) -> "rz.ShedError":
        self.shed += 1
        return rz.ShedError(
            f"admission: {self.name} {reason} "
            f"(active={self._active}/{self.concurrency} "
            f"queued={self._queued}/{self.queue_depth} "
            f"predicted_wait_ms={predicted_ms:.0f})",
            retry_after_s=self._retry_after_s(predicted_ms),
            cost_class=self.name,
        )

    # -- admission -----------------------------------------------------

    def acquire(self, deadline: "rz.Deadline | None") -> Ticket:
        """Admit (possibly after a bounded, deadline-clamped queue wait)
        or raise :class:`ShedError` without blocking on anything but
        this gate's own lock.  Stats emit OUTSIDE the critical section
        — this lock sits on every request's path (same treatment the
        PlanePool got in PR 8)."""
        t0 = time.monotonic()
        try:
            wait_ms = self._acquire_locked(deadline, t0)
        except rz.ShedError:
            self.stats.count_with_custom_tags(
                "net.admission.shed", 1, [f"class:{self.name}"]
            )
            raise
        self.stats.count_with_custom_tags(
            "net.admission.admitted", 1, [f"class:{self.name}"]
        )
        if wait_ms > 0:
            self.stats.histogram("net.admission.queueWaitMs", wait_ms)
        return Ticket(self, wait_ms)

    def _acquire_locked(
        self, deadline: "rz.Deadline | None", t0: float
    ) -> float:
        """The lock-held admission decision; returns the queue wait in
        ms or raises :class:`ShedError`."""
        with self._cv:
            if self._active < self.concurrency and self._queued == 0:
                self._active += 1
                self.admitted += 1
                return 0.0
            ahead = self._queued
            predicted_ms = self._predicted_wait_ms(ahead + 1)
            if self._queued >= self.queue_depth:
                raise self._shed_locked(predicted_ms, "queue full")
            if (
                deadline is not None
                and deadline.remaining_ms() < predicted_ms + self._ewma_ms
            ):
                # Queuing would only produce a 504 after the fact —
                # answer 429 now, before any work happens.
                raise self._shed_locked(
                    predicted_ms, "predicted wait exceeds deadline"
                )
            self._queued += 1
            try:
                while self._active >= self.concurrency:
                    timeout = None
                    if deadline is not None:
                        timeout = deadline.remaining()
                        if timeout <= 0:
                            raise self._shed_locked(
                                self._predicted_wait_ms(self._queued),
                                "deadline expired in queue",
                            )
                    self._cv.wait(timeout)
            finally:
                self._queued -= 1
            self._active += 1
            self.admitted += 1
            return (time.monotonic() - t0) * 1000.0

    def _release(self, service_s: float) -> None:
        with self._cv:
            self._active -= 1
            self._ewma_ms = (
                _EWMA_ALPHA * service_s * 1000.0
                + (1.0 - _EWMA_ALPHA) * self._ewma_ms
            )
            self._cv.notify()

    # -- introspection -------------------------------------------------

    def snapshot(self) -> dict:
        with self._cv:
            return {
                "concurrency": self.concurrency,
                "queueDepth": self.queue_depth,
                "active": self._active,
                "queued": self._queued,
                "ewmaServiceMs": round(self._ewma_ms, 3),
                "admitted": self.admitted,
                "shed": self.shed,
            }


class AdmissionController:
    """Per-class gates behind one handle.  The Handler acquires a
    ticket per request (query routes classify from the parsed plan;
    import routes are ``write``; remote legs are ``internal``) and
    releases it when the response is computed."""

    def __init__(
        self,
        point_concurrency: int = 32,
        heavy_concurrency: int = 8,
        write_concurrency: int = 16,
        internal_concurrency: int = 128,
        subscribe_concurrency: int = 4,
        queue_depth: int = 64,
        stats=None,
    ):
        self._gates = {
            CLASS_POINT: _ClassGate(
                CLASS_POINT, point_concurrency, queue_depth, stats
            ),
            CLASS_HEAVY: _ClassGate(
                CLASS_HEAVY, heavy_concurrency, queue_depth, stats
            ),
            CLASS_WRITE: _ClassGate(
                CLASS_WRITE, write_concurrency, queue_depth, stats
            ),
            # The internal lane's queue is as wide as its gate: a map
            # leg briefly over the limit should wait (its coordinator
            # holds budget), but a pile-up twice the gate deep means
            # the node is genuinely saturated and must shed so the
            # coordinator can fail over.
            CLASS_INTERNAL: _ClassGate(
                CLASS_INTERNAL,
                internal_concurrency,
                max(1, int(internal_concurrency)),
                stats,
            ),
            # The subscribe lane gates standing-query work — the
            # registration snapshot and the notifier's batch
            # evaluation.  Narrow by design: push freshness degrades
            # under load, pull latency doesn't.
            CLASS_SUBSCRIBE: _ClassGate(
                CLASS_SUBSCRIBE, subscribe_concurrency, queue_depth, stats
            ),
        }

    def gate(self, cls: str) -> _ClassGate:
        return self._gates[cls]

    def acquire(
        self, cls: str, deadline: "rz.Deadline | None" = None
    ) -> Ticket:
        """Admit a request of class ``cls`` or raise
        :class:`resilience.ShedError`.  ``deadline`` defaults to the
        contextvar-current one (the handler's deadline scope)."""
        if deadline is None:
            deadline = rz.current_deadline()
        return self._gates[cls].acquire(deadline)

    def snapshot(self) -> dict:
        return {name: g.snapshot() for name, g in self._gates.items()}

    def gauges(self) -> dict[str, float]:
        """Scrape-time gauges for /metrics (net.admission.* per class)."""
        out: dict[str, float] = {}
        for name, g in self._gates.items():
            snap = g.snapshot()
            out[f"net.admission.active[class:{name}]"] = snap["active"]
            out[f"net.admission.queued[class:{name}]"] = snap["queued"]
            out[f"net.admission.concurrency[class:{name}]"] = snap[
                "concurrency"
            ]
            out[f"net.admission.ewmaServiceMs[class:{name}]"] = snap[
                "ewmaServiceMs"
            ]
        return out
