"""Wire codecs — internal types <-> protobuf / JSON.

Converts between the framework's result types (RowBitmap, Pair, attrs)
and the HTTP API's two content types, reproducing the reference's
polymorphic QueryResult encoding (reference: handler.go:1380-1470,
bitmap.go:220-268, attr.go:256-303).
"""

from __future__ import annotations

from typing import Any

import numpy as np

from pilosa_tpu.bsi import ValCount
from pilosa_tpu.core.bitmap import RowBitmap
from pilosa_tpu.core.cache import Pair
from pilosa_tpu.net import wire_pb2 as wire
from pilosa_tpu.ops import bitplane as bp

# Attr value type tags (reference: attr.go:34-40)
ATTR_TYPE_STRING = 1
ATTR_TYPE_INT = 2
ATTR_TYPE_BOOL = 3
ATTR_TYPE_FLOAT = 4

_U64_MASK = (1 << 64) - 1


def _u64(v: int) -> int:
    return v & _U64_MASK


# ---------------------------------------------------------------------------
# attrs
# ---------------------------------------------------------------------------


def attrs_to_proto(attrs: dict[str, Any]) -> list[wire.Attr]:
    """Sorted-by-key Attr list (reference: attr.go:256-276)."""
    out = []
    for k in sorted(attrs):
        v = attrs[k]
        a = wire.Attr(Key=k)
        # bool must be tested before int (bool subclasses int in Python).
        if isinstance(v, bool):
            a.Type = ATTR_TYPE_BOOL
            a.BoolValue = v
        elif isinstance(v, str):
            a.Type = ATTR_TYPE_STRING
            a.StringValue = v
        elif isinstance(v, int):
            a.Type = ATTR_TYPE_INT
            a.IntValue = v
        elif isinstance(v, float):
            a.Type = ATTR_TYPE_FLOAT
            a.FloatValue = v
        else:
            raise TypeError(f"unrecognized attribute type: {type(v).__name__}")
        out.append(a)
    return out


def attrs_from_proto(pb_attrs) -> dict[str, Any]:
    """reference: attr.go:279-303"""
    out: dict[str, Any] = {}
    for a in pb_attrs:
        if a.Type == ATTR_TYPE_STRING:
            out[a.Key] = a.StringValue
        elif a.Type == ATTR_TYPE_INT:
            out[a.Key] = a.IntValue
        elif a.Type == ATTR_TYPE_BOOL:
            out[a.Key] = a.BoolValue
        elif a.Type == ATTR_TYPE_FLOAT:
            out[a.Key] = a.FloatValue
    return out


# ---------------------------------------------------------------------------
# RowBitmap
# ---------------------------------------------------------------------------


def bitmap_to_proto(b: RowBitmap) -> wire.Bitmap:
    """Flat absolute-column bit list (reference: bitmap.go:245-255)."""
    pb = wire.Bitmap()
    for s in sorted(b.segments):
        offs = bp.np_row_to_columns(np.asarray(b.segments[s]))
        base = s * bp.SLICE_WIDTH
        pb.Bits.extend(int(o) + base for o in offs)
    if b.attrs:
        pb.Attrs.extend(attrs_to_proto(b.attrs))
    return pb


def bitmap_from_proto(pb: wire.Bitmap) -> RowBitmap:
    """reference: bitmap.go:258-268"""
    b = RowBitmap.from_bits(pb.Bits)
    b.attrs = attrs_from_proto(pb.Attrs)
    return b


def bitmap_to_json(b: RowBitmap) -> dict:
    """JSON shape {"attrs": {...}, "bits": [...]} (reference:
    bitmap.go:220-233)."""
    bits: list[int] = []
    for s in sorted(b.segments):
        offs = bp.np_row_to_columns(np.asarray(b.segments[s]))
        base = s * bp.SLICE_WIDTH
        bits.extend(int(o) + base for o in offs)
    return {"attrs": b.attrs or {}, "bits": bits}


# ---------------------------------------------------------------------------
# QueryResult / QueryResponse
# ---------------------------------------------------------------------------


def result_to_proto(result: Any) -> wire.QueryResult:
    """Polymorphic result encode (reference: handler.go:1444-1470).

    RowBitmap -> Bitmap; [Pair] -> Pairs; int -> N; bool -> Changed;
    None -> empty result.
    """
    pb = wire.QueryResult()
    if isinstance(result, RowBitmap):
        pb.Bitmap.CopyFrom(bitmap_to_proto(result))
    elif isinstance(result, ValCount):
        # BSI aggregate (Sum/Min/Max): rides the Pairs message — value
        # u64-wrapped in Key (negatives sign-extend on decode), count
        # in Count.  The coordinator's reduce interprets it; external
        # protobuf clients see one Pair.
        pb.Pairs.append(
            wire.Pair(Key=_u64(result.value), Count=_u64(result.count))
        )
    elif isinstance(result, bool):
        pb.Changed = result
    elif isinstance(result, (int, np.integer)):
        pb.N = _u64(int(result))
    elif isinstance(result, list):
        for p in result:
            pb.Pairs.append(wire.Pair(Key=_u64(p.id), Count=_u64(p.count)))
    elif result is not None:
        raise TypeError(f"unknown query result type: {type(result).__name__}")
    return pb


def result_from_proto(pb: wire.QueryResult) -> Any:
    """Inverse of result_to_proto (reference: client.go:283-301)."""
    if pb.HasField("Bitmap"):
        return bitmap_from_proto(pb.Bitmap)
    if pb.Pairs:
        return [Pair(id=p.Key, count=p.Count) for p in pb.Pairs]
    if pb.Changed:
        return True
    if pb.N:
        return int(pb.N)
    # Ambiguity of the reference's sparse encoding: an absent field set
    # means 0 / False / nil; prefer 0 (counts dominate reads).
    return 0 if not pb.HasField("Bitmap") else None


def result_to_json(result: Any) -> Any:
    if isinstance(result, RowBitmap):
        return bitmap_to_json(result)
    if isinstance(result, ValCount):
        return {"value": int(result.value), "count": int(result.count)}
    if isinstance(result, list):
        return [{"id": _u64(p.id), "count": _u64(p.count)} for p in result]
    if isinstance(result, (int, np.integer)) and not isinstance(result, bool):
        return int(result)
    return result


def response_to_proto(
    results: list[Any],
    column_attr_sets: list[tuple[int, dict[str, Any]]] | None = None,
    err: str = "",
) -> wire.QueryResponse:
    pb = wire.QueryResponse(Err=err)
    for r in results or []:
        pb.Results.append(result_to_proto(r))
    for id_, attrs in column_attr_sets or []:
        pb.ColumnAttrSets.append(
            wire.ColumnAttrSet(ID=_u64(id_), Attrs=attrs_to_proto(attrs))
        )
    return pb


def response_to_json(
    results: list[Any],
    column_attr_sets: list[tuple[int, dict[str, Any]]] | None = None,
) -> dict:
    """reference: handler.go:216-280 JSON shape."""
    out: dict[str, Any] = {"results": [result_to_json(r) for r in results or []]}
    if column_attr_sets is not None:
        out["columnAttrs"] = [
            {"id": _u64(id_), "attrs": attrs} for id_, attrs in column_attr_sets
        ]
    return out
