"""Orchestration: build the index, run the passes, apply the allowlist."""

from __future__ import annotations

import time

from pilosa_tpu.analyze.compilehaz import CompilePass
from pilosa_tpu.analyze.config import AnalyzeConfig, load_config
from pilosa_tpu.analyze.index import build_index
from pilosa_tpu.analyze.locks import LockPass
from pilosa_tpu.analyze.report import Report
from pilosa_tpu.analyze.resources import ResourcePass

PASSES = ("locks", "compile", "resources")


def run_analysis(
    config: AnalyzeConfig | None = None,
    passes=PASSES,
    index=None,
):
    """Run the selected passes; returns ``(Report, LockGraph | None)``."""
    t0 = time.monotonic()
    cfg = config or load_config()
    idx = index or build_index(cfg)
    findings = []
    graph = None
    if "locks" in passes:
        lock_findings, graph = LockPass(idx).run()
        findings.extend(lock_findings)
    if "compile" in passes:
        findings.extend(CompilePass(idx).run())
    if "resources" in passes:
        findings.extend(ResourcePass(idx).run())

    # stable order + allowlist
    findings.sort(key=lambda f: (f.rule, f.path, f.line, f.key))
    for f in findings:
        entry = cfg.allowed(f)
        if entry is not None:
            f.allowed_by = entry.reason or entry.match
    rep = Report(findings=findings)
    rep.stale_allow = [
        f"[{e.rule}] {e.match}" for e in cfg.stale_allow_entries()
    ]
    rep.stats = idx.stats()
    if graph is not None:
        rep.stats["edges"] = len(graph.edges)
        rep.stats["nonblocking_edges"] = sum(
            1 for e in graph.edges.values() if e.nonblocking
        )
    rep.elapsed_s = time.monotonic() - t0
    return rep, graph


def static_lock_graph(config: AnalyzeConfig | None = None):
    """Just the lock graph — the runtime validator's reference."""
    _, graph = run_analysis(config=config, passes=("locks",))
    return graph
