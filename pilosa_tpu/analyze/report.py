"""Findings and the analyzer report (text + JSON artifact)."""

from __future__ import annotations

import json
from dataclasses import dataclass, field


@dataclass
class Finding:
    """One analyzer hit.

    ``key`` is the STABLE fingerprint allowlist entries match against —
    built from qualified names and rule details, never line numbers, so
    an unrelated edit above a documented site cannot un-document it.
    """

    rule: str
    path: str
    line: int
    message: str
    key: str
    severity: str = "error"  # "error" | "warn"
    allowed_by: str = ""  # reason text of the matching allowlist entry

    def location(self) -> str:
        return f"{self.path}:{self.line}"

    def to_dict(self) -> dict:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "severity": self.severity,
            "message": self.message,
            "key": self.key,
            "allowed": bool(self.allowed_by),
            "allowed_by": self.allowed_by,
        }


@dataclass
class Report:
    findings: list[Finding] = field(default_factory=list)
    stale_allow: list[str] = field(default_factory=list)
    stats: dict = field(default_factory=dict)
    elapsed_s: float = 0.0

    @property
    def active(self) -> list[Finding]:
        """Findings NOT covered by the allowlist — these fail the gate."""
        return [f for f in self.findings if not f.allowed_by]

    @property
    def allowed(self) -> list[Finding]:
        return [f for f in self.findings if f.allowed_by]

    def exit_code(self) -> int:
        return 1 if self.active else 0

    def to_dict(self) -> dict:
        return {
            "findings": [f.to_dict() for f in self.findings],
            "active": len(self.active),
            "allowed": len(self.allowed),
            "stale_allow": self.stale_allow,
            "stats": self.stats,
            "elapsed_s": round(self.elapsed_s, 3),
        }

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), indent=2, sort_keys=True)

    def render_text(self) -> str:
        out: list[str] = []
        by_rule: dict[str, list[Finding]] = {}
        for f in self.active:
            by_rule.setdefault(f.rule, []).append(f)
        for rule in sorted(by_rule):
            out.append(f"[{rule}]")
            for f in sorted(by_rule[rule], key=lambda f: (f.path, f.line)):
                out.append(f"  {f.location()}: {f.message}")
                out.append(f"    key: {f.key}")
        if self.allowed:
            out.append(f"-- {len(self.allowed)} finding(s) documented in analyze.toml:")
            for f in sorted(self.allowed, key=lambda f: (f.rule, f.path, f.line)):
                reason = f.allowed_by.split(". ")[0].split(": ")[0]
                out.append(
                    f"  [{f.rule}] {f.location()}: allowed — {reason}"
                )
        for stale in self.stale_allow:
            out.append(f"-- STALE allowlist entry (matched nothing): {stale}")
        s = self.stats
        out.append(
            f"analyze: {s.get('files', 0)} files, {s.get('locks', 0)} locks, "
            f"{s.get('edges', 0)} lock-order edges "
            f"({s.get('nonblocking_edges', 0)} non-blocking); "
            f"{len(self.active)} active finding(s), {len(self.allowed)} "
            f"allowed; {self.elapsed_s:.2f}s"
        )
        return "\n".join(out)
