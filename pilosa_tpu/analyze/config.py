"""analyze.toml — analyzer configuration and the documented allowlist.

The file lives at the repository root next to ``pyproject.toml``.  Its
``[[allow]]`` entries are the ONLY way to ship with a finding: each
names a rule, an ``fnmatch`` pattern over the finding's stable key, and
a mandatory human reason — the known-safe sites are documented, never
silenced.  Entries that stop matching anything are reported as stale so
the file cannot rot.

Everything else in the file tunes resolution rather than suppressing
output: interface groups (duck-typed receivers like ``.stats`` /
``.tracer``), factory return types (``device.pool() -> PlanePool``),
and declared dynamic call edges for callbacks the AST cannot follow
(the residency pool's eviction hooks, a span's deferred ``__exit__``).
"""

from __future__ import annotations

import fnmatch
import os
from dataclasses import dataclass, field

try:  # Python 3.11+
    import tomllib as _toml
except ModuleNotFoundError:  # pragma: no cover - 3.10 container fallback
    import tomli as _toml  # type: ignore[no-redef]


@dataclass
class AllowEntry:
    rule: str
    match: str
    reason: str
    hits: int = 0  # findings matched during this run (0 after = stale)

    def matches(self, finding) -> bool:
        if self.rule not in ("*", finding.rule):
            return False
        return fnmatch.fnmatchcase(finding.key, self.match)


@dataclass
class InterfaceGroup:
    """Duck-typed receiver resolution: a call ``x.m(...)`` on an
    unresolvable receiver resolves to every ``classes`` member defining
    ``m`` when ``m`` is one of the group's method names."""

    name: str
    classes: list[str]
    methods: list[str]


@dataclass
class CallEdge:
    """A declared dynamic call edge the AST cannot see (stored
    callbacks, context-manager exits)."""

    src: str
    dst: str
    reason: str = ""


@dataclass
class AnalyzeConfig:
    package: str = "pilosa_tpu"
    exclude: list[str] = field(default_factory=list)
    allow: list[AllowEntry] = field(default_factory=list)
    groups: list[InterfaceGroup] = field(default_factory=list)
    call_edges: list[CallEdge] = field(default_factory=list)
    # function qualname -> class qualname it returns an instance of
    returns: dict[str, str] = field(default_factory=dict)
    # function qualname -> LOCK id it returns (``with mod.fn():``
    # acquires that lock — e.g. plan.collective_launch, whose returned
    # module mutex the AST cannot otherwise attribute)
    lock_returns: dict[str, str] = field(default_factory=dict)
    # attribute name -> class qualnames (fallback when inference fails)
    attr_types: dict[str, list[str]] = field(default_factory=dict)
    blocking_calls: list[str] = field(default_factory=list)
    hot_modules: list[str] = field(default_factory=list)
    compile_entry_points: list[str] = field(default_factory=list)
    bucket_fns: list[str] = field(default_factory=list)
    scoped_resources: dict[str, str] = field(default_factory=dict)
    path: str = ""

    def allowed(self, finding) -> AllowEntry | None:
        for entry in self.allow:
            if entry.matches(finding):
                entry.hits += 1
                return entry
        return None

    def stale_allow_entries(self) -> list[AllowEntry]:
        return [e for e in self.allow if e.hits == 0]


def repo_root() -> str:
    """The directory holding ``analyze.toml`` / ``pyproject.toml`` —
    the parent of the installed package directory when running from a
    source checkout, else the current working directory."""
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    root = os.path.dirname(here)
    if os.path.exists(os.path.join(root, "analyze.toml")):
        return root
    return os.getcwd()


def load_config(path: str | None = None) -> AnalyzeConfig:
    """Load ``analyze.toml``; a missing file yields the built-in
    defaults (empty allowlist)."""
    if path is None:
        path = os.path.join(repo_root(), "analyze.toml")
    cfg = AnalyzeConfig(path=path)
    if not os.path.exists(path):
        return cfg
    with open(path, "rb") as fh:
        data = _toml.load(fh)

    top = data.get("analyze", {})
    cfg.package = top.get("package", cfg.package)
    cfg.exclude = list(top.get("exclude", []))

    locks = data.get("locks", {})
    cfg.blocking_calls = list(locks.get("blocking-calls", []))
    for g in locks.get("group", []):
        cfg.groups.append(
            InterfaceGroup(
                name=g.get("name", ""),
                classes=list(g.get("classes", [])),
                methods=list(g.get("methods", [])),
            )
        )
    for c in locks.get("call", []):
        cfg.call_edges.append(
            CallEdge(
                src=c.get("from", ""),
                dst=c.get("to", ""),
                reason=c.get("reason", ""),
            )
        )
    cfg.returns = dict(locks.get("returns", {}))
    cfg.lock_returns = dict(locks.get("lock-returns", {}))
    cfg.attr_types = {
        k: list(v) for k, v in locks.get("attr-types", {}).items()
    }

    comp = data.get("compile", {})
    cfg.hot_modules = list(comp.get("hot-modules", []))
    cfg.compile_entry_points = list(comp.get("entry-points", []))
    cfg.bucket_fns = list(comp.get("bucket-fns", []))

    res = data.get("resources", {})
    cfg.scoped_resources = dict(res.get("scoped", {}))

    for a in data.get("allow", []):
        cfg.allow.append(
            AllowEntry(
                rule=a.get("rule", "*"),
                match=a.get("match", ""),
                reason=a.get("reason", ""),
            )
        )
    return cfg
