"""pilosa_tpu.analyze — the concurrency & compile-hazard analyzer.

The reference Pilosa leans on Go's toolchain for correctness: ``go
vet`` plus the ``-race`` detector guard a 29-lock, many-goroutine core.
This package is the Python/JAX rebuild's equivalent, purpose-built for
THIS codebase's three recurring bug classes instead of generic style:

* **lock-order** (:mod:`.locks`): discovers every
  ``threading.Lock/RLock/Condition`` the package creates, builds the
  interprocedural acquisition graph (``with`` nesting, ``acquire()``
  calls, and calls made while a lock is held), reports cycles as
  potential deadlocks, and flags blocking calls (socket I/O,
  ``Future.result``, ``queue.get``, device transfers, ``time.sleep``)
  made under a lock.
* **compile-hazard** (:mod:`.compilehaz`): JAX-layer lints — dynamic
  shapes reaching a jit entry point without the canonical pow2
  bucketing (``bp.pow2_bucket`` / ``plan.slice_bucket``), f-string /
  stringified values in compile keys, host<->device sync inside hot
  loops, and ``functools.lru_cache`` on methods (leaks ``self``).
* **resource-discipline** (:mod:`.resources`): pin leases, trace
  spans, ChunkPipes, and deadline scopes acquired without a
  guaranteeing ``with``/``finally``.

Run as ``python -m pilosa_tpu.analyze`` (wired into ``make check`` and
CI as a blocking gate).  Known-safe sites are DOCUMENTED, not silenced,
in ``analyze.toml`` — every allowlist entry carries a reason and goes
stale-visible when the code it matched disappears.

The static lock graph is additionally proven against reality: with
``PILOSA_LOCK_CHECK=1`` (:mod:`.runtime`) every lock the package
creates is wrapped so acquisition order observed while the tier-1 and
chaos suites run is checked for consistency with the static graph.
"""

from __future__ import annotations

from pilosa_tpu.analyze.config import AnalyzeConfig, load_config, repo_root
from pilosa_tpu.analyze.report import Finding, Report
from pilosa_tpu.analyze.run import run_analysis

__all__ = [
    "AnalyzeConfig",
    "Finding",
    "Report",
    "load_config",
    "repo_root",
    "run_analysis",
]
