"""PILOSA_LOCK_CHECK=1 — runtime validation of the static lock graph.

The static analyzer is only trustworthy if reality agrees with it, so
this module wraps every ``threading.Lock/RLock/Condition`` the PACKAGE
creates (foreign creations — stdlib, jax — pass through untouched) and
records, per acquisition, the ordered pairs (held-lock -> new-lock)
observed across all threads.  A lock's runtime identity is its
CREATION SITE (file, line), which is exactly how the static pass
registers it — so :func:`verify` can check that every observed
acquisition order is present in the static graph's transitive closure.
A disagreement means the analyzer missed an interaction (fix the
resolution or declare the callback edge in analyze.toml) — the suites
running green under this mode is what makes the static report
evidence, not opinion.

Install happens from ``pilosa_tpu/__init__`` BEFORE any submodule
import, so module-level locks are wrapped too.  Overhead per
acquisition is one thread-local list append plus, for never-seen
pairs, one set insert — measured noise on the tier-1 suite.
"""

from __future__ import annotations

import os
import sys
import threading

ENV = "PILOSA_LOCK_CHECK"

_real_lock = threading.Lock
_real_rlock = threading.RLock
_real_condition = threading.Condition

_installed = False
_pkg_dir: str | None = None

# (src_site, dst_site, nonblocking) -> count; guarded by _edges_mu.
# Sites are (relpath, line).
_edges: dict = {}
_edges_mu = _real_lock()
# every wrapped-lock creation site seen at runtime
_created: set = set()

_tls = threading.local()


def enabled() -> bool:
    return bool(os.environ.get(ENV))


def _held() -> list:
    h = getattr(_tls, "held", None)
    if h is None:
        h = _tls.held = []
    return h


def _relpath(filename: str) -> str:
    assert _pkg_dir is not None
    rel = os.path.relpath(filename, os.path.dirname(_pkg_dir))
    return rel.replace(os.sep, "/")


def _note_acquire(site, nonblocking: bool) -> None:
    held = _held()
    if site not in held:
        new_edges = [
            (h, site, nonblocking) for h in dict.fromkeys(held) if h != site
        ]
        if new_edges:
            with _edges_mu:
                for e in new_edges:
                    _edges[e] = _edges.get(e, 0) + 1
    held.append(site)


def _note_release(site) -> None:
    held = _held()
    for i in range(len(held) - 1, -1, -1):
        if held[i] == site:
            del held[i]
            return


class _CheckedLock:
    """Order-recording wrapper around one Lock/RLock instance."""

    __slots__ = ("_inner", "site")

    def __init__(self, inner, site):
        self._inner = inner
        self.site = site

    def acquire(self, blocking=True, timeout=-1):
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            _note_acquire(self.site, not blocking)
        return ok

    def release(self):
        self._inner.release()
        _note_release(self.site)

    def locked(self):
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()

    def __repr__(self):
        return f"<checked {self._inner!r} @ {self.site[0]}:{self.site[1]}>"


class _CheckedRLock(_CheckedLock):
    """Adds the RLock protocol Condition relies on; the save/restore
    hooks keep the held-stack honest across ``Condition.wait``."""

    __slots__ = ()

    def _is_owned(self):
        return self._inner._is_owned()

    def _release_save(self):
        state = self._inner._release_save()
        # fully released: drop every occurrence of this site
        held = _held()
        n = held.count(self.site)
        for _ in range(n):
            _note_release(self.site)
        return (state, n)

    def _acquire_restore(self, saved):
        state, n = saved
        self._inner._acquire_restore(state)
        for _ in range(n):
            _note_acquire(self.site, False)

    def _at_fork_reinit(self):  # pragma: no cover - fork safety
        self._inner._at_fork_reinit()
        _tls.held = []


def _caller_site():
    """(relpath, line) of the package frame creating a lock; None when
    the creator is outside the package (leave those locks alone)."""
    f = sys._getframe(2)
    # normpath: imports via a relative sys.path entry (the tools/
    # scripts do ``sys.path.insert(0, ".")``) yield co_filenames like
    # ``/root/x/./pilosa_tpu/...`` that a raw prefix test rejects.
    filename = os.path.normpath(f.f_code.co_filename)
    if _pkg_dir is None or not filename.startswith(_pkg_dir + os.sep):
        return None
    return (_relpath(filename), f.f_lineno)


def _make_lock():
    site = _caller_site()
    inner = _real_lock()
    if site is None:
        return inner
    _created.add(site)
    return _CheckedLock(inner, site)


def _make_rlock():
    site = _caller_site()
    inner = _real_rlock()
    if site is None:
        return inner
    _created.add(site)
    return _CheckedRLock(inner, site)


def _make_condition(lock=None):
    site = _caller_site()
    if lock is None and site is not None:
        # Condition() creates its lock HERE: give it this site so the
        # static registry (which keys the Condition call) matches.
        _created.add(site)
        lock = _CheckedRLock(_real_rlock(), site)
    return _real_condition(lock)


def install() -> None:
    """Patch the threading lock factories (idempotent).  Must run
    before the package's submodules create their module-level locks."""
    global _installed, _pkg_dir
    if _installed:
        return
    _pkg_dir = os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))
    )
    threading.Lock = _make_lock
    threading.RLock = _make_rlock
    threading.Condition = _make_condition
    _installed = True


def observed_edges() -> dict:
    with _edges_mu:
        return dict(_edges)


def observed_sites() -> set:
    return set(_created)


def reset() -> None:
    """Drop observations (unit tests)."""
    with _edges_mu:
        _edges.clear()


def _match_site(site, lock_sites: dict) -> str | None:
    """Map a runtime creation site to a static lock id; tolerates a
    couple of lines of drift for multi-line factory calls."""
    lid = lock_sites.get(site)
    if lid is not None:
        return lid
    path, line = site
    best = None
    for (p, ln), cand in lock_sites.items():
        if p == path and abs(ln - line) <= 3:
            if best is not None:
                return None  # ambiguous
            best = cand
    return best


def verify(graph=None, config=None, edges=None, sites=None) -> list[str]:
    """Compare observations against the static graph; returns human-
    readable disagreements (empty = consistent).  ``edges``/``sites``
    override the live observations (unit tests)."""
    if graph is None:
        from pilosa_tpu.analyze.run import static_lock_graph

        graph = static_lock_graph(config)
    if edges is None:
        edges = observed_edges()
    if sites is None:
        sites = observed_sites()
    problems: list[str] = []
    site_to_id: dict = {}
    for site in sites:
        lid = _match_site(site, graph.lock_sites)
        if lid is None:
            problems.append(
                f"lock created at {site[0]}:{site[1]} was never "
                "discovered by the static pass"
            )
        else:
            site_to_id[site] = lid
    # transitive closure over static edges (order consistency, not
    # just direct adjacency: A->C observed while the code path goes
    # A->B->C is still the same order)
    for (src, dst, nb), count in sorted(edges.items()):
        a = site_to_id.get(src)
        b = site_to_id.get(dst)
        if a is None or b is None:
            continue  # unknown-site problem already reported
        if a == b:
            continue
        if not graph.has_path(a, b):
            problems.append(
                f"observed acquisition order {a} -> {b}"
                f"{' (non-blocking)' if nb else ''} x{count} "
                f"(locks at {src[0]}:{src[1]} -> {dst[0]}:{dst[1]}) "
                "has no path in the static lock graph — the analyzer "
                "missed an interaction; fix resolution or declare the "
                "call edge in analyze.toml"
            )
    return problems


def report() -> str:
    edges = observed_edges()
    lines = [
        f"lock-check: {len(observed_sites())} wrapped locks, "
        f"{len(edges)} distinct ordered pairs observed"
    ]
    for (src, dst, nb), count in sorted(edges.items()):
        lines.append(
            f"  {src[0]}:{src[1]} -> {dst[0]}:{dst[1]}"
            f"{' (non-blocking)' if nb else ''} x{count}"
        )
    return "\n".join(lines)
