"""Pass 3 — resource discipline: scoped objects need a guaranteed exit.

Pin leases (``pool.pinned(...)``), trace spans (``tracer.span(...)``),
deadline scopes, and ChunkPipes hold something real — a pinned HBM
entry, an open trace, a contextvar token, a bounded buffer a reader
blocks on.  Each must be used as a ``with`` (or have its ``close`` /
``release`` guaranteed by a ``finally``), or escape to an owner that
does.  An acquisition whose cleanup rides the happy path leaks exactly
when a query fails — the moment the lease mattered.

Detection per call to a configured factory:
  * ``with F(...)``                         -> ok
  * ``return F(...)`` / ``yield F(...)``    -> ok (escapes to caller)
  * ``self.x = F(...)`` / container store   -> ok (owner manages it)
  * ``x = F(...)`` later used as ``with x`` -> ok
  * ``x = F(...)`` with ``x.close()/release()/unpin()/finish()`` inside
    some ``finally``                        -> ok
  * ``x = F(...)`` passed to another call   -> ok (escapes)
  * anything else                           -> ``leaked-scope`` finding
"""

from __future__ import annotations

import ast

from pilosa_tpu.analyze.report import Finding

# factory attr/name -> human description; extended by analyze.toml
# [resources.scoped] entries.
_DEFAULT_SCOPED = {
    "pinned": "pin lease",
    "span": "trace span",
    "start_trace": "trace root",
    "deadline_scope": "deadline scope",
    "ChunkPipe": "chunk pipe",
}
_RELEASERS = {"close", "release", "unpin", "finish", "__exit__", "abort"}


def _call_name(call: ast.Call) -> str | None:
    f = call.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return None


class ResourcePass:
    def __init__(self, idx):
        self.idx = idx
        self.scoped = dict(_DEFAULT_SCOPED)
        self.scoped.update(self.idx.config.scoped_resources)
        self.findings: list[Finding] = []

    def run(self) -> list[Finding]:
        for fq, fi in self.idx.functions.items():
            self._check_function(fq, fi)
        return self.findings

    def _check_function(self, fq: str, fi) -> None:
        node = fi.node
        parent: dict = {}
        for p in ast.walk(node):
            for c in ast.iter_child_nodes(p):
                parent[c] = p

        # names with a releaser called inside ANY finally/With-exit in
        # this function, and names later used as a with-context
        released: set[str] = set()
        withed: set[str] = set()
        for st in ast.walk(node):
            if isinstance(st, ast.Try):
                for fin_st in st.finalbody:
                    for c in ast.walk(fin_st):
                        if (
                            isinstance(c, ast.Call)
                            and isinstance(c.func, ast.Attribute)
                            and c.func.attr in _RELEASERS
                            and isinstance(c.func.value, ast.Name)
                        ):
                            released.add(c.func.value.id)
            if isinstance(st, ast.With):
                for item in st.items:
                    ce = item.context_expr
                    if isinstance(ce, ast.Name):
                        withed.add(ce.id)
                    # contextlib.closing(x) / ExitStack().enter_context(x)
                    if isinstance(ce, ast.Call):
                        for a in ce.args:
                            if isinstance(a, ast.Name):
                                withed.add(a.id)

        for call in ast.walk(node):
            if not isinstance(call, ast.Call):
                continue
            name = _call_name(call)
            if name not in self.scoped:
                continue
            ctx = parent.get(call)
            # with F(...):  — direct scope
            if isinstance(ctx, ast.withitem):
                continue
            # return/yield F(...) — escapes to the caller
            if isinstance(ctx, (ast.Return, ast.Yield, ast.YieldFrom)):
                continue
            # argument to another call — escapes
            if isinstance(ctx, ast.Call) and call in ctx.args:
                continue
            if isinstance(ctx, ast.Assign):
                tgt = ctx.targets[0] if len(ctx.targets) == 1 else None
                # self.x = F(...) or container[k] = F(...): owner manages
                if isinstance(tgt, (ast.Attribute, ast.Subscript)):
                    continue
                if isinstance(tgt, ast.Name):
                    if tgt.id in withed or tgt.id in released:
                        continue
                    # stored then returned / passed on?
                    if self._escapes(node, tgt.id):
                        continue
            self.findings.append(
                Finding(
                    rule="leaked-scope",
                    path=fi.path,
                    line=call.lineno,
                    message=(
                        f"{fq}: {self.scoped[name]} from {name}(...) is "
                        "not guaranteed release — use `with`, or release "
                        "in a `finally`"
                    ),
                    key=f"leaked-scope:{fq}:{name}",
                )
            )
        # dedup identical keys (a helper called twice)
        seen: set = set()
        uniq = []
        for f in self.findings:
            if f.key in seen:
                continue
            seen.add(f.key)
            uniq.append(f)
        self.findings = uniq

    @staticmethod
    def _escapes(func_node, var: str) -> bool:
        for n in ast.walk(func_node):
            if isinstance(n, ast.Return) and n.value is not None:
                for c in ast.walk(n.value):
                    if isinstance(c, ast.Name) and c.id == var:
                        return True
            if isinstance(n, ast.Call):
                for a in list(n.args) + [k.value for k in n.keywords]:
                    for c in ast.walk(a):
                        if isinstance(c, ast.Name) and c.id == var:
                            return True
        return False
