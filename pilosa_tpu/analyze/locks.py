"""Pass 1 — interprocedural lock-order analysis.

Per function the walker tracks the set of held locks through ``with``
nesting and explicit ``acquire()``/``release()`` calls (including the
pool's ``acquire(blocking=False)`` eviction-callback idiom, which
yields NON-BLOCKING edges — they cannot deadlock, but cycles through
them are still reported so the design stays documented in
``analyze.toml`` rather than implicit).  A fixpoint over the call graph
then summarizes, for every function, the locks it may transitively
acquire and the blocking calls it may transitively reach, so an
acquisition made three calls below a ``with`` still produces its edge.

Lock identity is the CREATION SITE (``module.Class.attr``, a module
global, or a function local) — all instances of a class share one node,
the standard lock-order abstraction.  ``threading.Condition(self._mu)``
aliases to the wrapped lock; a Condition's ``wait()`` under exactly its
own lock is the one blocking call that is exempt (wait releases it).

Findings:
  * ``lock-cycle`` — a cycle in the acquisition graph (severity
    ``error`` when every edge is blocking, ``warn`` when a
    non-blocking edge breaks the deadlock).
  * ``blocking-under-lock`` — socket I/O, ``Future.result``, bare
    ``queue.get``, ``join``, ``wait``, ``time.sleep``, or a device
    transfer reachable while a lock is held.
  * ``self-deadlock`` — a non-reentrant Lock re-acquired (possibly
    through calls) while already held.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

from pilosa_tpu.analyze.report import Finding

# Attribute names whose call is treated as blocking regardless of the
# receiver (receiver-aware exemptions applied after).
_BLOCKING_ATTRS = {
    "result": "Future.result",
    "wait": "wait",
    "sleep": "sleep",
    "block_until_ready": "block_until_ready",
    "recv": "socket.recv",
    "recvfrom": "socket.recvfrom",
    "accept": "socket.accept",
    "connect": "socket.connect",
    "sendall": "socket.sendall",
    "sendto": "socket.sendto",
    "getresponse": "http.getresponse",
    "urlopen": "urlopen",
    "device_put": "jax.device_put",
    "device_get": "jax.device_get",
}
_BLOCKING_NAMES = {
    "sleep": "sleep",
    "urlopen": "urlopen",
    "wait": "futures.wait",
    "as_completed": "futures.as_completed",
}


def _dotted(node) -> str:
    """Best-effort dotted rendering of a call target for messages."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return f"{_dotted(node.value)}.{node.attr}"
    if isinstance(node, ast.Call):
        return f"{_dotted(node.func)}()"
    return "<expr>"


@dataclass(frozen=True)
class Edge:
    src: str
    dst: str
    nonblocking: bool
    path: str
    line: int
    via: str  # human chain description


@dataclass
class _FuncFacts:
    # (lock_id, nonblocking, line, held-snapshot tuple)
    acquires: list = field(default_factory=list)
    # (candidate qualnames tuple, held tuple, line, call text)
    calls: list = field(default_factory=list)
    # (desc, exempt_lock_or_None, held tuple, line)
    blocking: list = field(default_factory=list)


class LockGraph:
    """The acquisition graph handed to reporting AND to the runtime
    validator (analyze.runtime verifies observed edges against it)."""

    def __init__(self):
        self.edges: dict[tuple, Edge] = {}  # (src, dst) -> witness edge
        self.lock_sites: dict[tuple, str] = {}  # (path, line) -> lock_id
        self.locks: dict[str, object] = {}  # lock_id -> LockSite

    def add(self, edge: Edge) -> None:
        cur = self.edges.get((edge.src, edge.dst))
        # A blocking witness outranks a non-blocking one.
        if cur is None or (cur.nonblocking and not edge.nonblocking):
            self.edges[(edge.src, edge.dst)] = edge

    def has_path(self, src: str, dst: str) -> bool:
        seen, stack = set(), [src]
        while stack:
            n = stack.pop()
            if n == dst:
                return True
            if n in seen:
                continue
            seen.add(n)
            stack.extend(b for (a, b) in self.edges if a == n)
        return False

    def to_dict(self) -> dict:
        return {
            "locks": {
                lid: {"path": s.path, "line": s.line, "kind": s.kind}
                for lid, s in sorted(self.locks.items())
            },
            "edges": [
                {
                    "src": e.src,
                    "dst": e.dst,
                    "nonblocking": e.nonblocking,
                    "via": e.via,
                    "where": f"{e.path}:{e.line}",
                }
                for e in sorted(
                    self.edges.values(), key=lambda e: (e.src, e.dst)
                )
            ],
        }


class LockPass:
    def __init__(self, idx):
        self.idx = idx
        self.cfg = idx.config
        self.facts: dict[str, _FuncFacts] = {}
        self.graph = LockGraph()
        self.findings: list[Finding] = []
        # summaries: qualname -> {lock_id: (nonblocking, chain)}
        self.may_acquire: dict[str, dict] = {}
        # qualname -> {key: (desc, exempt_lock, chain)}
        self.may_block: dict[str, dict] = {}

    # ------------------------------------------------------------------

    def run(self):
        for fq, fi in self.idx.functions.items():
            self.facts[fq] = _Walker(self, fi).walk()
        self._apply_config_edges()
        self._fixpoint()
        self._edges_and_findings()
        self._cycles()
        self.graph.lock_sites = dict(self.idx.locks_by_loc)
        self.graph.locks = dict(self.idx.locks)
        return self.findings, self.graph

    def _apply_config_edges(self) -> None:
        for ce in self.cfg.call_edges:
            facts = self.facts.get(ce.src)
            if facts is None:
                continue
            facts.calls.append(
                ((ce.dst,), (), 0, f"<config: {ce.reason or ce.dst}>")
            )

    # ------------------------------------------------------------------
    # interprocedural fixpoint
    # ------------------------------------------------------------------

    def _fixpoint(self) -> None:
        acq = {fq: {} for fq in self.facts}
        blk = {fq: {} for fq in self.facts}
        for fq, facts in self.facts.items():
            for lock, nb, line, _held in facts.acquires:
                cur = acq[fq].get(lock)
                if cur is None or (cur[0] and not nb):
                    acq[fq][lock] = (nb, (f"{fq}:{line}",))
            for desc, exempt, _held, line in facts.blocking:
                blk[fq][(desc, exempt)] = (desc, exempt, (f"{fq}:{line}",))
        changed = True
        while changed:
            changed = False
            for fq, facts in self.facts.items():
                for cands, _held, line, _txt in facts.calls:
                    for g in cands:
                        if g == fq:
                            continue
                        for lock, (nb, chain) in acq.get(g, {}).items():
                            if len(chain) >= 8:
                                continue
                            cur = acq[fq].get(lock)
                            if cur is None or (cur[0] and not nb):
                                acq[fq][lock] = (
                                    nb,
                                    (f"{fq}:{line}",) + chain,
                                )
                                changed = True
                        for key, (desc, exempt, chain) in blk.get(
                            g, {}
                        ).items():
                            if len(chain) >= 8:
                                continue
                            if key not in blk[fq]:
                                blk[fq][key] = (
                                    desc,
                                    exempt,
                                    (f"{fq}:{line}",) + chain,
                                )
                                changed = True
        self.may_acquire = acq
        self.may_block = blk

    # ------------------------------------------------------------------
    # edges + findings
    # ------------------------------------------------------------------

    def _reentrant(self, lock: str) -> bool:
        site = self.idx.locks.get(lock)
        return bool(site and site.reentrant)

    def _emit_edges(self, fi, held, lock, nb, line, via) -> None:
        for h in held:
            if h == lock:
                if not nb and not self._reentrant(lock):
                    self.findings.append(
                        Finding(
                            rule="self-deadlock",
                            path=fi.path,
                            line=line,
                            message=(
                                f"{fi.qualname} may re-acquire non-reentrant "
                                f"{lock} while already holding it ({via})"
                            ),
                            key=f"self-deadlock:{fi.qualname}:{lock}",
                        )
                    )
                continue
            self.graph.add(Edge(h, lock, nb, fi.path, line, via))

    def _edges_and_findings(self) -> None:
        for fq, facts in self.facts.items():
            fi = self.idx.functions[fq]
            for lock, nb, line, held in facts.acquires:
                self._emit_edges(fi, held, lock, nb, line, f"with in {fq}")
            for cands, held, line, txt in facts.calls:
                if not held:
                    continue
                for g in cands:
                    if g == fq:
                        continue
                    for lock, (nb, chain) in self.may_acquire.get(
                        g, {}
                    ).items():
                        self._emit_edges(
                            fi, held, lock, nb, line,
                            f"{fq} -> " + " -> ".join(chain),
                        )
                    for desc, exempt, chain in self.may_block.get(
                        g, {}
                    ).values():
                        self._blocking_finding(
                            fi, held, desc, exempt, line,
                            via=" -> ".join(chain),
                        )
            for desc, exempt, held, line in facts.blocking:
                if held:
                    self._blocking_finding(fi, held, desc, exempt, line)

    def _blocking_finding(self, fi, held, desc, exempt, line, via="") -> None:
        locks = sorted(set(held))
        if exempt is not None and locks == [exempt]:
            return  # cv.wait under exactly its own lock
        key = f"blocking-under-lock:{fi.qualname}:{'+'.join(locks)}:{desc}"
        if any(f.key == key for f in self.findings):
            return
        msg = f"{desc} while holding {', '.join(locks)}"
        if via:
            msg += f" (via {via})"
        self.findings.append(
            Finding(
                rule="blocking-under-lock",
                path=fi.path,
                line=line,
                message=msg,
                key=key,
                severity="warn",
            )
        )

    # ------------------------------------------------------------------
    # cycles
    # ------------------------------------------------------------------

    def _cycles(self) -> None:
        adj: dict[str, list[str]] = {}
        for a, b in self.graph.edges:
            adj.setdefault(a, []).append(b)
        order = sorted(adj)
        seen_cycles: set = set()
        for start in order:
            # DFS for simple cycles through `start` using only nodes
            # >= start (Johnson-style dedup); graphs here are tiny.
            stack = [(start, [start])]
            while stack:
                node, path = stack.pop()
                for nxt in sorted(adj.get(node, [])):
                    if nxt == start and len(path) > 0:
                        cyc = tuple(path)
                        canon = tuple(sorted(cyc))
                        if canon in seen_cycles or len(cyc) < 2:
                            continue
                        seen_cycles.add(canon)
                        self._cycle_finding(cyc)
                    elif nxt > start and nxt not in path and len(path) < 6:
                        stack.append((nxt, path + [nxt]))
            if len(seen_cycles) > 100:
                break

    def _cycle_finding(self, cyc: tuple) -> None:
        # rotate so the lexicographically-smallest lock leads: stable key
        i = cyc.index(min(cyc))
        cyc = cyc[i:] + cyc[:i]
        edges = [
            self.graph.edges[(cyc[j], cyc[(j + 1) % len(cyc)])]
            for j in range(len(cyc))
        ]
        all_blocking = all(not e.nonblocking for e in edges)
        chain = " -> ".join(cyc + (cyc[0],))
        detail = "; ".join(
            f"{e.src}->{e.dst}{' (non-blocking)' if e.nonblocking else ''} "
            f"at {e.path}:{e.line}"
            for e in edges
        )
        self.findings.append(
            Finding(
                rule="lock-cycle",
                path=edges[0].path,
                line=edges[0].line,
                message=(
                    ("potential deadlock: " if all_blocking else
                     "lock-order cycle (broken by a non-blocking acquire): ")
                    + chain + " — " + detail
                ),
                key="lock-cycle:" + "->".join(cyc),
                severity="error" if all_blocking else "warn",
            )
        )


class _Walker:
    """Single-function walk: held-set tracking + local inference."""

    def __init__(self, pass_: LockPass, fi):
        self.p = pass_
        self.idx = pass_.idx
        self.fi = fi
        self.mi = self.idx.modules[fi.modname]
        self.facts = _FuncFacts()
        self.var_types: dict[str, set] = {}
        if fi.class_qual:
            self.var_types["self<class>"] = fi.class_qual
        self.local_locks: dict[str, str] = {}

    # -- pre-pass ------------------------------------------------------

    def _prepass(self) -> None:
        node = self.fi.node
        self.var_types = self.idx.infer_types(
            self.mi, self.fi.class_qual, node
        )
        for st in ast.walk(node):
            if not isinstance(st, ast.Assign) or len(st.targets) != 1:
                continue
            t = st.targets[0]
            if not isinstance(t, ast.Name) or not isinstance(st.value, ast.Call):
                continue
            kind = self.idx._lock_factory_kind(self.mi, st.value)
            if kind:
                lid = f"{self.fi.qualname}.<{t.id}>"
                self.idx._register_lock(lid, self.mi, st.value, kind)
                self.local_locks[t.id] = lid

    # -- walk ----------------------------------------------------------

    def walk(self) -> _FuncFacts:
        self._prepass()
        self._body(self.fi.node.body, [])
        return self.facts

    def _body(self, stmts, held) -> None:
        held = list(held)
        for st in stmts:
            self._stmt(st, held)

    def _resolve_lock(self, expr) -> str | None:
        lid = self.idx.resolve_lock_expr(
            self.mi, self.fi.class_qual, expr, self.local_locks
        )
        return lid

    def _acquire(self, lock, nb, line, held) -> None:
        self.facts.acquires.append(
            (lock, nb, line, tuple(h for h in held))
        )
        held.append(lock)

    def _release(self, lock, held) -> None:
        if lock in held:
            held.reverse()
            held.remove(lock)
            held.reverse()

    def _stmt(self, st, held) -> None:
        if isinstance(st, ast.With):
            pushed = []
            for item in st.items:
                ce = item.context_expr
                lid = self._resolve_lock(ce)
                if lid is not None:
                    self._acquire(lid, False, ce.lineno, held)
                    pushed.append(lid)
                else:
                    self._exprs(ce, held)
            self._body(st.body, held)
            for lid in reversed(pushed):
                self._release(lid, held)
            return
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # Closure: walk its body with a FRESH held set but the same
            # local context, attributing its effects to the enclosing
            # function — conservative for the worker-thread closures the
            # gossip/prefetch layers use.
            self._body(st.body, [])
            return
        if isinstance(st, ast.ClassDef):
            return
        if isinstance(st, ast.If):
            # `if not X.acquire(blocking=False): return` — the guarded
            # remainder of the function runs with X held non-blocking.
            acq = self._acquire_in_test(st.test)
            if acq is not None and self._body_escapes(st.body):
                lock, nb, line = acq
                self._acquire(lock, nb, line, held)
                self._body(st.orelse, held)
                return
            self._exprs(st.test, held)
            self._body(st.body, held)
            self._body(st.orelse, held)
            return
        if isinstance(st, (ast.For, ast.AsyncFor)):
            self._exprs(st.iter, held)
            self._body(st.body, held)
            self._body(st.orelse, held)
            return
        if isinstance(st, ast.While):
            self._exprs(st.test, held)
            self._body(st.body, held)
            self._body(st.orelse, held)
            return
        if isinstance(st, ast.Try):
            self._body(st.body, held)
            for h in st.handlers:
                self._body(h.body, held)
            self._body(st.orelse, held)
            self._body(st.finalbody, held)
            return
        if isinstance(st, ast.Expr) and isinstance(st.value, ast.Call):
            call = st.value
            f = call.func
            if isinstance(f, ast.Attribute) and f.attr in ("acquire", "release"):
                lid = self._resolve_lock(f.value)
                if lid is not None:
                    if f.attr == "acquire":
                        self._acquire(
                            lid, self._nonblocking(call), call.lineno, held
                        )
                    else:
                        self._release(lid, held)
                    return
        # generic statement: scan expressions
        for child in ast.iter_child_nodes(st):
            self._exprs(child, held)

    @staticmethod
    def _body_escapes(body) -> bool:
        return len(body) >= 1 and isinstance(
            body[-1], (ast.Return, ast.Raise, ast.Continue, ast.Break)
        )

    def _acquire_in_test(self, test):
        """(lock, nonblocking, line) when the If test is
        ``not X.acquire(...)`` / ``X.acquire(...)`` on a known lock."""
        node = test
        if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.Not):
            node = node.operand
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "acquire"
        ):
            lid = self._resolve_lock(node.func.value)
            if lid is not None:
                return (lid, self._nonblocking(node), node.lineno)
        return None

    @staticmethod
    def _nonblocking(call: ast.Call) -> bool:
        for kw in call.keywords:
            if kw.arg == "blocking" and isinstance(kw.value, ast.Constant):
                return kw.value.value is False
        if call.args and isinstance(call.args[0], ast.Constant):
            return call.args[0].value is False
        return False

    # -- expressions ---------------------------------------------------

    def _exprs(self, node, held) -> None:
        if node is None:
            return
        for call in [
            n for n in ast.walk(node) if isinstance(n, ast.Call)
        ]:
            self._handle_call(call, held)

    def _handle_call(self, call: ast.Call, held) -> None:
        if self.idx._lock_factory_kind(self.mi, call):
            return
        f = call.func
        # mid-expression acquire/release on a known lock
        if isinstance(f, ast.Attribute) and f.attr in ("acquire", "release"):
            lid = self._resolve_lock(f.value)
            if lid is not None:
                if f.attr == "acquire":
                    self._acquire(lid, self._nonblocking(call), call.lineno, held)
                else:
                    self._release(lid, held)
                return
        self._check_blocking(call, held)
        cands = self.idx.resolve_call(
            self.mi, self.fi.class_qual, call, self.var_types
        )
        if cands:
            self.facts.calls.append(
                (tuple(cands), tuple(held), call.lineno, _dotted(f))
            )

    def _check_blocking(self, call: ast.Call, held) -> None:
        f = call.func
        desc = None
        exempt = None
        if isinstance(f, ast.Attribute):
            attr = f.attr
            if attr == "join":
                if not self._looks_like_thread_join(call):
                    return
                desc = "thread.join"
            elif attr == "get":
                # bare .get() — queue.get; dict.get always passes a key
                if call.args or any(k.arg != "timeout" for k in call.keywords):
                    return
                if (
                    isinstance(f.value, ast.Name)
                    and f.value.id in self.mi.ctxvars
                ):
                    return  # ContextVar.get() — a read, not a pop
                desc = "queue.get"
            elif attr == "connect":
                # sqlite3.connect opens a database file, not a socket
                if isinstance(f.value, ast.Name) and f.value.id == "sqlite3":
                    return
                desc = _BLOCKING_ATTRS[attr]
            elif attr in _BLOCKING_ATTRS:
                if isinstance(f.value, ast.Constant):
                    return
                desc = _BLOCKING_ATTRS[attr]
                if attr == "wait":
                    lid = self._resolve_lock(f.value)
                    if lid is not None:
                        desc = f"Condition.wait({lid})"
                        exempt = lid
        elif isinstance(f, ast.Name) and f.id in _BLOCKING_NAMES:
            if self.idx.resolve_symbol(self.mi, f) is not None:
                return  # package-local function named wait/sleep
            desc = _BLOCKING_NAMES[f.id]
        if desc is None:
            txt = _dotted(f)
            for pat in self.cfg_blocking():
                if txt == pat or txt.endswith("." + pat):
                    desc = pat
                    break
        if desc is None:
            return
        self.facts.blocking.append(
            (desc, exempt, tuple(held), call.lineno)
        )

    def cfg_blocking(self):
        return self.p.cfg.blocking_calls

    @staticmethod
    def _looks_like_thread_join(call: ast.Call) -> bool:
        recv = call.func.value
        if isinstance(recv, ast.Constant):
            return False  # "sep".join(...)
        if isinstance(recv, ast.Attribute) and recv.attr == "path":
            return False  # os.path.join
        if len(call.args) > 1:
            return False
        if call.args and not isinstance(call.args[0], (ast.Constant, ast.Name)):
            return False
        if len(call.args) == 1 and isinstance(call.args[0], ast.Constant):
            if not isinstance(call.args[0].value, (int, float)):
                return False
        return True
