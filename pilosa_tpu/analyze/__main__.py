"""``python -m pilosa_tpu.analyze`` — the CI gate.

Exit status 0 when every finding is covered by ``analyze.toml``;
1 when any active finding remains (the gate fails CLOSED on new
hazards).  ``--json`` writes the machine-readable report (published as
a CI build artifact), ``--graph`` dumps the static lock-order graph.
"""

from __future__ import annotations

import argparse
import json
import sys

from pilosa_tpu.analyze.config import load_config
from pilosa_tpu.analyze.run import PASSES, run_analysis


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m pilosa_tpu.analyze",
        description="concurrency & compile-hazard analyzer",
    )
    ap.add_argument(
        "passes",
        nargs="*",
        default=[],
        metavar="pass",
        help=f"subset of passes to run: {', '.join(PASSES)} (default: all)",
    )
    ap.add_argument("--config", help="path to analyze.toml")
    ap.add_argument("--json", dest="json_path", help="write JSON report")
    ap.add_argument("--graph", help="write the static lock graph as JSON")
    ap.add_argument(
        "-q", "--quiet", action="store_true", help="summary line only"
    )
    args = ap.parse_args(argv)

    cfg = load_config(args.config)
    for p in args.passes:
        if p not in PASSES:
            ap.error(f"unknown pass {p!r} (choose from {', '.join(PASSES)})")
    passes = tuple(args.passes) if args.passes else PASSES
    if "locks" not in passes and args.graph:
        passes = passes + ("locks",)
    rep, graph = run_analysis(config=cfg, passes=passes)

    if args.json_path:
        with open(args.json_path, "w", encoding="utf-8") as fh:
            fh.write(rep.to_json() + "\n")
    if args.graph and graph is not None:
        with open(args.graph, "w", encoding="utf-8") as fh:
            json.dump(graph.to_dict(), fh, indent=2, sort_keys=True)
            fh.write("\n")

    text = rep.render_text()
    if args.quiet:
        text = text.splitlines()[-1]
    print(text)
    return rep.exit_code()


if __name__ == "__main__":
    sys.exit(main())
