"""AST package index: modules, classes, functions, locks, resolution.

One parse of the whole package feeds all three passes.  Resolution is
deliberately best-effort — exact where the AST allows (self-methods,
module functions, imports, locally-inferred instance types, configured
factory returns) and duck-typed through configured interface groups
where it does not (``.stats`` / ``.tracer`` receivers).  Unresolved
calls resolve to nothing rather than to everything: the lock pass wants
a graph that is complete over the package's REAL interactions (the
runtime validation mode keeps it honest) without drowning in
impossible edges.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field

LOCK_FACTORIES = {"Lock": "Lock", "RLock": "RLock", "Condition": "Condition"}


@dataclass
class LockSite:
    lock_id: str  # e.g. "pilosa_tpu.device.pool.PlanePool._mu"
    path: str  # repo-relative, e.g. "pilosa_tpu/device/pool.py"
    line: int  # line of the threading.X(...) call
    kind: str  # "Lock" | "RLock" | "Condition"

    @property
    def reentrant(self) -> bool:
        return self.kind == "RLock"


@dataclass
class FunctionInfo:
    qualname: str  # "pilosa_tpu.exec.plan._ProgramCache.__call__"
    modname: str
    class_qual: str | None
    node: object  # ast.FunctionDef | ast.AsyncFunctionDef
    path: str


@dataclass
class ClassInfo:
    qualname: str
    modname: str
    node: object
    bases: list[str] = field(default_factory=list)  # resolved qualnames
    methods: dict[str, FunctionInfo] = field(default_factory=dict)
    # attr name -> candidate class qualnames (from self.X = Cls(...))
    attr_types: dict[str, set] = field(default_factory=dict)
    # container attr name -> element class qualnames (self.X[k] = <obj>)
    elem_types: dict[str, set] = field(default_factory=dict)
    # attr name -> lock_id (self.X = threading.Lock() / alias target)
    lock_attrs: dict[str, str] = field(default_factory=dict)


@dataclass
class ModuleInfo:
    modname: str  # dotted, e.g. "pilosa_tpu.exec.plan"
    path: str  # repo-relative
    tree: object
    # local name -> dotted target (module, class, or function qualname)
    imports: dict[str, str] = field(default_factory=dict)
    # module-level lock name -> lock_id
    lock_globals: dict[str, str] = field(default_factory=dict)
    # module-level names bound to contextvars.ContextVar(...) — their
    # .get() is a contextvar read, not a queue pop
    ctxvars: set = field(default_factory=set)


class PackageIndex:
    """Parsed package + symbol tables + lock registry."""

    def __init__(self, pkg_dir: str, package: str, config):
        self.pkg_dir = pkg_dir
        self.package = package
        self.config = config
        self.root = os.path.dirname(pkg_dir)
        self.modules: dict[str, ModuleInfo] = {}
        self.classes: dict[str, ClassInfo] = {}
        self.functions: dict[str, FunctionInfo] = {}
        self.locks: dict[str, LockSite] = {}
        self.locks_by_loc: dict[tuple, str] = {}
        # method name -> class qualnames defining it (for group lookup)
        self.method_classes: dict[str, list[str]] = {}
        # group method name -> candidate function qualnames
        self.group_methods: dict[str, list[str]] = {}
        self._load()
        self._index_symbols()
        self._index_groups()

    # ------------------------------------------------------------------
    # loading
    # ------------------------------------------------------------------

    def _load(self) -> None:
        excl = set(self.config.exclude or [])
        for dirpath, dirnames, filenames in os.walk(self.pkg_dir):
            dirnames[:] = sorted(
                d for d in dirnames if d != "__pycache__"
            )
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                full = os.path.join(dirpath, fn)
                rel = os.path.relpath(full, self.root)
                if rel in excl or rel.replace(os.sep, "/") in excl:
                    continue
                mod = rel[: -len(".py")].replace(os.sep, ".")
                if mod.endswith(".__init__"):
                    mod = mod[: -len(".__init__")]
                with open(full, "r", encoding="utf-8") as fh:
                    src = fh.read()
                tree = ast.parse(src, filename=rel)
                self.modules[mod] = ModuleInfo(
                    modname=mod, path=rel.replace(os.sep, "/"), tree=tree
                )

    # ------------------------------------------------------------------
    # symbol tables
    # ------------------------------------------------------------------

    def _index_symbols(self) -> None:
        for mi in self.modules.values():
            self._index_imports(mi)
        for mi in self.modules.values():
            for node in mi.tree.body:
                if isinstance(node, ast.ClassDef):
                    self._index_class(mi, node)
                elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    q = f"{mi.modname}.{node.name}"
                    self.functions[q] = FunctionInfo(
                        q, mi.modname, None, node, mi.path
                    )
            self._discover_module_locks(mi)
        # attr/elem types settle in two rounds (cross-class chains)
        for _ in range(2):
            for mi in self.modules.values():
                for node in mi.tree.body:
                    if isinstance(node, ast.ClassDef):
                        ci = self.classes[f"{mi.modname}.{node.name}"]
                        self._index_attr_types(mi, ci)
        for mi in self.modules.values():
            for node in mi.tree.body:
                if isinstance(node, ast.ClassDef):
                    ci = self.classes[f"{mi.modname}.{node.name}"]
                    self._discover_class_locks(mi, ci)

    def _index_imports(self, mi: ModuleInfo) -> None:
        for node in ast.walk(mi.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.name.startswith(self.package):
                        mi.imports[alias.asname or alias.name.split(".")[0]] = (
                            alias.name
                        )
            elif isinstance(node, ast.ImportFrom):
                if not node.module or not node.module.startswith(self.package):
                    continue
                for alias in node.names:
                    mi.imports[alias.asname or alias.name] = (
                        f"{node.module}.{alias.name}"
                    )

    def _index_class(self, mi: ModuleInfo, node: ast.ClassDef) -> None:
        q = f"{mi.modname}.{node.name}"
        ci = ClassInfo(qualname=q, modname=mi.modname, node=node)
        for b in node.bases:
            bq = self.resolve_symbol(mi, b)
            if bq:
                ci.bases.append(bq)
        for item in node.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fq = f"{q}.{item.name}"
                fi = FunctionInfo(fq, mi.modname, q, item, mi.path)
                ci.methods[item.name] = fi
                self.functions[fq] = fi
                self.method_classes.setdefault(item.name, []).append(q)
        self.classes[q] = ci

    def resolve_symbol(self, mi: ModuleInfo, node) -> str | None:
        """Dotted name of a Name/Attribute expression, through this
        module's package imports; None for anything external."""
        if isinstance(node, ast.Name):
            tgt = mi.imports.get(node.id)
            if tgt:
                return tgt
            if f"{mi.modname}.{node.id}" in self.classes:
                return f"{mi.modname}.{node.id}"
            if f"{mi.modname}.{node.id}" in self.functions:
                return f"{mi.modname}.{node.id}"
            return None
        if isinstance(node, ast.Attribute):
            base = self.resolve_symbol(mi, node.value)
            if base:
                return f"{base}.{node.attr}"
            return None
        return None

    def _annotation_class(self, mi, ann) -> str | None:
        """Package class named by a return/arg annotation; unwraps
        ``X | None`` and ``Optional[X]``."""
        if ann is None:
            return None
        if isinstance(ann, ast.BinOp):  # X | None
            left = self._annotation_class(mi, ann.left)
            return left or self._annotation_class(mi, ann.right)
        if isinstance(ann, ast.Subscript):  # Optional[X] / list[X]
            return self._annotation_class(mi, ann.slice)
        if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
            try:
                ann = ast.parse(ann.value, mode="eval").body
            except SyntaxError:
                return None
            return self._annotation_class(mi, ann)
        sym = self.resolve_symbol(mi, ann)
        if sym in self.classes:
            return sym
        return None

    def _call_result_class(self, mi, call: ast.Call, var_types) -> str | None:
        """Class qualname a call returns an instance of: direct class
        instantiation, a configured factory return, or a resolvable
        function whose return annotation names a package class."""
        fq = self.resolve_symbol(mi, call.func)
        if fq is None and isinstance(call.func, ast.Attribute):
            # method call on an inferred receiver
            for cand in self._receiver_classes(mi, call.func.value, var_types):
                r = self.config.returns.get(f"{cand}.{call.func.attr}")
                if r:
                    return r
                for c in self._mro(cand):
                    ci = self.classes.get(c)
                    if ci and call.func.attr in ci.methods:
                        meth = ci.methods[call.func.attr]
                        ann = self._annotation_class(
                            self.modules[meth.modname], meth.node.returns
                        )
                        if ann:
                            return ann
                        break
            return None
        if fq in self.classes:
            return fq
        if fq:
            r = self.config.returns.get(fq)
            if r:
                return r
            fn = self.functions.get(fq)
            if fn is not None:
                return self._annotation_class(
                    self.modules[fn.modname], fn.node.returns
                )
        return None

    # unwrappers around an iterable that preserve the element type
    _ITER_WRAPPERS = {"sorted", "list", "tuple", "reversed", "set", "iter"}

    def _container_elem_types(self, mi, node, var_types) -> set:
        """Element classes when ``node`` is a read from a typed
        container attribute: self.X[k], self.X.get/pop(k),
        self.X.values()/items() (iteration handled by callers)."""
        attr = None
        if isinstance(node, ast.Subscript):
            attr = node.value
        elif (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in ("get", "pop", "values", "items", "setdefault")
        ):
            attr = node.func.value
        if (
            isinstance(attr, ast.Attribute)
            and isinstance(attr.value, ast.Name)
            and attr.value.id == "self"
        ):
            cq = var_types.get("self<class>")
            out: set = set()
            if cq:
                for c in self._mro(cq):
                    ci = self.classes.get(c)
                    if ci and attr.attr in ci.elem_types:
                        out |= ci.elem_types[attr.attr]
            return out
        return set()

    def expr_types(self, mi, node, var_types) -> set:
        """Candidate classes an expression evaluates to."""
        if isinstance(node, ast.Name):
            if node.id == "self" and var_types.get("self<class>"):
                return {var_types["self<class>"]}
            v = var_types.get(node.id)
            return set(v) if v else set()
        if isinstance(node, ast.Call):
            cls = self._call_result_class(mi, node, var_types)
            if cls:
                return {cls}
            return self._container_elem_types(mi, node, var_types)
        if isinstance(node, ast.Subscript):
            return self._container_elem_types(mi, node, var_types)
        if isinstance(node, ast.Attribute):
            out: set = set()
            if isinstance(node.value, ast.Name) and node.value.id == "self":
                cq = var_types.get("self<class>")
                if cq:
                    for c in self._mro(cq):
                        ci = self.classes.get(c)
                        if ci and node.attr in ci.attr_types:
                            out |= ci.attr_types[node.attr]
            cfg = self.config.attr_types.get(node.attr)
            if cfg:
                out |= set(cfg)
            return out
        if isinstance(node, ast.BoolOp):  # x = given or Default()
            out = set()
            for v in node.values:
                out |= self.expr_types(mi, v, var_types)
            return out
        if isinstance(node, ast.IfExp):
            return self.expr_types(mi, node.body, var_types) | self.expr_types(
                mi, node.orelse, var_types
            )
        return set()

    def _iter_elem_types(self, mi, it, var_types) -> tuple[set, bool]:
        """(element classes, is_items_pairs) for a ``for``/comprehension
        iterable expression."""
        while (
            isinstance(it, ast.Call)
            and isinstance(it.func, ast.Name)
            and it.func.id in self._ITER_WRAPPERS
            and it.args
        ):
            it = it.args[0]
        if isinstance(it, ast.Call) and isinstance(it.func, ast.Attribute):
            if it.func.attr in ("values", "items"):
                elems = self._container_elem_types(mi, it, var_types)
                return elems, it.func.attr == "items"
        return set(), False

    def infer_types(self, mi, class_qual, fnode) -> dict:
        """Local-variable class inference for one function body: two
        passes so chained assignments settle."""
        vt: dict = {}
        if class_qual:
            vt["self<class>"] = class_qual
        args = getattr(fnode, "args", None)
        if args is not None:
            for arg in list(args.args) + list(args.kwonlyargs):
                cls = self._annotation_class(mi, arg.annotation)
                if cls:
                    vt.setdefault(arg.arg, set()).add(cls)
        for _ in range(2):
            for st in ast.walk(fnode):
                if isinstance(st, ast.Assign) and len(st.targets) == 1:
                    t = st.targets[0]
                    if isinstance(t, ast.Name):
                        ts = self.expr_types(mi, st.value, vt)
                        if ts:
                            vt.setdefault(t.id, set()).update(ts)
                elif isinstance(st, (ast.For, ast.AsyncFor)):
                    self._bind_loop_target(mi, st.target, st.iter, vt)
                elif isinstance(
                    st, (ast.ListComp, ast.SetComp, ast.GeneratorExp, ast.DictComp)
                ):
                    for gen in st.generators:
                        self._bind_loop_target(mi, gen.target, gen.iter, vt)
        return vt

    def _bind_loop_target(self, mi, target, it, vt) -> None:
        elems, is_items = self._iter_elem_types(mi, it, vt)
        if not elems:
            return
        if is_items:
            if (
                isinstance(target, ast.Tuple)
                and len(target.elts) == 2
                and isinstance(target.elts[1], ast.Name)
            ):
                vt.setdefault(target.elts[1].id, set()).update(elems)
        elif isinstance(target, ast.Name):
            vt.setdefault(target.id, set()).update(elems)

    def _index_attr_types(self, mi, ci: ClassInfo) -> None:
        """Populate attr_types (self.X = <typed expr>) and elem_types
        (self.X[k] = <typed expr>) from every method body."""
        for meth in ci.methods.values():
            vt = self.infer_types(mi, ci.qualname, meth.node)
            for st in ast.walk(meth.node):
                if not isinstance(st, ast.Assign) or len(st.targets) != 1:
                    continue
                t = st.targets[0]
                ts = self.expr_types(mi, st.value, vt)
                if not ts:
                    continue
                if (
                    isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"
                ):
                    ci.attr_types.setdefault(t.attr, set()).update(ts)
                elif (
                    isinstance(t, ast.Subscript)
                    and isinstance(t.value, ast.Attribute)
                    and isinstance(t.value.value, ast.Name)
                    and t.value.value.id == "self"
                ):
                    ci.elem_types.setdefault(t.value.attr, set()).update(ts)

    def _receiver_classes(self, mi, node, var_types) -> list[str]:
        """Candidate class qualnames for a call receiver expression."""
        return sorted(self.expr_types(mi, node, var_types))

    def _mro(self, cq: str) -> list[str]:
        out, seen = [], set()
        stack = [cq]
        while stack:
            c = stack.pop(0)
            if c in seen or c not in self.classes:
                continue
            seen.add(c)
            out.append(c)
            stack.extend(self.classes[c].bases)
        return out

    # ------------------------------------------------------------------
    # lock discovery
    # ------------------------------------------------------------------

    def _lock_factory_kind(self, mi, call: ast.Call) -> str | None:
        """"Lock"/"RLock"/"Condition" when ``call`` is a threading
        factory call, else None."""
        f = call.func
        if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
            if f.value.id == "threading" and f.attr in LOCK_FACTORIES:
                return f.attr
        if isinstance(f, ast.Name) and f.id in LOCK_FACTORIES:
            # from threading import Lock — not used in-tree, but cheap
            for node in ast.walk(mi.tree):
                if isinstance(node, ast.ImportFrom) and node.module == "threading":
                    if any((a.asname or a.name) == f.id for a in node.names):
                        return LOCK_FACTORIES[f.id]
        return None

    def _register_lock(self, lock_id: str, mi, call, kind: str) -> str:
        site = LockSite(lock_id, mi.path, call.lineno, kind)
        self.locks[lock_id] = site
        self.locks_by_loc[(site.path, site.line)] = lock_id
        return lock_id

    def _discover_module_locks(self, mi: ModuleInfo) -> None:
        for node in mi.tree.body:
            tgt = None
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                tgt = node.targets[0]
                value = node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                tgt = node.target
                value = node.value
            else:
                continue
            if isinstance(tgt, ast.Name) and isinstance(value, ast.Call):
                kind = self._lock_factory_kind(mi, value)
                if kind:
                    lid = self._register_lock(
                        f"{mi.modname}.{tgt.id}", mi, value, kind
                    )
                    mi.lock_globals[tgt.id] = lid
                fname = value.func
                if (
                    isinstance(fname, ast.Attribute)
                    and fname.attr == "ContextVar"
                ) or (
                    isinstance(fname, ast.Name) and fname.id == "ContextVar"
                ):
                    mi.ctxvars.add(tgt.id)

    def _discover_class_locks(self, mi: ModuleInfo, ci: ClassInfo) -> None:
        for meth in ci.methods.values():
            for st in ast.walk(meth.node):
                if not isinstance(st, ast.Assign) or len(st.targets) != 1:
                    continue
                t = st.targets[0]
                if not (
                    isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"
                    and isinstance(st.value, ast.Call)
                ):
                    continue
                call = st.value
                kind = self._lock_factory_kind(mi, call)
                if not kind:
                    continue
                if kind == "Condition" and call.args:
                    arg = call.args[0]
                    # Condition(self._mu): pure alias of an existing lock
                    if (
                        isinstance(arg, ast.Attribute)
                        and isinstance(arg.value, ast.Name)
                        and arg.value.id == "self"
                    ):
                        tgt = self._class_lock_attr(ci.qualname, arg.attr)
                        if tgt:
                            ci.lock_attrs[t.attr] = tgt
                            continue
                    # Condition(threading.Lock()): the inner Lock IS the
                    # lock; its creation site is this line.
                lid = f"{ci.qualname}.{t.attr}"
                self._register_lock(lid, mi, call, kind)
                ci.lock_attrs[t.attr] = lid

    def _class_lock_attr(self, class_qual: str, attr: str) -> str | None:
        for c in self._mro(class_qual):
            ci = self.classes.get(c)
            if ci and attr in ci.lock_attrs:
                return ci.lock_attrs[attr]
        return None

    # ------------------------------------------------------------------
    # interface groups
    # ------------------------------------------------------------------

    def _index_groups(self) -> None:
        for g in self.config.groups:
            for cq in g.classes:
                ci = self.classes.get(cq)
                if ci is None:
                    continue
                names = g.methods or list(ci.methods)
                for m in names:
                    if m in ci.methods:
                        self.group_methods.setdefault(m, []).append(
                            ci.methods[m].qualname
                        )

    # ------------------------------------------------------------------
    # call / lock-expression resolution (used by the passes)
    # ------------------------------------------------------------------

    def resolve_call(self, mi, class_qual, call: ast.Call, var_types) -> list[str]:
        """Candidate function qualnames a call may invoke.  Empty when
        unresolvable — the passes treat that as 'no effect' and lean on
        config call-edges plus the runtime validator for coverage."""
        f = call.func
        # self.m(...) -> method on this class (or a base)
        if (
            isinstance(f, ast.Attribute)
            and isinstance(f.value, ast.Name)
            and f.value.id == "self"
            and class_qual
        ):
            for c in self._mro(class_qual):
                ci = self.classes.get(c)
                if ci and f.attr in ci.methods:
                    return [ci.methods[f.attr].qualname]
            # fall through: self.<attr>.<m> handled below via receiver
        # plain name: module function / imported function / class ctor
        if isinstance(f, ast.Name):
            sym = self.resolve_symbol(mi, f)
            if sym in self.functions:
                return [sym]
            if sym in self.classes:
                init = self.classes[sym].methods.get("__init__")
                return [init.qualname] if init else []
            return []
        if not isinstance(f, ast.Attribute):
            return []
        # dotted module path: pkg.mod.func(...)
        sym = self.resolve_symbol(mi, f)
        if sym in self.functions:
            return [sym]
        if sym in self.classes:
            init = self.classes[sym].methods.get("__init__")
            return [init.qualname] if init else []
        # receiver with an inferred / configured class
        out: list[str] = []
        for cand in self._receiver_classes(mi, f.value, var_types):
            for c in self._mro(cand):
                ci = self.classes.get(c)
                if ci and f.attr in ci.methods:
                    out.append(ci.methods[f.attr].qualname)
                    break
        if out:
            return sorted(set(out))
        # duck-typed interface group fallback
        return list(self.group_methods.get(f.attr, []))

    def resolve_lock_expr(self, mi, class_qual, node, local_locks) -> str | None:
        """Lock id of an expression used as ``with <expr>`` or
        ``<expr>.acquire()``; None when it isn't a known lock."""
        if isinstance(node, ast.Call) and not node.args and not node.keywords:
            # ``with mod.fn():`` where fn is a declared lock-returning
            # factory ([locks.lock-returns] in analyze.toml — e.g.
            # plan.collective_launch returning the process collective
            # mutex): the acquisition is of the RETURNED lock.
            sym = self.resolve_symbol(mi, node.func)
            if sym is None and isinstance(node.func, ast.Name):
                sym = mi.imports.get(node.func.id)
            if sym:
                lid = self.config.lock_returns.get(sym)
                if lid and lid in self.locks:
                    return lid
            return None
        if isinstance(node, ast.Name):
            if node.id in local_locks:
                return local_locks[node.id]
            if node.id in mi.lock_globals:
                return mi.lock_globals[node.id]
            sym = mi.imports.get(node.id)
            if sym and sym in self.locks:
                return sym
            return None
        if isinstance(node, ast.Attribute):
            if isinstance(node.value, ast.Name) and node.value.id == "self":
                if class_qual:
                    lid = self._class_lock_attr(class_qual, node.attr)
                    if lid:
                        return lid
                return None
            # two-level: self.store.lock — receiver class carries it
            if (
                isinstance(node.value, ast.Attribute)
                and isinstance(node.value.value, ast.Name)
                and node.value.value.id == "self"
            ):
                vt = {"self<class>": class_qual} if class_qual else {}
                for cand in self._receiver_classes(mi, node.value, vt):
                    lid = self._class_lock_attr(cand, node.attr)
                    if lid:
                        return lid
            # module attr: mod.LOCK
            sym = self.resolve_symbol(mi, node)
            if sym and sym in self.locks:
                return sym
            return None
        return None

    def stats(self) -> dict:
        return {
            "files": len(self.modules),
            "classes": len(self.classes),
            "functions": len(self.functions),
            "locks": len(self.locks),
        }


def build_index(config) -> PackageIndex:
    import importlib

    pkg = importlib.import_module(config.package)
    pkg_dir = os.path.dirname(os.path.abspath(pkg.__file__))
    return PackageIndex(pkg_dir, config.package, config)
