"""Pass 2 — compile-hazard lint for the JAX layer.

The recompile blowups PR 7 hand-hunted are mechanical: a jit entry
point whose input shape (or static key) varies with schema/traffic
mints a fresh ~326 ms XLA program per distinct value unless every
shape-bearing component is bucketed through the canonical helpers
(``bp.pow2_bucket`` / ``plan.slice_bucket`` / ``bp.pad_rows``).

Rules:

* ``jit-unbucketed-shape`` — a function (in a configured hot module)
  that both builds a dynamically-shaped array (``concatenate`` /
  ``stack`` / ``pad`` / ``zeros`` sized from ``.shape`` / ``len()``)
  AND dispatches a compile entry point, without ever calling a bucket
  helper.  Function granularity keeps it honest: cross-function flows
  are out of scope (and covered by the program-cache bound gauges at
  runtime).  Container-length bucketing follows the same rule: a
  compressed-plane payload (sparse positions / RLE runs) carries a
  data-dependent length, so any site feeding one to the anchored
  kernels (``compiled_anchored_count`` / ``anchored_count_exec``) must
  pad it through ``bp.payload_bucket`` — pow2 container-length shape
  classes keep the jit keys pure geometry.
* ``jit-key-fstring`` — an f-string / ``str()`` / ``repr()`` inside an
  argument to a compile entry point: stringified dynamic values make
  unbounded compile keys.
* ``host-sync-in-loop`` — ``.item()`` / ``jax.device_get`` /
  ``block_until_ready`` / ``np.asarray`` on a device value inside a
  ``for``/``while`` in a hot module: a per-iteration host<->device
  round trip in exactly the paths the coalescer exists to batch.
* ``lru-cache-method`` — ``functools.lru_cache``/``cache`` on a
  method: the cache keys on ``self`` and keeps every instance alive.
"""

from __future__ import annotations

import ast

from pilosa_tpu.analyze.report import Finding

_DEFAULT_ENTRY_POINTS = {
    "compiled_batched",
    "compiled_total_count",
    "compiled_anchored_count",
    "anchored_count_exec",
}
_DEFAULT_BUCKET_FNS = {
    "pow2_bucket",
    "slice_bucket",
    "pad_rows",
    "bucket_classes",
    "payload_bucket",
}
_BUILDERS = {"concatenate", "stack", "pad", "zeros", "ones", "full", "empty"}
_SYNC_ATTRS = {"item", "block_until_ready", "device_get"}


def _attr_name(func) -> str | None:
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return None


class CompilePass:
    def __init__(self, idx):
        self.idx = idx
        self.cfg = idx.config
        self.entry_points = _DEFAULT_ENTRY_POINTS | set(
            self.cfg.compile_entry_points
        )
        self.bucket_fns = _DEFAULT_BUCKET_FNS | set(self.cfg.bucket_fns)
        self.findings: list[Finding] = []

    def _is_hot(self, path: str) -> bool:
        if not self.cfg.hot_modules:
            return True
        return any(
            path == m or path.startswith(m.rstrip("/") + "/")
            for m in self.cfg.hot_modules
        )

    def run(self) -> list[Finding]:
        for fq, fi in self.idx.functions.items():
            self._lru_cache_rule(fq, fi)
            if self._is_hot(fi.path):
                self._function_rules(fq, fi)
        seen: set = set()
        uniq = []
        for f in self.findings:
            if f.key in seen:
                continue
            seen.add(f.key)
            uniq.append(f)
        self.findings = uniq
        return self.findings

    # ------------------------------------------------------------------

    def _lru_cache_rule(self, fq: str, fi) -> None:
        if fi.class_qual is None:
            return
        node = fi.node
        args = node.args.args
        if not args or args[0].arg not in ("self", "cls"):
            return
        deco_names = set()
        for d in node.decorator_list:
            if isinstance(d, ast.Call):
                d = d.func
            n = _attr_name(d)
            if n:
                deco_names.add(n)
        if deco_names & {"lru_cache", "cache"}:
            if "staticmethod" in deco_names:
                return
            self.findings.append(
                Finding(
                    rule="lru-cache-method",
                    path=fi.path,
                    line=node.lineno,
                    message=(
                        f"lru_cache on method {fq}: the cache keys on "
                        "self and keeps every instance (and its device "
                        "arrays) alive — use a module-level cache keyed "
                        "explicitly, or cache on an attribute"
                    ),
                    key=f"lru-cache-method:{fq}",
                )
            )

    # ------------------------------------------------------------------

    def _function_rules(self, fq: str, fi) -> None:
        entry_calls: list[ast.Call] = []
        builder_dynamic: list[ast.Call] = []
        has_bucket_call = False
        loop_depth_syncs: list[tuple] = []
        device_vars: set[str] = set()

        def is_entry(call: ast.Call) -> bool:
            n = _attr_name(call.func)
            return n in self.entry_points

        def subtree_has_shape(node) -> bool:
            for n in ast.walk(node):
                if isinstance(n, ast.Attribute) and n.attr == "shape":
                    return True
                if (
                    isinstance(n, ast.Call)
                    and isinstance(n.func, ast.Name)
                    and n.func.id == "len"
                ):
                    return True
            return False

        def scan(node, in_loop: bool) -> None:
            nonlocal has_bucket_call
            for child in ast.iter_child_nodes(node):
                child_in_loop = in_loop or isinstance(
                    node, (ast.For, ast.While)
                )
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                if isinstance(child, ast.Call):
                    n = _attr_name(child.func)
                    if n in self.bucket_fns:
                        has_bucket_call = True
                    if is_entry(child):
                        entry_calls.append(child)
                    if n in _BUILDERS and subtree_has_shape(child):
                        builder_dynamic.append(child)
                    if child_in_loop and self._is_sync(child, device_vars):
                        loop_depth_syncs.append((child, n))
                if isinstance(child, ast.Assign) and isinstance(
                    child.value, ast.Call
                ):
                    vn = _attr_name(child.value.func) or ""
                    if vn in (
                        "device_put",
                        "device_get",
                        "device_plane",
                        "device_row",
                    ) or vn in self.entry_points:
                        for t in child.targets:
                            if isinstance(t, ast.Name):
                                device_vars.add(t.id)
                scan(child, child_in_loop)

        scan(fi.node, False)

        if entry_calls and builder_dynamic and not has_bucket_call:
            c = builder_dynamic[0]
            self.findings.append(
                Finding(
                    rule="jit-unbucketed-shape",
                    path=fi.path,
                    line=c.lineno,
                    message=(
                        f"{fq} builds a dynamically-shaped array "
                        f"({_attr_name(c.func)} sized from .shape/len) and "
                        "dispatches a compile entry point without routing "
                        "the size through pow2_bucket/slice_bucket/pad_rows "
                        "— every distinct shape compiles a fresh program"
                    ),
                    key=f"jit-unbucketed-shape:{fq}",
                )
            )
        for call in entry_calls:
            for arg in list(call.args) + [k.value for k in call.keywords]:
                for n in ast.walk(arg):
                    bad = None
                    if isinstance(n, ast.JoinedStr):
                        bad = "f-string"
                    elif (
                        isinstance(n, ast.Call)
                        and isinstance(n.func, ast.Name)
                        and n.func.id in ("str", "repr")
                    ):
                        bad = n.func.id + "()"
                    if bad:
                        self.findings.append(
                            Finding(
                                rule="jit-key-fstring",
                                path=fi.path,
                                line=n.lineno,
                                message=(
                                    f"{fq} passes a {bad} into compile "
                                    f"entry {_attr_name(call.func)} — "
                                    "stringified dynamic values make "
                                    "unbounded compile keys"
                                ),
                                key=f"jit-key-fstring:{fq}:{_attr_name(call.func)}",
                            )
                        )
        for call, n in loop_depth_syncs:
            self.findings.append(
                Finding(
                    rule="host-sync-in-loop",
                    path=fi.path,
                    line=call.lineno,
                    message=(
                        f"{fq}: {n or 'sync'} on a device value inside a "
                        "loop — one host<->device round trip per iteration"
                    ),
                    key=f"host-sync-in-loop:{fq}:{n}",
                    severity="warn",
                )
            )

    def _is_sync(self, call: ast.Call, device_vars: set) -> bool:
        f = call.func
        n = _attr_name(f)
        if n in ("item", "block_until_ready"):
            return True
        if n == "device_get":
            return True
        if n == "asarray" and isinstance(f, ast.Attribute):
            # np.asarray(x) syncs only when x is a device value; flag
            # just the locally-provable case to keep host-side numpy
            # assembly loops quiet.
            if call.args and isinstance(call.args[0], ast.Name):
                return call.args[0].id in device_vars
        return False
