"""Query-path distributed tracing — Span/Tracer with cross-node links.

Dapper-model tracing (low-overhead, always-on, sampled retention): every
query gets a trace; the last ``capacity`` finished traces are retained in
a ring buffer served as JSON by ``GET /debug/traces``.

A trace is a tree of :class:`Span` objects sharing one ``trace_id``.
Spans time with ``time.monotonic()`` and link parent→child two ways:

* in-process via a ``contextvars.ContextVar`` holding the active span —
  crossing threads works because the executor's pool captures the
  submitting context (``contextvars.copy_context``);
* across nodes via W3C-style headers: the coordinator's rpc span id
  travels as ``X-Trace-Id``/``X-Span-Id`` on the fan-out request, the
  remote handler continues the trace under that parent, and the remote's
  finished spans return in an ``X-Trace-Spans`` response header that the
  client absorbs back into the coordinator's open trace — so ONE trace
  on the coordinator covers parse, plan, local slice execution, and
  every remote node's leg.

``NOP_TRACER`` is the disabled implementation: components constructed
without a tracer (unit tests, embedders) pay one no-op method call per
span site.
"""

from __future__ import annotations

import contextvars
import json
import threading
import time
import uuid
from collections import deque

# Propagation headers (W3C trace-context shaped: 16-byte trace id,
# 8-byte span id, hex).
TRACE_HEADER = "X-Trace-Id"
SPAN_HEADER = "X-Span-Id"
SPANS_HEADER = "X-Trace-Spans"

# Bounds: spans retained per trace and spans exported in the response
# header — a pathological query cannot balloon memory or the header.
MAX_SPANS_PER_TRACE = 512
MAX_EXPORT_SPANS = 128

_current_span: "contextvars.ContextVar[Span | None]" = contextvars.ContextVar(
    "pilosa_current_span", default=None
)


def current_span() -> "Span | None":
    """The context-current span (None outside any trace) — lets layers
    without a Tracer handle (retry policy, clients) annotate the span
    they run under."""
    return _current_span.get()


def new_trace_id() -> str:
    return uuid.uuid4().hex  # 16 bytes hex


def new_span_id() -> str:
    return uuid.uuid4().hex[:16]  # 8 bytes hex


class Span:
    """One timed operation within a trace.

    Usable as a context manager (activates itself as the current span
    for the dynamic extent, finishes on exit, and records the exception
    type on error paths).
    """

    __slots__ = (
        "tracer",
        "name",
        "trace_id",
        "span_id",
        "parent_id",
        "start",
        "_t0",
        "duration_ms",
        "tags",
        "_token",
    )

    def __init__(self, tracer, name: str, trace_id: str, parent_id: str | None,
                 tags: dict | None = None):
        self.tracer = tracer
        self.name = name
        self.trace_id = trace_id
        self.span_id = new_span_id()
        self.parent_id = parent_id
        self.start = time.time()
        self._t0 = time.monotonic()
        self.duration_ms: float | None = None
        self.tags = dict(tags) if tags else {}
        self._token = None

    def annotate(self, **tags) -> "Span":
        self.tags.update(tags)
        return self

    def activate(self):
        """Make this the current span; returns a token for deactivate()."""
        return _current_span.set(self)

    def deactivate(self, token) -> None:
        _current_span.reset(token)

    def finish(self) -> None:
        if self.duration_ms is None:
            self.duration_ms = (time.monotonic() - self._t0) * 1000.0
            self.tracer._record(self)

    def __enter__(self) -> "Span":
        self._token = self.activate()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if self._token is not None:
            self.deactivate(self._token)
            self._token = None
        if exc_type is not None:
            self.tags.setdefault("error", exc_type.__name__)
        self.finish()

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "start": self.start,
            "duration_ms": round(self.duration_ms, 3)
            if self.duration_ms is not None
            else None,
            "tags": self.tags,
        }


class Tracer:
    """Collects spans into traces; retains finished traces in a ring."""

    def __init__(self, capacity: int = 64):
        self.capacity = max(1, int(capacity))
        self._mu = threading.Lock()
        # trace_id -> {"root": Span, "spans": [span dicts], "started": t}
        self._open: dict[str, dict] = {}
        self._ring: "deque[dict]" = deque(maxlen=self.capacity)
        # Spans that finished after their trace was finalized (debug aid).
        self.late_spans = 0

    # -- span creation --------------------------------------------------

    def current(self) -> Span | None:
        return _current_span.get()

    def start_trace(
        self,
        name: str,
        trace_id: str | None = None,
        parent_span_id: str | None = None,
        **tags,
    ) -> Span:
        """Open a trace root.  ``trace_id``/``parent_span_id`` continue a
        propagated trace (the remote leg of a fan-out); both None starts
        a fresh trace."""
        span = Span(self, name, trace_id or new_trace_id(), parent_span_id, tags)
        with self._mu:
            self._open[span.trace_id] = {"root": span, "spans": []}
        return span

    def span(self, name: str, parent: Span | None = None, **tags) -> Span:
        """A child of ``parent`` (default: the context-current span).
        Without any active trace the span still times and works as a
        context manager, but is never retained."""
        parent = parent or _current_span.get()
        if parent is None:
            return Span(self, name, new_trace_id(), None, tags)
        return Span(self, name, parent.trace_id, parent.span_id, tags)

    # -- recording ------------------------------------------------------

    def _record(self, span: Span) -> None:
        with self._mu:
            ent = self._open.get(span.trace_id)
            if ent is None:
                self.late_spans += 1
                return
            if ent["root"] is span:
                return  # the root records at finish_root
            if len(ent["spans"]) < MAX_SPANS_PER_TRACE:
                ent["spans"].append(span.to_dict())

    def absorb(self, payload: "str | dict") -> None:
        """Merge a remote node's exported spans (the ``X-Trace-Spans``
        response header) into the matching open trace."""
        try:
            if isinstance(payload, str):
                payload = json.loads(payload)
            trace_id = payload["trace_id"]
            spans = payload["spans"]
        except (ValueError, KeyError, TypeError):
            return
        with self._mu:
            ent = self._open.get(trace_id)
            if ent is None:
                self.late_spans += 1
                return
            room = MAX_SPANS_PER_TRACE - len(ent["spans"])
            ent["spans"].extend(
                s for s in spans[:room] if isinstance(s, dict)
            )

    def finish_root(self, root: Span) -> dict | None:
        """Finish the trace root, finalize the trace, retain it in the
        ring, and return the trace record."""
        if root.duration_ms is None:
            root.duration_ms = (time.monotonic() - root._t0) * 1000.0
        with self._mu:
            ent = self._open.pop(root.trace_id, None)
            if ent is None:
                return None
            record = {
                "trace_id": root.trace_id,
                "name": root.name,
                "start": root.start,
                "duration_ms": round(root.duration_ms, 3),
                "spans": [root.to_dict()] + ent["spans"],
            }
            self._ring.append(record)
            return record

    # -- consumption ----------------------------------------------------

    def traces(self, min_ms: float = 0.0) -> list[dict]:
        """Retained traces, most recent last; ``min_ms`` filters on the
        root duration."""
        with self._mu:
            out = list(self._ring)
        if min_ms > 0:
            out = [t for t in out if t["duration_ms"] >= min_ms]
        return out

    def remote_headers(self, span: Span) -> dict[str, str]:
        """Headers that continue ``span``'s trace on a remote node."""
        return {TRACE_HEADER: span.trace_id, SPAN_HEADER: span.span_id}

    @staticmethod
    def export_payload(record: dict) -> str:
        """Compact JSON for the ``X-Trace-Spans`` response header."""
        return json.dumps(
            {
                "trace_id": record["trace_id"],
                "spans": record["spans"][:MAX_EXPORT_SPANS],
            },
            separators=(",", ":"),
        )


def stage_breakdown(record: dict) -> dict[str, float]:
    """Total milliseconds per span name — the slow-query log's per-stage
    breakdown.  The root span is excluded (it IS the total)."""
    root_id = record["spans"][0]["span_id"] if record["spans"] else None
    out: dict[str, float] = {}
    for s in record["spans"]:
        if s["span_id"] == root_id:
            continue
        if s["duration_ms"] is not None:
            out[s["name"]] = round(out.get(s["name"], 0.0) + s["duration_ms"], 3)
    return out


class _NopSpan(Span):
    """Inert span: annotate/finish/context-manager are no-ops beyond
    context activation (children of a nop span are nop spans)."""

    def __init__(self):  # noqa: D107 — singleton, no tracer
        pass

    def annotate(self, **tags):
        return self

    def activate(self):
        return None

    def deactivate(self, token):
        pass

    def finish(self):
        pass

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        pass


NOP_SPAN = _NopSpan()


class NopTracer(Tracer):
    """Tracing disabled: every span site costs one method call."""

    def __init__(self):
        super().__init__(capacity=1)

    def start_trace(self, name, trace_id=None, parent_span_id=None, **tags):
        return NOP_SPAN

    def span(self, name, parent=None, **tags):
        return NOP_SPAN

    def absorb(self, payload):
        pass

    def finish_root(self, root):
        return None

    def traces(self, min_ms: float = 0.0):
        return []

    def remote_headers(self, span):
        return {}


NOP_TRACER = NopTracer()
