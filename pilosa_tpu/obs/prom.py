"""Prometheus text exposition of the Expvar store.

Renders an :meth:`ExpvarStatsClient.snapshot` dict (counts / gauges /
histograms keyed by tag-qualified names like ``setBit[frame:f,index:i]``)
in the Prometheus text format (version 0.0.4), served by ``GET /metrics``:

* counts      → ``pilosa_<name>_total`` counters
* gauges      → ``pilosa_<name>`` gauges
* histograms  → ``pilosa_<name>`` summaries (quantile series + ``_sum``
  and ``_count``); quantiles are the snapshot's interpolated
  percentiles over the bounded reservoir (a WINDOWED view), while
  ``_sum``/``_count`` come from the lifetime monotonic totals so
  ``rate()`` keeps working past 4096 observations
* hierarchical tags (``index:i``, ``frame:f``, ``view:standard``,
  ``slice:0``) → labels; a bare tag becomes ``tag="..."``.

Sets (string-valued) have no numeric representation and are skipped.
"""

from __future__ import annotations

import re

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

_NAME_OK = re.compile(r"[^a-zA-Z0-9_:]")
_LABEL_OK = re.compile(r"[^a-zA-Z0-9_]")

_QUANTILES = (("0.5", "p50"), ("0.9", "p90"), ("0.99", "p99"), ("0.999", "p999"))


def _metric_name(raw: str, prefix: str = "pilosa") -> str:
    name = _NAME_OK.sub("_", raw).strip("_")
    if not name:
        name = "unnamed"
    if name[0].isdigit():
        name = "_" + name
    return f"{prefix}_{name}"


def _label_name(raw: str) -> str:
    name = _LABEL_OK.sub("_", raw)
    if not name or name[0].isdigit():
        name = "_" + name
    return name


def _escape(value: str) -> str:
    return value.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def parse_key(key: str) -> tuple[str, dict[str, str]]:
    """``name[tag1,tag2]`` -> (name, labels).  Tags are ``k:v`` pairs
    (``index:i``); a tag without a colon maps to label ``tag``."""
    name, _, rest = key.partition("[")
    labels: dict[str, str] = {}
    if rest.endswith("]"):
        for tag in rest[:-1].split(","):
            if not tag:
                continue
            k, sep, v = tag.partition(":")
            if sep:
                labels[_label_name(k)] = v
            else:
                labels[_label_name("tag")] = tag
    return name, labels


def _fmt_labels(labels: dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{k}="{_escape(str(v))}"' for k, v in sorted(labels.items())
    )
    return "{" + inner + "}"


def _fmt_value(v) -> str:
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def render(snapshot: dict, extra_gauges: dict | None = None) -> str:
    """Snapshot -> exposition text.  ``extra_gauges`` are pre-named
    process metrics (uptime, threads) rendered without the key parsing."""
    # family name -> {"type": ..., "lines": [...]}; one # TYPE header per
    # family no matter how many label sets share the name.
    families: dict[str, dict] = {}

    def family(name: str, typ: str) -> list[str]:
        f = families.setdefault(name, {"type": typ, "lines": []})
        return f["lines"]

    for raw_key, value in sorted((snapshot.get("counts") or {}).items()):
        name, labels = parse_key(raw_key)
        fam = _metric_name(name) + "_total"
        family(fam, "counter").append(
            f"{fam}{_fmt_labels(labels)} {_fmt_value(value)}"
        )

    for raw_key, value in sorted((snapshot.get("gauges") or {}).items()):
        name, labels = parse_key(raw_key)
        fam = _metric_name(name)
        family(fam, "gauge").append(
            f"{fam}{_fmt_labels(labels)} {_fmt_value(value)}"
        )

    for raw_key, h in sorted((snapshot.get("histograms") or {}).items()):
        name, labels = parse_key(raw_key)
        fam = _metric_name(name)
        lines = family(fam, "summary")
        for q, pkey in _QUANTILES:
            if pkey in h:
                qlabels = dict(labels, quantile=q)
                lines.append(f"{fam}{_fmt_labels(qlabels)} {_fmt_value(h[pkey])}")
        # _sum/_count must be lifetime monotonic for rate() to work;
        # the snapshot carries them separately from the windowed
        # reservoir ("count"/"sum" vs "n"/"mean").  Fall back to the
        # reservoir view only for pre-upgrade snapshots.
        if "count" in h:
            lines.append(
                f"{fam}_sum{_fmt_labels(labels)} {_fmt_value(h.get('sum', 0.0))}"
            )
            lines.append(
                f"{fam}_count{_fmt_labels(labels)} {_fmt_value(h['count'])}"
            )
        elif "n" in h:
            mean = h.get("mean", 0.0)
            lines.append(
                f"{fam}_sum{_fmt_labels(labels)} {_fmt_value(mean * h['n'])}"
            )
            lines.append(f"{fam}_count{_fmt_labels(labels)} {_fmt_value(h['n'])}")

    for name, value in sorted((extra_gauges or {}).items()):
        fam = _metric_name(name)
        family(fam, "gauge").append(f"{fam} {_fmt_value(value)}")

    out: list[str] = []
    for fam in sorted(families):
        ent = families[fam]
        out.append(f"# TYPE {fam} {ent['type']}")
        out.extend(ent["lines"])
    return "\n".join(out) + ("\n" if out else "")
