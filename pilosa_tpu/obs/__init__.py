"""Observability: stats clients, hierarchical tags, latency histograms,
query-path distributed tracing, Prometheus exposition.

reference: stats.go (StatsClient interface + nop/expvar/multi impls),
statsd/statsd.go (DataDog dogstatsd client).  trace.py (Span/Tracer with
X-Trace-Id/X-Span-Id propagation) and prom.py (/metrics rendering) are
pilosa_tpu extensions.
"""

from pilosa_tpu.obs.stats import (
    ExpvarStatsClient,
    MultiStatsClient,
    NopStatsClient,
    StatsDClient,
    new_stats_client,
)
from pilosa_tpu.obs.trace import NOP_TRACER, NopTracer, Span, Tracer

__all__ = [
    "ExpvarStatsClient",
    "MultiStatsClient",
    "NOP_TRACER",
    "NopStatsClient",
    "NopTracer",
    "Span",
    "StatsDClient",
    "Tracer",
    "new_stats_client",
]
