"""StatsClient — metrics with hierarchical tags.

Interface parity with the reference (reference: stats.go:34-61):
``tags / with_tags / count / count_with_custom_tags / gauge / histogram /
set / timing``; tag propagation is hierarchical — holder tags
``index:<n>``, then ``frame:<n>``, ``view:<n>``, ``slice:<n>`` via
``with_tags`` (reference: holder.go:259, index.go:443, frame.go:438,
view.go:257).

Implementations: Nop (default), Expvar (in-memory snapshot served by
/debug/vars, reference: stats.go:78-150), StatsD (dogstatsd datagram
format over UDP, reference: statsd/statsd.go), Multi fan-out
(reference: stats.go:152-219).
"""

from __future__ import annotations

import socket
import threading
from collections import defaultdict


def union_string_slice(a: list[str], b: list[str]) -> list[str]:
    """Sorted union (reference: stats.go:222-247)."""
    return sorted(set(a) | set(b))


def _percentile(sorted_values: list[float], q: float) -> float:
    """Linear-interpolated quantile of SORTED ``sorted_values`` (numpy's
    default method): position ``q * (n - 1)`` interpolates between its
    neighbors, so small samples aren't biased the way plain index
    truncation is (``[1,2,3,4]`` p50 = 2.5, not 3)."""
    n = len(sorted_values)
    if n == 1:
        return sorted_values[0]
    pos = q * (n - 1)
    lo = int(pos)
    hi = min(lo + 1, n - 1)
    frac = pos - lo
    return sorted_values[lo] * (1.0 - frac) + sorted_values[hi] * frac


class NopStatsClient:
    """reference: stats.go:66-76"""

    def tags(self) -> list[str]:
        return []

    def with_tags(self, *tags: str) -> "NopStatsClient":
        return self

    def count(self, name: str, value: int = 1) -> None:
        pass

    def count_with_custom_tags(self, name: str, value: int, tags: list[str]) -> None:
        pass

    def gauge(self, name: str, value: float) -> None:
        pass

    def histogram(self, name: str, value: float) -> None:
        pass

    def set(self, name: str, value: str) -> None:
        pass

    def timing(self, name: str, value: float) -> None:
        pass

    def close(self) -> None:
        pass


class _ExpvarStore:
    """The shared mutable state behind one Expvar client family
    (``with_tags`` children share their parent's store).  A real class
    rather than a dict so the lock is a named attribute the
    concurrency analyzer (pilosa_tpu/analyze) can track."""

    __slots__ = ("lock", "counts", "gauges", "sets", "histograms",
                 "hist_totals")

    def __init__(self):
        self.lock = threading.Lock()
        self.counts = defaultdict(int)
        self.gauges: dict = {}
        self.sets: dict = {}
        self.histograms = defaultdict(list)
        # Lifetime monotonic [count, sum] per histogram key.  The
        # reservoir above is bounded at 4096 samples, so anything
        # derived from it slides; Prometheus ``rate()`` over ``_count``
        # and ``_sum`` needs monotonic lifetime totals.
        self.hist_totals = defaultdict(lambda: [0, 0.0])


class ExpvarStatsClient:
    """In-memory counters/gauges keyed by tag-qualified names, readable
    as one JSON snapshot from /debug/vars (reference: stats.go:78-150)."""

    def __init__(self, _store: _ExpvarStore | None = None,
                 _tags: list[str] | None = None):
        self._store = _store if _store is not None else _ExpvarStore()
        self._tags = _tags or []

    def _key(self, name: str, tags: list[str] | None = None) -> str:
        all_tags = union_string_slice(self._tags, tags or [])
        if all_tags:
            return f"{name}[{','.join(all_tags)}]"
        return name

    def tags(self) -> list[str]:
        return list(self._tags)

    def with_tags(self, *tags: str) -> "ExpvarStatsClient":
        return ExpvarStatsClient(
            self._store, union_string_slice(self._tags, list(tags))
        )

    def count(self, name: str, value: int = 1) -> None:
        with self._store.lock:
            self._store.counts[self._key(name)] += value

    def count_with_custom_tags(self, name: str, value: int, tags: list[str]) -> None:
        with self._store.lock:
            self._store.counts[self._key(name, tags)] += value

    def gauge(self, name: str, value: float) -> None:
        with self._store.lock:
            self._store.gauges[self._key(name)] = value

    def histogram(self, name: str, value: float) -> None:
        with self._store.lock:
            key = self._key(name)
            h = self._store.histograms[key]
            h.append(value)
            if len(h) > 4096:  # bound memory (percentiles are windowed)
                del h[: len(h) - 4096]
            tot = self._store.hist_totals[key]
            tot[0] += 1
            tot[1] += value

    def set(self, name: str, value: str) -> None:
        with self._store.lock:
            self._store.sets[self._key(name)] = value

    def timing(self, name: str, value: float) -> None:
        self.histogram(name, value)

    def close(self) -> None:
        pass

    def snapshot(self) -> dict:
        """For /debug/vars (and the /metrics Prometheus rendering)."""
        with self._store.lock:
            out: dict = {
                "counts": dict(self._store.counts),
                "gauges": dict(self._store.gauges),
                "sets": dict(self._store.sets),
            }
            hists = {}
            for k, values in self._store.histograms.items():
                if not values:
                    continue
                s = sorted(values)
                tot = self._store.hist_totals.get(k)
                hists[k] = {
                    # Windowed view (last <=4096 samples): min/max/mean
                    # and the percentiles.
                    "n": len(s),
                    "min": s[0],
                    "max": s[-1],
                    "mean": sum(s) / len(s),
                    "p50": _percentile(s, 0.5),
                    "p90": _percentile(s, 0.9),
                    "p99": _percentile(s, 0.99),
                    "p999": _percentile(s, 0.999),
                    # Lifetime monotonic totals (what _count/_sum in the
                    # Prometheus exposition must come from).
                    "count": tot[0] if tot else len(s),
                    "sum": tot[1] if tot else sum(s),
                }
            out["histograms"] = hists
            return out


class StatsDClient:
    """dogstatsd datagram client (reference: statsd/statsd.go:30-127):
    ``pilosa.<name>:<value>|<type>|#tag1,tag2`` over UDP; prefix
    ``pilosa.``, fire-and-forget."""

    PREFIX = "pilosa."
    # Datagram clamp: a metric+tags payload past this many bytes would
    # hit EMSGSIZE (or fragment) on typical MTUs; oversize datagrams
    # drop the tag suffix first, then truncate (dogstatsd servers skip
    # a malformed line; an EMSGSIZE loses it silently either way).
    MAX_PAYLOAD = 1432

    def __init__(self, host: str = "127.0.0.1:8125", _tags: list[str] | None = None):
        self.host = host
        self._tags = _tags or []
        addr, _, port = host.partition(":")
        self._addr = (addr or "127.0.0.1", int(port or 8125))
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)

    def _send(self, name: str, payload: str, tags: list[str] | None = None) -> None:
        all_tags = union_string_slice(self._tags, tags or [])
        base = f"{self.PREFIX}{name}:{payload}"
        msg = base
        if all_tags:
            msg += f"|#{','.join(all_tags)}"
        data = msg.encode()
        if len(data) > self.MAX_PAYLOAD:
            data = base.encode()
            if len(data) > self.MAX_PAYLOAD:
                # Truncate on a codepoint boundary: a blind byte slice
                # can cut a multi-byte UTF-8 sequence mid-rune, and a
                # malformed datagram is dropped wholesale by dogstatsd.
                cut = self.MAX_PAYLOAD
                while cut > 0 and (data[cut] & 0xC0) == 0x80:
                    cut -= 1
                data = data[:cut]
        try:
            self._sock.sendto(data, self._addr)
        except OSError:
            pass  # fire-and-forget

    def tags(self) -> list[str]:
        return list(self._tags)

    def with_tags(self, *tags: str) -> "StatsDClient":
        c = StatsDClient.__new__(StatsDClient)
        c.host = self.host
        c._tags = union_string_slice(self._tags, list(tags))
        c._addr = self._addr
        c._sock = self._sock
        return c

    def count(self, name: str, value: int = 1) -> None:
        self._send(name, f"{value}|c")

    def count_with_custom_tags(self, name: str, value: int, tags: list[str]) -> None:
        self._send(name, f"{value}|c", tags)

    def gauge(self, name: str, value: float) -> None:
        self._send(name, f"{value}|g")

    def histogram(self, name: str, value: float) -> None:
        self._send(name, f"{value}|h")

    def set(self, name: str, value: str) -> None:
        self._send(name, f"{value}|s")

    def timing(self, name: str, value: float) -> None:
        self._send(name, f"{value}|ms")

    def close(self) -> None:
        """Release the UDP socket.  with_tags children share the parent
        socket, so closing any one releases it for all."""
        self._sock.close()


class MultiStatsClient:
    """Fan-out to several clients (reference: stats.go:152-219)."""

    def __init__(self, clients: list):
        self.clients = list(clients)

    def tags(self) -> list[str]:
        # Union over ALL children, not just the first (parity with
        # reference stats.go MultiStatsClient.Tags).
        out: list[str] = []
        for c in self.clients:
            out = union_string_slice(out, c.tags())
        return out

    def with_tags(self, *tags: str) -> "MultiStatsClient":
        return MultiStatsClient([c.with_tags(*tags) for c in self.clients])

    def count(self, name: str, value: int = 1) -> None:
        for c in self.clients:
            c.count(name, value)

    def count_with_custom_tags(self, name: str, value: int, tags: list[str]) -> None:
        for c in self.clients:
            c.count_with_custom_tags(name, value, tags)

    def gauge(self, name: str, value: float) -> None:
        for c in self.clients:
            c.gauge(name, value)

    def histogram(self, name: str, value: float) -> None:
        for c in self.clients:
            c.histogram(name, value)

    def set(self, name: str, value: str) -> None:
        for c in self.clients:
            c.set(name, value)

    def timing(self, name: str, value: float) -> None:
        for c in self.clients:
            c.timing(name, value)

    def close(self) -> None:
        for c in self.clients:
            close = getattr(c, "close", None)
            if close is not None:
                close()

    def snapshot(self) -> dict:
        for c in self.clients:
            if hasattr(c, "snapshot"):
                return c.snapshot()
        return {}


def new_stats_client(service: str, host: str = ""):
    """reference: server/server.go:236-245"""
    if service in ("", "nop", "none"):
        return NopStatsClient()
    if service == "expvar":
        return ExpvarStatsClient()
    if service == "statsd":
        return StatsDClient(host or "127.0.0.1:8125")
    raise ValueError(f"unknown metrics service: {service!r}")
