"""Per-launch device telemetry and roofline accounting.

The production counterpart of bench.py's offline bandwidth figures
(ROADMAP 2/5): every device launch site — executor direct, coalescer
concat, fused interpreter, limb total-count (incl. the ICI collective),
the TopN scorer, and the numpy host fallback — records a
:class:`LaunchRecord` into a lock-light per-site accumulator, and the
derived per-site achieved GB/s is compared against the stream floor the
one-shot probe measured at server open (device/floorprobe.py).

Roofline model (Williams et al., CACM 2009): the bitmap kernels are
memory-bound, so "how fast could this go" is the stream floor and
"how fast does it go" is logical plane bytes streamed / device time.
``GET /debug/perf`` renders the table; ``exec.launch.gbps[site:*]`` /
``exec.launch.floorPct[site:*]`` / ``device.streamFloorGbps`` land on
/metrics as scrape-time gauges.

Discipline (Dapper-style always-on): ``record_launch`` must stay OFF
every launch path's critical section — per-site locks guard only plain
counter increments, never device work, stats emission, or allocation
beyond one small dict.  The tier-1 overhead guard
(tests/test_perf.py) asserts telemetry-on query p99 within 5% of
telemetry-off.

Also here: :class:`LatencyHistograms` — native fixed-bucket cumulative
Prometheus HISTOGRAM families (per admission class and per HTTP route,
``[obs] latency-buckets-ms``) with SLO burn-rate gauges against
``[obs] slo-ms`` / ``slo-objective``.  The Expvar reservoir summaries
stay for everything else; these families exist because bucketed
cumulative histograms aggregate across replicas and feed
``histogram_quantile()`` where summaries cannot.
"""

from __future__ import annotations

import bisect
import threading
import time
from collections import deque

WORD_BYTES = 4  # uint32 planes

# Rolling per-site launch-duration window (percentiles are a recent
# view, like the Expvar reservoir); lifetime byte/time counters are
# monotonic.
WINDOW = 512
# Recent launches retained for the /debug/perf slowest-launch table.
RECENT = 256
SLOWEST = 16

# Default latency buckets (ms): roughly log-spaced from sub-ms point
# reads to the 60 s query-timeout tail.
DEFAULT_BUCKETS_MS = (
    1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0,
    250.0, 500.0, 1000.0, 2500.0, 5000.0, 10000.0,
)

# SLO burn-rate window (seconds): the "fast burn" window alerting
# rules page on.  Kept short so a soak shows the burn move.
BURN_WINDOW_S = 300.0


def plane_bytes(rows: int, words: int) -> int:
    """Logical plane bytes streamed for ``rows`` slice-rows of
    ``words`` uint32 words each (slices x leaves x words geometry) —
    the roofline numerator.  Logical means PRE-padding: pad rows are
    bucketing overhead, not useful bytes."""
    return int(rows) * int(words) * WORD_BYTES


class LaunchRecord(dict):
    """One device launch: site, reduce kind, batch occupancy, logical
    bytes streamed, dispatch-vs-completion split, and the submitting
    query's trace id.  A dict subclass so /debug/perf serializes it
    as-is."""

    __slots__ = ()

    def __init__(
        self,
        site: str,
        *,
        reduce: str = "",
        queries: int = 1,
        rows: int = 0,
        n_bytes: int = 0,
        eff_bytes: int = 0,
        dispatch_ms: float = 0.0,
        total_ms: float = 0.0,
        trace_id: str = "",
    ):
        super().__init__(
            site=site,
            reduce=reduce,
            queries=int(queries),
            rows=int(rows),
            bytes=int(n_bytes),
            # Effective bytes actually read by the launch — smaller
            # than the logical geometry for compressed-container
            # launches; defaults to logical for dense launches.
            eff_bytes=int(eff_bytes) or int(n_bytes),
            dispatch_ms=round(float(dispatch_ms), 3),
            total_ms=round(float(total_ms), 3),
            trace_id=trace_id,
        )


class _Site:
    """One launch site's accumulator.  The lock is a LEAF: nothing is
    called while holding it."""

    __slots__ = (
        "lock", "launches", "queries", "rows", "n_bytes", "eff_bytes",
        "dispatch_ms", "total_ms", "window", "reduces",
    )

    def __init__(self):
        self.lock = threading.Lock()
        self.launches = 0
        self.queries = 0
        self.rows = 0
        self.n_bytes = 0
        self.eff_bytes = 0
        self.dispatch_ms = 0.0
        self.total_ms = 0.0
        self.window: deque = deque(maxlen=WINDOW)
        self.reduces: dict[str, int] = {}


def _percentile(sorted_values: list[float], q: float) -> float:
    n = len(sorted_values)
    if n == 1:
        return sorted_values[0]
    pos = q * (n - 1)
    lo = int(pos)
    hi = min(lo + 1, n - 1)
    frac = pos - lo
    return sorted_values[lo] * (1.0 - frac) + sorted_values[hi] * frac


class PerfRegistry:
    """Process-wide launch-telemetry registry (like device.pool(), the
    launch sites it instruments are process-global device state)."""

    def __init__(self, enabled: bool = True):
        self._mu = threading.Lock()  # sites map + recent ring + floor
        self._enabled = enabled
        self._floor_gbps = 0.0
        self._sites: dict[str, _Site] = {}
        self._recent: deque = deque(maxlen=RECENT)

    # -- configuration -------------------------------------------------

    def configure(self, enabled: bool | None = None) -> None:
        if enabled is not None:
            with self._mu:
                self._enabled = bool(enabled)

    @property
    def enabled(self) -> bool:
        return self._enabled

    def set_floor(self, gbps: float) -> None:
        with self._mu:
            self._floor_gbps = float(gbps)

    def floor_gbps(self) -> float:
        return self._floor_gbps

    def reset(self) -> None:
        """Drop accumulated launches (tests/bench tiers)."""
        with self._mu:
            self._sites = {}
            self._recent = deque(maxlen=RECENT)

    # -- hot path ------------------------------------------------------

    def record_launch(
        self,
        site: str,
        *,
        reduce: str = "",
        queries: int = 1,
        rows: int = 0,
        n_bytes: int = 0,
        eff_bytes: int = 0,
        dispatch_ms: float = 0.0,
        total_ms: float = 0.0,
        trace_id: str = "",
    ) -> None:
        if not self._enabled:
            return
        # Dense launches read exactly their logical geometry; only the
        # compressed-container sites pass a smaller eff_bytes.
        eff = eff_bytes or n_bytes
        st = self._sites.get(site)
        if st is None:
            with self._mu:
                st = self._sites.setdefault(site, _Site())
        with st.lock:
            st.launches += 1
            st.queries += queries
            st.rows += rows
            st.n_bytes += n_bytes
            st.eff_bytes += eff
            st.dispatch_ms += dispatch_ms
            st.total_ms += total_ms
            st.window.append(total_ms)
            if reduce:
                st.reduces[reduce] = st.reduces.get(reduce, 0) + 1
        # Raw tuple, not a LaunchRecord: the dict (with its casts and
        # rounding) is built lazily at snapshot time — the record path
        # runs on launch worker threads whose latency serializes
        # straight into query time.
        with self._mu:
            self._recent.append(
                (site, reduce, queries, rows, n_bytes,
                 dispatch_ms, total_ms, trace_id, eff)
            )

    # -- derived views -------------------------------------------------

    def snapshot(self) -> dict:
        """The /debug/perf document: per-site roofline table + the
        slowest recent launches (with trace ids) + the probed floor."""
        with self._mu:
            floor = self._floor_gbps
            enabled = self._enabled
            sites = list(self._sites.items())
            recent = list(self._recent)
        table: dict[str, dict] = {}
        for name, st in sites:
            with st.lock:
                launches = st.launches
                queries = st.queries
                rows = st.rows
                n_bytes = st.n_bytes
                eff_bytes = st.eff_bytes
                dispatch_ms = st.dispatch_ms
                total_ms = st.total_ms
                window = sorted(st.window)
                reduces = dict(st.reduces)
            device_s = total_ms / 1e3
            gbps = (n_bytes / 1e9 / device_s) if device_s > 0 else 0.0
            eff_gbps = (eff_bytes / 1e9 / device_s) if device_s > 0 else 0.0
            row = {
                "launches": launches,
                "queries": queries,
                "rows": rows,
                "bytes": n_bytes,
                "eff_bytes": eff_bytes,
                "occupancy": round(queries / launches, 2) if launches else 0.0,
                "dispatch_ms": round(dispatch_ms, 3),
                "device_ms": round(total_ms, 3),
                "gbps": round(gbps, 3),
                "eff_gbps": round(eff_gbps, 3),
                "reduces": reduces,
            }
            if floor > 0:
                # %-of-floor from EFFECTIVE bytes: a compressed launch
                # reading 1% of its logical geometry must not claim the
                # logical GB/s against the stream floor.  Dense sites
                # (eff == logical) are unchanged.
                row["floor_pct"] = round(100.0 * eff_gbps / floor, 1)
            if window:
                row["p50_ms"] = round(_percentile(window, 0.5), 3)
                row["p99_ms"] = round(_percentile(window, 0.99), 3)
            table[name] = row
        slowest = [
            LaunchRecord(
                t[0], reduce=t[1], queries=t[2], rows=t[3],
                n_bytes=t[4], dispatch_ms=t[5], total_ms=t[6],
                trace_id=t[7], eff_bytes=t[8],
            )
            for t in sorted(recent, key=lambda t: t[6], reverse=True)[:SLOWEST]
        ]
        return {
            "enabled": enabled,
            "floor_gbps": round(floor, 3),
            "sites": table,
            "slowest": slowest,
        }

    def gauges(self) -> dict[str, float]:
        """Scrape-time gauges for /metrics (injected like the
        program-cache gauges, so they render without a stats
        backend)."""
        snap = self.snapshot()
        out: dict[str, float] = {}
        if snap["floor_gbps"] > 0:
            out["device.streamFloorGbps"] = snap["floor_gbps"]
        for site, row in snap["sites"].items():
            out[f"exec.launch.gbps[site:{site}]"] = row["gbps"]
            out[f"exec.launch.effGbps[site:{site}]"] = row["eff_gbps"]
            if "floor_pct" in row:
                out[f"exec.launch.floorPct[site:{site}]"] = row["floor_pct"]
            out[f"exec.launch.launches[site:{site}]"] = row["launches"]
            out[f"exec.launch.bytes[site:{site}]"] = row["bytes"]
            out[f"exec.launch.effBytes[site:{site}]"] = row["eff_bytes"]
        return out


_REGISTRY = PerfRegistry()


def registry() -> PerfRegistry:
    return _REGISTRY


def enabled() -> bool:
    """Cheap pre-flight for the launch sites: record_launch() already
    no-ops when disabled, but the CALLER builds its argument dict
    (plane-byte geometry, np.prod over batch shapes) before the call —
    gating on this keeps telemetry-off truly free on the hot path."""
    return _REGISTRY._enabled


def record_launch(site: str, **kw) -> None:
    """Module-level shorthand the launch sites call."""
    _REGISTRY.record_launch(site, **kw)


def current_trace_id() -> str:
    """Trace id of the caller's active span ("" outside a trace) — for
    launch sites running on the submitting query's thread."""
    from pilosa_tpu.obs import trace

    sp = trace.current_span()
    return getattr(sp, "trace_id", "") or "" if sp is not None else ""


# ---------------------------------------------------------------------------
# native Prometheus histogram families + SLO burn rate
# ---------------------------------------------------------------------------


class _Series:
    __slots__ = ("counts", "sum", "count", "over_slo", "burn", "burn_t")

    def __init__(self, n_buckets: int):
        self.counts = [0] * (n_buckets + 1)  # +Inf tail bucket
        self.sum = 0.0
        self.count = 0
        self.over_slo = 0
        # (monotonic, cumulative count, cumulative over-slo) ring for
        # the windowed burn rate; appended at most ~1/s.
        self.burn: deque = deque(maxlen=int(BURN_WINDOW_S) + 8)
        self.burn_t = 0.0


class LatencyHistograms:
    """Fixed-bucket cumulative latency histograms, rendered as native
    Prometheus ``histogram`` families (``_bucket{le=}``/``_sum``/
    ``_count``) — NOT reservoir summaries: bucket counts are lifetime
    monotonic, so ``rate()``/``histogram_quantile()`` work across
    scrapes and replicas.

    Two families: ``pilosa_query_latency_ms{class=...}`` per admission
    class and ``pilosa_http_latency_ms{method=...,path=...}`` per HTTP
    route template.  With ``slo_ms > 0``, query observations over the
    target count as SLO errors and the windowed burn rate
    (error rate / error budget over the last 5 min) renders as
    ``pilosa_obs_slo_burn_rate{class=...}``."""

    def __init__(
        self,
        buckets_ms=DEFAULT_BUCKETS_MS,
        slo_ms: float = 0.0,
        slo_objective: float = 0.999,
    ):
        bl = sorted(float(b) for b in (buckets_ms or DEFAULT_BUCKETS_MS))
        if not bl:
            bl = list(DEFAULT_BUCKETS_MS)
        self.buckets = tuple(bl)
        self.slo_ms = float(slo_ms)
        self.slo_objective = float(slo_objective)
        self._mu = threading.Lock()  # leaf lock: plain increments only
        # family -> {labels tuple -> _Series}
        self._fams: dict[str, dict[tuple, _Series]] = {
            "query": {}, "http": {}, "tenant": {},
        }

    # -- hot path ------------------------------------------------------

    def observe_query(self, cls: str, ms: float, tenant: str = "") -> None:
        self._observe("query", (("class", cls),), ms)
        if tenant:
            # Separate family, not an extra label on "query": the
            # per-class series (and its SLO burn math) stays exactly
            # what single-tenant dashboards already chart, while
            # tenants get their own histogram + SLO series.
            self._observe(
                "tenant", (("class", cls), ("tenant", tenant)), ms
            )

    def observe_http(self, method: str, path: str, ms: float) -> None:
        self._observe("http", (("method", method), ("path", path)), ms)

    def _observe(self, family: str, labels: tuple, ms: float) -> None:
        i = bisect.bisect_left(self.buckets, ms)
        now = time.monotonic()
        with self._mu:
            fam = self._fams[family]
            s = fam.get(labels)
            if s is None:
                s = fam[labels] = _Series(len(self.buckets))
            if family in ("query", "tenant") and self.slo_ms > 0:
                # Checkpoint the totals BEFORE folding in this sample:
                # the entry marks the window boundary, and the sample
                # itself belongs inside the window.
                if now - s.burn_t >= 1.0:
                    s.burn.append((now, s.count, s.over_slo))
                    s.burn_t = now
                if ms > self.slo_ms:
                    s.over_slo += 1
            s.counts[i] += 1
            s.sum += ms
            s.count += 1

    # -- exposition ----------------------------------------------------

    def _burn(self, s: _Series, now: float) -> tuple[float, float]:
        """(windowed error rate, burn rate) over the last BURN_WINDOW_S."""
        base_count, base_over = 0, 0
        for t, c, o in s.burn:
            if now - t <= BURN_WINDOW_S:
                base_count, base_over = c, o
                break
        d_count = s.count - base_count
        d_over = s.over_slo - base_over
        if d_count <= 0:
            return 0.0, 0.0
        err = d_over / d_count
        budget = 1.0 - self.slo_objective
        return err, (err / budget) if budget > 0 else 0.0

    def render(self) -> str:
        """Exposition text block appended to /metrics (one ``# TYPE``
        per family; cumulative ``le`` buckets per the text-format
        histogram contract)."""
        from pilosa_tpu.obs.prom import _escape, _fmt_value

        with self._mu:
            snap = {
                fam: {
                    labels: (list(s.counts), s.sum, s.count, s.over_slo,
                             list(s.burn))
                    for labels, s in series.items()
                }
                for fam, series in self._fams.items()
            }
        now = time.monotonic()
        out: list[str] = []
        names = {"query": "pilosa_query_latency_ms",
                 "http": "pilosa_http_latency_ms",
                 "tenant": "pilosa_tenant_query_latency_ms"}
        for fam in ("query", "http", "tenant"):
            series = snap[fam]
            if not series:
                continue
            name = names[fam]
            out.append(f"# TYPE {name} histogram")
            for labels in sorted(series):
                counts, total, count, _over, _burn = series[labels]
                lbl = ",".join(
                    f'{k}="{_escape(str(v))}"' for k, v in labels
                )
                cum = 0
                for b, c in zip(self.buckets, counts):
                    cum += c
                    le = _fmt_value(b)
                    out.append(
                        f'{name}_bucket{{{lbl},le="{le}"}} {cum}'
                    )
                cum += counts[-1]
                out.append(f'{name}_bucket{{{lbl},le="+Inf"}} {cum}')
                out.append(f"{name}_sum{{{lbl}}} {_fmt_value(total)}")
                out.append(f"{name}_count{{{lbl}}} {count}")
        if self.slo_ms > 0 and snap["query"]:
            out.append("# TYPE pilosa_obs_slo_target_ms gauge")
            out.append(f"pilosa_obs_slo_target_ms {_fmt_value(self.slo_ms)}")
            out.append("# TYPE pilosa_obs_slo_objective gauge")
            out.append(
                f"pilosa_obs_slo_objective {_fmt_value(self.slo_objective)}"
            )
            err_lines: list[str] = []
            burn_lines: list[str] = []
            slo_series = [("query", ls) for ls in sorted(snap["query"])]
            slo_series += [("tenant", ls) for ls in sorted(snap["tenant"])]
            for fam, labels in slo_series:
                counts, total, count, over, burn = snap[fam][labels]
                s = _Series(len(self.buckets))
                s.count, s.over_slo = count, over
                s.burn = deque(burn)
                err, rate = self._burn(s, now)
                lbl = ",".join(
                    f'{k}="{_escape(str(v))}"' for k, v in labels
                )
                err_lines.append(
                    f"pilosa_obs_slo_error_rate{{{lbl}}} {_fmt_value(round(err, 6))}"
                )
                burn_lines.append(
                    f"pilosa_obs_slo_burn_rate{{{lbl}}} {_fmt_value(round(rate, 4))}"
                )
            out.append("# TYPE pilosa_obs_slo_error_rate gauge")
            out.extend(err_lines)
            out.append("# TYPE pilosa_obs_slo_burn_rate gauge")
            out.extend(burn_lines)
        return "\n".join(out) + ("\n" if out else "")
