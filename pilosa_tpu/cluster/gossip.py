"""Gossip membership — UDP gossip NodeSet + broadcaster.

The counterpart of the reference's memberlist-based gossip backend
(reference: gossip/gossip.go:31-222 on hashicorp/memberlist), built on a
small UDP protocol instead of an external library:

  JOIN      → seed replies JOIN-ACK with its member list
  PING      → periodic probe to a random member, piggybacking the local
              member list and (optionally) serialized node state; the
              receiver merges both and replies ACK
  USER      → application messages (the 5 schema broadcast messages,
              type-byte envelope from cluster/broadcast.py)

send_sync delivers a USER datagram to every live member and blocks
until each peer ACKs it, retrying with backoff and raising on peers
that never confirm — the UDP equivalent of the reference's reliable
errgroup-TCP SendSync with error propagation (reference:
gossip.go:124-149).  Receivers dedup message ids so retries stay
exactly-once.  send_async sends to ``gossip_fanout`` random members
and relies on periodic exchange for convergence (reference:
TransmitLimitedQueue, gossip.go:152-164).
Liveness is SWIM-shaped, like memberlist's: a member silent past
``suspect_after`` becomes SUSPECT — still live — and triggers one more
direct PING plus ``indirect_probes`` PING-REQ messages through random
third parties (relay pings the target; the target's ACK at the relay
produces an IND-ACK back to the requester, refreshing the suspect
without direct contact).  Only continued silence on BOTH paths for
another ``suspect_after`` confirms DOWN, so an asymmetric partition
(A↔B blocked, both reach C) never flaps placement (reference surface:
memberlist indirect probing + NotifyLeave → node state DOWN,
gossip/gossip.go:31-45, cluster.go:161-173).

State sync piggybacks a ``state_provider()`` blob on PING/ACK and feeds
received blobs to ``state_merger(blob)`` — the server wires these to
LocalStatus/HandleRemoteStatus so schemas replicate like the
reference's LocalState/MergeRemoteState (reference: gossip.go:191-222,
server.go:382-412).  Small blobs inline in the datagram; blobs too big
for one UDP packet travel as a digest instead, and a receiver that
hasn't merged that digest pulls the state through a chunked
STATE-REQ/STATE-CHUNK exchange — the UDP analog of memberlist's TCP
push/pull state transfer (reference: gossip.go:191-222), so a large
schema can never silently stop syncing at the datagram size limit.
"""

from __future__ import annotations

import base64
import hashlib
import itertools
import json
import random
import socket
import threading
import time
import uuid
from collections import OrderedDict

from pilosa_tpu.testing import faults

# State blobs up to this many raw bytes inline in PING/ACK datagrams;
# larger ones are advertised by digest and fetched chunked (a single UDP
# datagram tops out at ~65507 bytes and base64 inflates 4/3).
INLINE_STATE_MAX = 16 * 1024
# Raw bytes per STATE-CHUNK datagram (b64 -> ~44 KB on the wire).
STATE_CHUNK_SIZE = 32 * 1024
# Partial chunk assemblies are dropped after this long.
_ASSEMBLY_TTL = 30.0
# Hot-slice piggyback caps: slices announced per index per datagram and
# how long a peer's announcement stays fresh enough to steer staging.
HOT_SLICES_MAX = 32
HOT_TTL_S = 120.0
# Blobs larger than this many chunks skip UDP and stream over the
# peer's HTTP listener (the analog of memberlist's TCP push/pull,
# reference: gossip/gossip.go:191-222): a large schema under sustained
# datagram loss would otherwise re-spray the whole chunk set per ping.
STREAM_STATE_CHUNKS = 8
_STREAM_TIMEOUT_S = 10.0
# UDP transfer attempts (REQs sent / assemblies expired) per digest
# before the stream fallback takes over.
_UDP_STATE_MAX_ATTEMPTS = 3
# Failed HTTP streams per digest before falling back to UDP chunking
# even for large blobs (a peer reachable over UDP but not HTTP must
# still converge).  When BOTH paths exhaust a round, the counters reset
# and the alternation starts over.
_STREAM_MAX_FAILURES = 2


def gossip_port_for(host: str, offset: int = 1000) -> int:
    """Default gossip port: HTTP port + offset."""
    _, _, port = host.partition(":")
    return int(port or 10101) + offset


class GossipNodeSet:
    """NodeSet + Broadcaster + BroadcastReceiver in one object, like the
    reference's GossipNodeSet (reference: gossip/gossip.go:31-45)."""

    def __init__(
        self,
        host: str,
        bind: str = "",
        seed: str = "",
        gossip_interval: float = 1.0,
        suspect_after: float = 5.0,
        gossip_fanout: int = 3,
        state_provider=None,
        state_merger=None,
        state_fetcher=None,
        hot_provider=None,
        health_provider=None,
        logger=None,
        stats=None,
        ack_timeout: float = 0.25,
        stream_timeout: float = _STREAM_TIMEOUT_S,
    ):
        self.host = host  # the node's HTTP host:port (cluster identity)
        if bind:
            addr, _, port = bind.partition(":")
            self.bind = (addr or "0.0.0.0", int(port))
        else:
            # Listen on all interfaces; peers must be able to reach us
            # cross-machine.
            self.bind = ("0.0.0.0", gossip_port_for(host))
        # Address advertised in join/ping envelopes: the node's public
        # hostname (from its HTTP identity) + the gossip port — never
        # the wildcard/loopback bind address.
        adv_host = host.partition(":")[0] or "127.0.0.1"
        self.advertise = (adv_host, self.bind[1])
        self.seed = seed  # seed gossip addr "a.b.c.d:port"
        self.gossip_interval = gossip_interval
        self.suspect_after = suspect_after
        self.gossip_fanout = gossip_fanout
        self.state_provider = state_provider
        self.state_merger = state_merger
        # Hot-slice piggyback: ``hot_provider() -> {index: [slice,...]}``
        # rides every PING/ACK (capped, see HOT_SLICES_MAX), announcing
        # which slices this node is actually serving queries over right
        # now.  Receivers keep the per-peer sets; a restarting node
        # reads the union (``remote_hot_slices``) to stage its hottest
        # fragments FIRST (core/holder.stage_device_mirrors).
        self.hot_provider = hot_provider
        self._hot_remote: dict[str, tuple[float, dict]] = {}
        # Device-health piggyback: ``health_provider() -> bool``
        # (degraded = accelerator quarantined, node serving from host
        # planes) rides every PING/ACK; receivers keep the per-peer
        # flag and invoke ``on_peer_health(host, degraded)`` so the
        # server can deprioritize degraded replicas in routing
        # (Cluster.note_degraded).
        self.health_provider = health_provider
        self.on_peer_health = None
        self._health_remote: dict[str, bool] = {}
        # Stream fallback: fetch a peer's whole state blob over its
        # HTTP listener (GET /state) when UDP chunking is the wrong
        # tool — injectable for tests.
        self.state_fetcher = state_fetcher or self._http_state_fetch
        self.logger = logger or (lambda m: None)
        # Datagram traffic counters (gossip.sent/recv + bytes); Nop
        # unless the server wires a real stats client.
        from pilosa_tpu.obs.stats import NopStatsClient

        self.stats = stats or NopStatsClient()

        self._handler = None  # BroadcastHandler (the server)
        self._sock: socket.socket | None = None
        self._closing = threading.Event()
        self._threads: list[threading.Thread] = []
        self._mu = threading.Lock()
        # member -> {addr: (ip, port), last_seen: float,
        #            state: UP|SUSPECT|DOWN}.  SUSPECT is SWIM's middle
        # state: direct pings went unanswered, indirect probes through
        # third parties are in flight, and the member still counts as
        # live until they too fail (memberlist semantics behind
        # reference: gossip/gossip.go:31-45).
        self._members: dict[str, dict] = {}
        # SWIM ping-req relay bookkeeping: suspect host -> {requester
        # gossip addr: deadline} to answer with ind-ack when the suspect
        # acks one of OUR pings.  Keyed by requester so repeated
        # ping-reqs from the same suspecting node refresh one entry
        # instead of accumulating an ind-ack burst.
        self._relay_pending: dict[str, dict[tuple, float]] = {}
        # Indirect probes to issue per suspect per tick.
        self.indirect_probes = 2
        self.on_membership_change = None  # callback(list[(host, state)])
        # Reliable send_sync machinery: per-message ack events on the
        # sender, an id-dedup LRU on the receiver (retries stay
        # exactly-once).  Ids carry a per-process random prefix so a
        # restarted node's fresh counter can never collide with ids a
        # peer remembers from the previous incarnation.
        self._msg_ids = itertools.count()
        self._msg_prefix = uuid.uuid4().hex[:12]
        self._ack_events: dict[str, threading.Event] = {}
        self._seen_user: OrderedDict[str, float] = OrderedDict()
        self.sync_retries = 5
        # First ACK wait (doubles per retry) and the HTTP state-stream
        # fallback timeout — [gossip] ack-timeout-ms / stream-timeout-ms
        # config keys (defaults preserve the former constants).
        self.ack_timeout = ack_timeout
        self.stream_timeout = stream_timeout
        # Chunked state transfer: digests already merged (content-keyed
        # LRU — a digest seen from any peer needs no re-fetch) and
        # in-progress chunk assemblies keyed by (sender, digest).
        self._merged_digests: OrderedDict[str, float] = OrderedDict()
        self._assemblies: dict[tuple[str, str], dict] = {}
        # digest -> UDP transfer attempts (STATE-REQs sent + timed-out
        # assemblies); past _UDP_STATE_MAX_ATTEMPTS the digest flips to
        # the HTTP stream fallback.  Counting REQs (not just expired
        # assemblies) catches TOTAL chunk loss, where no assembly ever
        # forms.  When a digest exhausts BOTH paths' budgets, the offer
        # handler resets both counters and the alternation starts over
        # — neither path can permanently wedge the other.
        self._udp_state_attempts: OrderedDict[str, int] = OrderedDict()
        self._stream_failures: OrderedDict[str, int] = OrderedDict()
        self._streams_in_flight: set[str] = set()

    # ------------------------------------------------------------------
    # NodeSet
    # ------------------------------------------------------------------

    def nodes(self) -> list[str]:
        """Live members only — presence here means UP (the
        broadcast.NodeSet contract consumed by Cluster.node_states).
        SUSPECT members are still live: SWIM keeps a member until
        indirect probes through third parties also fail."""
        with self._mu:
            return sorted(
                h for h, m in self._members.items() if m["state"] != "DOWN"
            )

    def member_states(self) -> dict[str, str]:
        with self._mu:
            return {h: m["state"] for h, m in self._members.items()}

    def open(self) -> None:
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        # Chunked state transfers burst several ~44 KB datagrams; the
        # default rcvbuf (~208 KB on Linux) would shed most of a large
        # blob.  Best-effort — the kernel clamps to net.core.rmem_max.
        try:
            self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, 4 << 20)
        except OSError:
            pass
        self._sock.bind(self.bind)
        self._sock.settimeout(0.2)
        self.advertise = (self.advertise[0], self.bind[1])
        self._register(self.host, self.advertise)
        for name, fn in (("gossip-rx", self._rx_loop), ("gossip-tick", self._tick_loop)):
            t = threading.Thread(target=fn, daemon=True, name=f"{name}:{self.host}")
            t.start()
            self._threads.append(t)
        if self.seed:
            # Best-effort: a join datagram lost to the network is
            # re-sent by the tick loop for as long as this node knows
            # only itself (memberlist likewise retries joins).
            self._send_logged(
                _parse_addr(self.seed),
                {"t": "join", "from": self.host, "gaddr": _fmt_addr(self.advertise)},
            )

    def close(self) -> None:
        self._closing.set()
        if self._sock is not None:
            self._sock.close()

    # ------------------------------------------------------------------
    # Broadcaster
    # ------------------------------------------------------------------

    def send_sync(self, msg) -> None:
        """Deliver ``msg`` to every live member, blocking until each one
        ACKs (retry with backoff); raises listing the peers that never
        confirmed — reliable like the reference's TCP SendSync
        (reference: gossip.go:124-149)."""
        from pilosa_tpu.cluster.broadcast import marshal_message

        payload = base64.b64encode(marshal_message(msg)).decode()
        errors: list[str] = []
        errors_mu = threading.Lock()

        def deliver(host: str, member: dict) -> None:
            mid = f"{self._msg_prefix}/{next(self._msg_ids)}"
            ev = threading.Event()
            with self._mu:
                self._ack_events[mid] = ev
            try:
                timeout = self.ack_timeout
                for _ in range(self.sync_retries):
                    try:
                        self._send(
                            member["addr"],
                            {
                                "t": "user",
                                "from": self.host,
                                "p": payload,
                                "id": mid,
                            },
                        )
                    except OSError as e:
                        with errors_mu:
                            errors.append(f"{host}: {e}")
                        return
                    if ev.wait(timeout):
                        return
                    timeout *= 2
                with errors_mu:
                    errors.append(f"{host}: no ack after {self.sync_retries} tries")
            finally:
                with self._mu:
                    self._ack_events.pop(mid, None)

        # Concurrent fan-out, like the reference's errgroup SendSync
        # (reference: gossip.go:124-149) — total wall time is one peer's
        # retry budget, not the sum over unresponsive peers.
        threads = []
        for host, member in self._snapshot().items():
            if host == self.host or member["state"] == "DOWN":
                continue
            t = threading.Thread(target=deliver, args=(host, member), daemon=True)
            t.start()
            threads.append(t)
        for t in threads:
            t.join()
        if errors:
            raise RuntimeError("; ".join(sorted(errors)))

    def send_async(self, msg) -> None:
        from pilosa_tpu.cluster.broadcast import marshal_message

        payload = base64.b64encode(marshal_message(msg)).decode()
        peers = [
            m
            for h, m in self._snapshot().items()
            if h != self.host and m["state"] != "DOWN"
        ]
        random.shuffle(peers)
        for member in peers[: self.gossip_fanout]:
            try:
                self._send(
                    member["addr"], {"t": "user", "from": self.host, "p": payload}
                )
            except OSError:
                pass

    # ------------------------------------------------------------------
    # BroadcastReceiver
    # ------------------------------------------------------------------

    def start(self, handler) -> None:
        self._handler = handler

    # ------------------------------------------------------------------
    # protocol internals
    # ------------------------------------------------------------------

    def _snapshot(self) -> dict[str, dict]:
        with self._mu:
            return {h: dict(m) for h, m in self._members.items()}

    def _register(self, host: str, addr, age_s: float = 0.0) -> None:
        """Record a liveness report for ``host``.  ``age_s`` is how old
        the report is: 0 for direct contact (a datagram from the member
        itself), the reporter's time-since-last-heard for third-party
        vouches (_merge_members).  last_seen only moves FORWARD to
        ``now - age_s`` — a stale vouch can never refresh a member past
        fresher local evidence, so a dead member's silence accumulates
        cluster-wide instead of peers mutually resurrecting it with
        stale 'UP' reports forever (the false-ALIVE dual of a
        false-DOWN storm; caught by the churn soak)."""
        now = time.monotonic()
        seen = now - max(age_s, 0.0)
        changed = False
        with self._mu:
            m = self._members.get(host)
            if m is None:
                fresh = age_s <= self.suspect_after
                self._members[host] = {
                    "addr": tuple(addr),
                    "last_seen": seen,
                    # A member discovered through an already-stale vouch
                    # starts SUSPECT: it must prove liveness within a
                    # probe window rather than being presumed UP.
                    "state": "UP" if fresh else "SUSPECT",
                }
                changed = fresh
            else:
                m["addr"] = tuple(addr)
                if seen > m["last_seen"]:
                    m["last_seen"] = seen
                    if (
                        m["state"] != "UP"
                        and now - m["last_seen"] <= self.suspect_after
                    ):
                        # Only DOWN->UP is externally visible: SUSPECT
                        # collapses to UP at the _notify boundary, so a
                        # SUSPECT->UP refresh must not fire a spurious
                        # membership callback every probe cycle.
                        changed = m["state"] == "DOWN"
                        m["state"] = "UP"
                        m.pop("suspect_since", None)
        if changed:
            self._notify()

    def _notify(self) -> None:
        if self.on_membership_change is not None:
            # SUSPECT is internal to the SWIM protocol; the NodeSet
            # contract (and the reference's status surface) knows only
            # UP/DOWN, and a suspected member is still UP.
            states = {
                h: ("UP" if s != "DOWN" else "DOWN")
                for h, s in self.member_states().items()
            }
            try:
                self.on_membership_change(sorted(states.items()))
            except Exception as e:  # noqa: BLE001
                self.logger(f"membership callback error: {e}")

    def _send(self, addr, obj: dict) -> None:
        if self._sock is not None:
            # Chaos hook (testing/faults.py): the datagram-send
            # boundary.  ``mode=drop``/``error`` with seeded ``prob``
            # injects deterministic datagram loss per SENDER (host =
            # this node's identity, path = the message type) — the
            # churn-soak's lossy network.
            faults.check("gossip.send", host=self.host, path=obj.get("t"))
            data = json.dumps(obj).encode()
            self._sock.sendto(data, tuple(addr))
            self.stats.count("gossip.sent")
            self.stats.count("gossip.sentBytes", len(data))

    def _send_logged(self, addr, obj: dict) -> None:
        """Best-effort send: failures are LOGGED, never silently dropped
        — a send that starts failing (oversized datagram, unreachable
        peer) must leave a trace (VERDICT r2: a swallowed EMSGSIZE made
        schema sync stop with no log)."""
        try:
            self._send(addr, obj)
        except OSError as e:
            self.logger(
                f"gossip send {obj.get('t')} to {_fmt_addr(addr)} failed: {e}"
            )

    def _member_list(self) -> list[dict]:
        now = time.monotonic()
        return [
            {
                "host": h,
                "gaddr": _fmt_addr(m["addr"]),
                "state": m["state"],
                # Age of this liveness report: receivers refresh
                # last_seen to (their now - age), never backwards.
                "age": round(now - m["last_seen"], 3),
            }
            for h, m in self._snapshot().items()
        ]

    def _merge_members(self, members: list[dict]) -> None:
        """Adopt third-party liveness reports: a peer vouching UP for a
        member refreshes its last_seen BY THE REPORT'S AGE, so liveness
        scales with cluster size (memberlist-style indirect
        confirmation) while a dead member's growing silence still
        accumulates everywhere — stale vouches cannot keep a corpse
        alive."""
        for m in members:
            if m.get("state") == "UP" and m["host"] != self.host:
                try:
                    age = max(float(m.get("age", 0.0)), 0.0)
                except (TypeError, ValueError):
                    age = 0.0
                self._register(m["host"], _parse_addr(m["gaddr"]), age_s=age)

    def _rx_loop(self) -> None:
        while not self._closing.is_set():
            try:
                data, addr = self._sock.recvfrom(65536)
            except socket.timeout:
                continue
            except OSError:
                return
            self.stats.count("gossip.recv")
            self.stats.count("gossip.recvBytes", len(data))
            try:
                obj = json.loads(data)
            except json.JSONDecodeError:
                continue
            try:
                self._handle(obj, addr)
            except Exception as e:  # noqa: BLE001 — peer boundary
                self.logger(f"gossip rx error: {e}")

    def _handle(self, obj: dict, addr) -> None:
        typ = obj.get("t")
        sender = obj.get("from", "")
        if typ == "join":
            self._register(sender, _parse_addr(obj["gaddr"]))
            self._send_logged(
                _parse_addr(obj["gaddr"]),
                {
                    "t": "join-ack",
                    "from": self.host,
                    "members": self._member_list(),
                },
            )
        elif typ == "join-ack":
            self._merge_members(obj.get("members", []))
        elif typ == "ping":
            self._register(sender, _parse_addr(obj["gaddr"]))
            self._merge_members(obj.get("members", []))
            self._merge_state(obj)
            self._merge_hot(sender, obj)
            self._merge_health(sender, obj)
            self._send_logged(
                _parse_addr(obj["gaddr"]),
                {
                    "t": "ack",
                    "from": self.host,
                    "gaddr": _fmt_addr(self.advertise),
                    "members": self._member_list(),
                    **self._state_field(),
                    **self._hot_field(),
                    **self._health_field(),
                },
            )
        elif typ == "ack":
            self._register(sender, _parse_addr(obj["gaddr"]))
            self._merge_members(obj.get("members", []))
            self._merge_state(obj)
            self._merge_hot(sender, obj)
            self._merge_health(sender, obj)
            # SWIM relay leg 3: if someone asked us to probe this
            # sender, tell them it answered.
            with self._mu:
                waiters = list(self._relay_pending.pop(sender, {}).items())
            now = time.monotonic()
            for req_addr, deadline in waiters:
                if now <= deadline:
                    self._send_logged(
                        req_addr,
                        {
                            "t": "ind-ack",
                            "from": self.host,
                            "target": sender,
                            "taddr": obj["gaddr"],
                        },
                    )
        elif typ == "ping-req":
            # SWIM relay leg 2: probe the target on the requester's
            # behalf; our eventual ack from the target triggers ind-ack.
            self._register(sender, _parse_addr(obj["gaddr"]))
            target = obj.get("target", "")
            if not target:
                return
            taddr = _parse_addr(obj["taddr"])
            with self._mu:
                self._relay_pending.setdefault(target, {})[
                    _parse_addr(obj["gaddr"])
                ] = time.monotonic() + 4 * self.suspect_after
            self._send_logged(
                taddr,
                {
                    "t": "ping",
                    "from": self.host,
                    "gaddr": _fmt_addr(self.advertise),
                    "members": self._member_list(),
                    **self._state_field(),
                    **self._hot_field(),
                    **self._health_field(),
                },
            )
        elif typ == "ind-ack":
            # SWIM relay leg 4: a third party reached the suspect —
            # refresh it without direct contact.
            target = obj.get("target", "")
            if target:
                self._register(target, _parse_addr(obj["taddr"]))
        elif typ == "user":
            mid = obj.get("id")
            if self._handler is None:
                # No handler wired yet — don't ack, so the sender keeps
                # retrying until this node can actually apply messages.
                return
            if mid is None or not self._is_seen(mid):
                from pilosa_tpu.cluster.broadcast import unmarshal_message

                msg = unmarshal_message(base64.b64decode(obj["p"]))
                # A handler exception propagates before the id is marked
                # seen or acked — the sender's retry re-applies instead
                # of being deduped into a silent drop.
                self._handler.receive_message(msg)
                if mid is not None:
                    self._mark_seen(mid)
            # Ack AFTER processing so a send_sync return means the
            # message was handled, not merely received.
            if mid is not None:
                self._send(addr, {"t": "user-ack", "from": self.host, "id": mid})
        elif typ == "user-ack":
            with self._mu:
                ev = self._ack_events.get(obj.get("id"))
            if ev is not None:
                ev.set()
        elif typ == "state-req":
            self._serve_state_req(addr)
        elif typ == "state-chunk":
            self._handle_state_chunk(obj)

    def _is_seen(self, mid: str) -> bool:
        """True when a user message id was already fully processed —
        retries of it are acked but not re-applied."""
        with self._mu:
            if mid in self._seen_user:
                self._seen_user.move_to_end(mid)
                return True
            return False

    def _mark_seen(self, mid: str) -> None:
        """Record a processed id (bounded LRU); called only after the
        handler applied the message successfully."""
        with self._mu:
            self._seen_user[mid] = time.monotonic()
            while len(self._seen_user) > 4096:
                self._seen_user.popitem(last=False)

    def _hot_field(self) -> dict:
        if self.hot_provider is None:
            return {}
        try:
            hot = self.hot_provider()
        except Exception as e:  # noqa: BLE001
            self.logger(f"hot provider error: {e}")
            return {}
        if not hot:
            return {}
        return {
            "hot": {
                str(idx): [int(s) for s in slices[:HOT_SLICES_MAX]]
                for idx, slices in hot.items()
                if slices
            }
        }

    def _health_field(self) -> dict:
        if self.health_provider is None:
            return {}
        try:
            degraded = bool(self.health_provider())
        except Exception as e:  # noqa: BLE001
            self.logger(f"health provider error: {e}")
            return {}
        # Only announce a non-default state (one key per datagram is
        # cheap, but an always-healthy fleet should pay nothing).
        return {"dvh": True} if degraded else {"dvh": False}

    def _merge_health(self, sender: str, obj: dict) -> None:
        flag = obj.get("dvh")
        if not sender or not isinstance(flag, bool):
            return
        with self._mu:
            prev = self._health_remote.get(sender)
            self._health_remote[sender] = flag
        if prev != flag and self.on_peer_health is not None:
            try:
                self.on_peer_health(sender, flag)
            except Exception as e:  # noqa: BLE001 — advisory hook
                self.logger(f"peer health callback error: {e}")

    def remote_device_health(self) -> dict[str, bool]:
        """{peer host: degraded} as last announced."""
        with self._mu:
            return dict(self._health_remote)

    def _merge_hot(self, sender: str, obj: dict) -> None:
        hot = obj.get("hot")
        if not sender or not isinstance(hot, dict):
            return
        clean: dict[str, list[int]] = {}
        for idx, slices in hot.items():
            if isinstance(slices, list):
                clean[str(idx)] = [
                    int(s) for s in slices[:HOT_SLICES_MAX]
                    if isinstance(s, int)
                ]
        with self._mu:
            self._hot_remote[sender] = (time.monotonic(), clean)

    def remote_hot_slices(self) -> dict[str, list[int]]:
        """Union of peers' fresh hot-slice announcements:
        ``{index: [slice,...]}`` — the gossip-informed head of the
        cold-staging priority queue."""
        now = time.monotonic()
        out: dict[str, dict[int, None]] = {}
        with self._mu:
            for _host, (t, hot) in self._hot_remote.items():
                if now - t > HOT_TTL_S:
                    continue
                for idx, slices in hot.items():
                    d = out.setdefault(idx, {})
                    for s in slices:
                        d.setdefault(s, None)
        return {idx: list(d) for idx, d in out.items()}

    def _state_field(self) -> dict:
        if self.state_provider is None:
            return {}
        try:
            blob = self.state_provider()
        except Exception as e:  # noqa: BLE001
            self.logger(f"state provider error: {e}")
            return {}
        if not blob:
            return {}
        if len(blob) <= INLINE_STATE_MAX:
            return {"state_blob": base64.b64encode(blob).decode()}
        # Too big for a datagram: advertise the digest (and size — the
        # receiver picks UDP chunks vs the HTTP stream from it);
        # interested peers pull the blob.
        return {
            "state_digest": hashlib.sha1(blob).hexdigest(),
            "state_size": len(blob),
        }

    def _merge_state(self, obj: dict) -> None:
        blob = obj.get("state_blob")
        if blob and self.state_merger is not None:
            try:
                self.state_merger(base64.b64decode(blob))
            except Exception as e:  # noqa: BLE001
                self.logger(f"state merge error: {e}")
            return
        digest = obj.get("state_digest")
        if not digest or self.state_merger is None:
            return
        now = time.monotonic()
        with self._mu:
            if digest in self._merged_digests:
                self._merged_digests.move_to_end(digest)
                return
            # A fresh in-flight assembly for this digest suppresses
            # duplicate STATE-REQs — every ping/ack carrying the digest
            # would otherwise trigger a full-blob retransmission.
            for (_, d), asm in self._assemblies.items():
                if d == digest and now - asm["t0"] <= _ASSEMBLY_TTL:
                    return
        # Stream fallback: a blob bigger than STREAM_STATE_CHUNKS
        # datagrams, or one whose UDP transfer already stalled once,
        # fetches over the peer's HTTP listener in one request instead
        # of re-spraying the chunk set (memberlist's TCP push/pull
        # analog, reference: gossip/gossip.go:191-222).
        size = obj.get("state_size")
        big = (
            isinstance(size, int)
            and size > STREAM_STATE_CHUNKS * STATE_CHUNK_SIZE
        )
        with self._mu:
            attempts = self._udp_state_attempts.get(digest, 0)
            sfails = self._stream_failures.get(digest, 0)
            stalled = attempts >= _UDP_STATE_MAX_ATTEMPTS
            if stalled and sfails >= _STREAM_MAX_FAILURES:
                # Both paths exhausted a round — reset and alternate
                # again rather than wedging on either.
                self._udp_state_attempts.pop(digest, None)
                self._stream_failures.pop(digest, None)
                attempts = sfails = 0
                stalled = False
        if (big or stalled) and sfails < _STREAM_MAX_FAILURES:
            # Dial only hosts the membership snapshot already knows: the
            # UDP "from" field is unauthenticated, and following it
            # blindly would let one spoofed datagram point the fetch at
            # an arbitrary host.
            claimed = obj.get("from", "")
            if claimed in self._snapshot():
                self._start_stream(claimed, digest)
                return
            self.logger(
                f"state stream: ignoring offer from unknown member {claimed!r}"
            )
            return
        sender = self._snapshot().get(obj.get("from", ""))
        if sender is not None:
            with self._mu:
                self._bump_state_attempts_locked(digest)
            self._send_logged(
                sender["addr"],
                {"t": "state-req", "from": self.host, "digest": digest},
            )

    @staticmethod
    def _bump_locked(counter: OrderedDict, key: str) -> None:
        """Increment a bounded per-digest counter (caller holds _mu)."""
        counter[key] = counter.get(key, 0) + 1
        while len(counter) > 64:
            counter.popitem(last=False)

    def _bump_state_attempts_locked(self, digest: str) -> None:
        self._bump_locked(self._udp_state_attempts, digest)

    def _start_stream(self, peer_host: str, digest: str) -> None:
        """Fetch a peer's state blob over HTTP on a worker thread (the
        receive loop must never block on a network round trip); one
        in-flight stream per digest."""
        if not peer_host or self.state_merger is None:
            return
        with self._mu:
            if digest in self._streams_in_flight:
                return
            self._streams_in_flight.add(digest)
        threading.Thread(
            target=self._stream_state,
            args=(peer_host, digest),
            daemon=True,
            name=f"state-stream:{peer_host}",
        ).start()

    def _stream_state(self, peer_host: str, digest: str) -> None:
        ok = False
        try:
            blob = self.state_fetcher(peer_host)
            if blob:
                # What arrives is recorded under its OWN digest; the
                # ADVERTISED digest is only marked merged when the
                # blob's sha1 actually matches it — a peer whose state
                # moved past the offer (or a tampered body) must not
                # retire a digest this node never merged.  state_merger
                # parses the blob and raises on garbage, which counts
                # as a stream failure below.
                got = hashlib.sha1(blob).hexdigest()
                self.state_merger(blob)
                ok = True
                now = time.monotonic()
                with self._mu:
                    self._merged_digests[got] = now
                    self._udp_state_attempts.pop(got, None)
                    self._stream_failures.pop(got, None)
                    while len(self._merged_digests) > 64:
                        self._merged_digests.popitem(last=False)
        except Exception as e:  # noqa: BLE001
            self.logger(f"state stream from {peer_host} failed: {e}")
        finally:
            if not ok:
                # EVERY unsuccessful stream (fetch error, empty body,
                # unparseable blob) counts toward the fallback budget:
                # past _STREAM_MAX_FAILURES the offer handler retries
                # UDP chunking even for large blobs, so a broken HTTP
                # path never pins the digest to doomed re-downloads.
                with self._mu:
                    self._bump_locked(self._stream_failures, digest)
            with self._mu:
                self._streams_in_flight.discard(digest)

    def _http_state_fetch(self, peer_host: str) -> bytes:
        """GET the peer's full state blob from its HTTP listener
        (net/handler.py serves /state from the same provider that
        feeds gossip)."""
        import urllib.request

        with urllib.request.urlopen(
            f"http://{peer_host}/state", timeout=self.stream_timeout
        ) as resp:
            return resp.read()

    def _serve_state_req(self, addr) -> None:
        """Stream the CURRENT state blob in numbered chunks.  The blob's
        own digest rides along (it may have moved past the requested
        one — the receiver validates against what actually arrives)."""
        if self.state_provider is None:
            return
        try:
            blob = self.state_provider()
        except Exception as e:  # noqa: BLE001
            self.logger(f"state provider error: {e}")
            return
        if not blob:
            return
        digest = hashlib.sha1(blob).hexdigest()
        chunks = [
            blob[i : i + STATE_CHUNK_SIZE]
            for i in range(0, len(blob), STATE_CHUNK_SIZE)
        ]
        for seq, chunk in enumerate(chunks):
            self._send_logged(
                addr,
                {
                    "t": "state-chunk",
                    "from": self.host,
                    "digest": digest,
                    "seq": seq,
                    "n": len(chunks),
                    "p": base64.b64encode(chunk).decode(),
                },
            )

    def _handle_state_chunk(self, obj: dict) -> None:
        sender = obj.get("from", "")
        digest = obj.get("digest", "")
        seq, n = obj.get("seq"), obj.get("n")
        if not digest or not isinstance(seq, int) or not isinstance(n, int):
            return
        if not (0 <= seq < n):
            return
        key = (sender, digest)
        now = time.monotonic()
        with self._mu:
            if digest in self._merged_digests:
                return
            # GC stale partial assemblies; each timed-out transfer
            # counts toward the stream-fallback threshold.
            for k in [
                k
                for k, a in self._assemblies.items()
                if now - a["t0"] > _ASSEMBLY_TTL
            ]:
                self._bump_state_attempts_locked(k[1])
                del self._assemblies[k]
            asm = self._assemblies.setdefault(key, {"t0": now, "n": n, "parts": {}})
            if asm["n"] != n:
                # Sender restarted the transfer with a different chunk
                # count; start over.
                asm = self._assemblies[key] = {"t0": now, "n": n, "parts": {}}
            asm["parts"][seq] = base64.b64decode(obj.get("p", ""))
            # Progress refreshes the TTL: a slow lossy transfer keeps its
            # partial assembly as long as chunks keep arriving.
            asm["t0"] = now
            if len(asm["parts"]) < n:
                return
            blob = b"".join(asm["parts"][i] for i in range(n))
            del self._assemblies[key]
        if hashlib.sha1(blob).hexdigest() != digest:
            self.logger(
                f"state transfer from {sender} failed digest check; dropped"
            )
            return
        if self.state_merger is not None:
            try:
                self.state_merger(blob)
            except Exception as e:  # noqa: BLE001
                # NOT recorded as merged: the next ping retries the
                # transfer instead of skipping this state forever.
                self.logger(f"state merge error: {e}")
                return
        with self._mu:
            self._merged_digests[digest] = now
            self._udp_state_attempts.pop(digest, None)
            self._stream_failures.pop(digest, None)
            while len(self._merged_digests) > 64:
                self._merged_digests.popitem(last=False)

    def _tick_loop(self) -> None:
        while not self._closing.wait(self.gossip_interval):
            # A node that still knows only itself re-sends its join —
            # the original datagram may have been lost (memberlist
            # retries joins the same way).
            peers = [
                (h, m)
                for h, m in self._snapshot().items()
                if h != self.host
            ]
            if not peers and self.seed:
                self._send_logged(
                    _parse_addr(self.seed),
                    {
                        "t": "join",
                        "from": self.host,
                        "gaddr": _fmt_addr(self.advertise),
                    },
                )
            # probe a random live peer
            if peers:
                host, member = random.choice(peers)
                self._send_logged(
                    member["addr"],
                    {
                        "t": "ping",
                        "from": self.host,
                        "gaddr": _fmt_addr(self.advertise),
                        "members": self._member_list(),
                        **self._state_field(),
                        **self._hot_field(),
                    **self._health_field(),
                    },
                )
            # SWIM suspect machinery: silence past suspect_after marks a
            # member SUSPECT and fans indirect probes through third
            # parties; only continued silence — direct AND indirect —
            # past another suspect_after confirms DOWN.  An asymmetric
            # partition (we can't reach B, C can) therefore never flaps
            # B to DOWN: C's ind-ack refreshes it.
            now = time.monotonic()
            changed = False
            suspects: list[tuple[str, dict]] = []
            with self._mu:
                for h, m in self._members.items():
                    if h == self.host:
                        m["last_seen"] = now
                        continue
                    silent = now - m["last_seen"]
                    if m["state"] == "UP" and silent > self.suspect_after:
                        m["state"] = "SUSPECT"
                        # DOWN is anchored to SUSPECT entry, not to
                        # last_seen: even after a tick-loop stall the
                        # member gets one full probed window before it
                        # can be confirmed DOWN.
                        m["suspect_since"] = now
                    if (
                        m["state"] == "SUSPECT"
                        and now - m.get("suspect_since", now)
                        > self.suspect_after
                    ):
                        m["state"] = "DOWN"
                        changed = True
                    elif m["state"] == "SUSPECT":
                        # Probed EVERY tick while suspect (not only on
                        # the transition): a lost probe round must not
                        # be able to confirm a reachable member DOWN.
                        suspects.append((h, dict(m)))
                relays = [
                    (h, m["addr"])
                    for h, m in self._members.items()
                    if h != self.host and m["state"] == "UP"
                ]
                # Expire stale relay bookkeeping.
                for tgt in list(self._relay_pending):
                    self._relay_pending[tgt] = {
                        a: d for a, d in self._relay_pending[tgt].items() if d >= now
                    }
                    if not self._relay_pending[tgt]:
                        del self._relay_pending[tgt]
            for h, m in suspects:
                # One more direct attempt plus k indirect probes.
                self._send_logged(
                    m["addr"],
                    {
                        "t": "ping",
                        "from": self.host,
                        "gaddr": _fmt_addr(self.advertise),
                        "members": self._member_list(),
                        **self._state_field(),
                        **self._hot_field(),
                    **self._health_field(),
                    },
                )
                pool = [r for r in relays if r[0] != h]
                random.shuffle(pool)
                for _, relay_addr in pool[: self.indirect_probes]:
                    self._send_logged(
                        relay_addr,
                        {
                            "t": "ping-req",
                            "from": self.host,
                            "gaddr": _fmt_addr(self.advertise),
                            "target": h,
                            "taddr": _fmt_addr(m["addr"]),
                        },
                    )
            if changed:
                self._notify()


def _parse_addr(s: str) -> tuple[str, int]:
    addr, _, port = s.partition(":")
    return (addr or "127.0.0.1", int(port))


def _fmt_addr(addr) -> str:
    return f"{addr[0]}:{addr[1]}"
