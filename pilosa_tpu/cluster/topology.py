"""Cluster topology: slice -> partition -> node placement.

Placement must be *hash-identical* to the reference so that data laid out
by one implementation is found by the other (reference: cluster.go:200-281):

* ``partition(index, slice) = fnv64a(index || slice_be8) % PartitionN``
* primary node = jump consistent hash (Lamping-Veach) of the partition id
  over the node list; replicas are the next ``ReplicaN-1`` nodes around
  the ring.

In the TPU-native design the same function also places slices onto
*devices within a node*: a node owns a set of slices, and those slices are
sharded round-robin over the local TPU mesh (see
:mod:`pilosa_tpu.parallel.mesh`), so the cluster-level map stays
compatible while intra-node reduces ride ICI collectives.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass

# reference: cluster.go:22-31
DEFAULT_PARTITION_N = 256
DEFAULT_REPLICA_N = 1

# reference: cluster.go:33-37
NODE_STATE_UP = "UP"
NODE_STATE_DOWN = "DOWN"

_FNV64_OFFSET = 0xCBF29CE484222325
_FNV64_PRIME = 0x100000001B3
_MASK64 = (1 << 64) - 1


def fnv64a(data: bytes) -> int:
    """64-bit FNV-1a (stdlib-free, matches Go's hash/fnv)."""
    h = _FNV64_OFFSET
    for b in data:
        h ^= b
        h = (h * _FNV64_PRIME) & _MASK64
    return h


def jump_hash(key: int, n: int) -> int:
    """Jump consistent hash (Lamping & Veach 2014) — maps ``key`` to a
    bucket in [0, n).  Same constants as the reference's jmphasher
    (reference: cluster.go:268-281)."""
    b, j = -1, 0
    key &= _MASK64
    while j < n:
        b = j
        key = (key * 2862933555777941757 + 1) & _MASK64
        j = int(float(b + 1) * (float(1 << 31) / float((key >> 33) + 1)))
    return b


@dataclass
class Node:
    """One cluster member (reference: cluster.go:40-45)."""

    host: str
    internal_host: str = ""
    state: str = NODE_STATE_DOWN

    def set_state(self, s: str) -> None:
        self.state = s

    def to_dict(self) -> dict:
        return {"host": self.host, "internalHost": self.internal_host}


class Cluster:
    """Node list + placement functions (reference: cluster.go:122-258)."""

    def __init__(
        self,
        nodes: list[Node] | None = None,
        partition_n: int = DEFAULT_PARTITION_N,
        replica_n: int = DEFAULT_REPLICA_N,
        long_query_time: float = 0.0,
    ):
        self.nodes: list[Node] = nodes or []
        self.partition_n = partition_n
        self.replica_n = replica_n
        self.long_query_time = long_query_time
        self.node_set = None  # membership backend; wired by the server
        self._mu = threading.Lock()

    # --- membership -----------------------------------------------------

    def node_by_host(self, host: str) -> Node | None:
        for n in self.nodes:
            if n.host == host:
                return n
        return None

    def add_node(self, host: str) -> Node:
        """Idempotently register a host, keeping the list sorted so every
        member computes the same ring (reference: cluster.go:176-187)."""
        with self._mu:
            n = self.node_by_host(host)
            if n is not None:
                return n
            n = Node(host=host)
            self.nodes.append(n)
            self.nodes.sort(key=lambda x: x.host)
            return n

    def node_states(self) -> dict[str, str]:
        """Merge node states from the membership backend: a node is UP iff
        the NodeSet currently sees it (reference: cluster.go:149-173)."""
        up = set()
        if self.node_set is not None:
            # NodeSet.nodes() yields host strings (broadcast.NodeSet
            # protocol); tolerate Node objects too.
            for n in self.node_set.nodes():
                up.add(n if isinstance(n, str) else n.host)
        else:
            # Static clusters have no failure detector; every configured
            # node counts as UP (the reference's StaticNodeSet returns
            # the full list, cluster.go:62-86).
            up = {n.host for n in self.nodes}
        out = {}
        for n in self.nodes:
            n.state = NODE_STATE_UP if n.host in up else NODE_STATE_DOWN
            out[n.host] = n.state
        return out

    def hosts(self) -> list[str]:
        return [n.host for n in self.nodes]

    # --- placement (reference: cluster.go:200-258) ----------------------

    def partition(self, index: str, slice_i: int) -> int:
        data = index.encode() + slice_i.to_bytes(8, "big")
        return fnv64a(data) % self.partition_n

    def partition_nodes(self, partition_id: int) -> list[Node]:
        replica_n = self.replica_n
        if replica_n > len(self.nodes):
            replica_n = len(self.nodes)
        elif replica_n == 0:
            replica_n = 1
        node_index = jump_hash(partition_id, len(self.nodes))
        return [
            self.nodes[(node_index + i) % len(self.nodes)] for i in range(replica_n)
        ]

    def fragment_nodes(self, index: str, slice_i: int) -> list[Node]:
        return self.partition_nodes(self.partition(index, slice_i))

    def owns_fragment(self, host: str, index: str, slice_i: int) -> bool:
        return any(n.host == host for n in self.fragment_nodes(index, slice_i))

    def split_by_owner(
        self, index: str, slices, hosts: set[str]
    ) -> tuple[list[int], list[int]]:
        """Partition ``slices`` into (placeable, lost) against a
        surviving host set — the failover planner's question: which of a
        dead node's slices still have a replica, and which are gone."""
        placeable: list[int] = []
        lost: list[int] = []
        for s in slices:
            owners = {n.host for n in self.fragment_nodes(index, s)}
            (placeable if owners & hosts else lost).append(s)
        return placeable, lost

    def owns_slices(self, index: str, max_slice: int, host: str) -> list[int]:
        """Slices whose *primary* owner is ``host`` (reference:
        cluster.go:246-258)."""
        out = []
        for i in range(max_slice + 1):
            p = self.partition(index, i)
            node_index = jump_hash(p, len(self.nodes))
            if self.nodes[node_index].host == host:
                out.append(i)
        return out

    def status_dict(self) -> dict:
        self.node_states()
        return {
            "nodes": [
                {"host": n.host, "internalHost": n.internal_host, "state": n.state}
                for n in self.nodes
            ]
        }


def new_cluster(n: int) -> Cluster:
    """Test helper mirroring the reference's fixture: n fake ``host%d:0``
    nodes (reference: cluster_test.go:146-176)."""
    c = Cluster()
    for i in range(n):
        c.nodes.append(Node(host=f"host{i}:0"))
    return c
