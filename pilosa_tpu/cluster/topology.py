"""Cluster topology: slice -> partition -> node placement.

Placement must be *hash-identical* to the reference so that data laid out
by one implementation is found by the other (reference: cluster.go:200-281):

* ``partition(index, slice) = fnv64a(index || slice_be8) % PartitionN``
* primary node = jump consistent hash (Lamping-Veach) of the partition id
  over the node list; replicas are the next ``ReplicaN-1`` nodes around
  the ring.

In the TPU-native design the same function also places slices onto
*devices within a node*: a node owns a set of slices, and those slices are
sharded round-robin over the local TPU mesh (see
:mod:`pilosa_tpu.parallel.mesh`), so the cluster-level map stays
compatible while intra-node reduces ride ICI collectives.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field

# reference: cluster.go:22-31
DEFAULT_PARTITION_N = 256
DEFAULT_REPLICA_N = 1

# reference: cluster.go:33-37
NODE_STATE_UP = "UP"
NODE_STATE_DOWN = "DOWN"

_FNV64_OFFSET = 0xCBF29CE484222325
_FNV64_PRIME = 0x100000001B3
_MASK64 = (1 << 64) - 1


def fnv64a(data: bytes) -> int:
    """64-bit FNV-1a (stdlib-free, matches Go's hash/fnv)."""
    h = _FNV64_OFFSET
    for b in data:
        h ^= b
        h = (h * _FNV64_PRIME) & _MASK64
    return h


def jump_hash(key: int, n: int) -> int:
    """Jump consistent hash (Lamping & Veach 2014) — maps ``key`` to a
    bucket in [0, n).  Same constants as the reference's jmphasher
    (reference: cluster.go:268-281)."""
    b, j = -1, 0
    key &= _MASK64
    while j < n:
        b = j
        key = (key * 2862933555777941757 + 1) & _MASK64
        j = int(float(b + 1) * (float(1 << 31) / float((key >> 33) + 1)))
    return b


class TopologyError(RuntimeError):
    """Illegal topology mutation (membership change outside the
    versioned-transition API, conflicting transitions, ...)."""


class MixedEpochError(TopologyError):
    """A query observed two different topology epochs while routing —
    the ring changed under it.  Queries must fail loudly here instead of
    silently reducing over a half-old, half-new placement."""

    def __init__(self, expected: int, actual: int):
        super().__init__(
            f"query observed a mixed-epoch route: routing started at "
            f"topology epoch {expected}, cluster is now at {actual}; "
            "retry the query"
        )
        self.expected = expected
        self.actual = actual


@dataclass
class Node:
    """One cluster member (reference: cluster.go:40-45)."""

    host: str
    internal_host: str = ""
    state: str = NODE_STATE_DOWN
    # Device-health flag (device/health.py, learned via the gossip
    # piggyback or set locally): the node is UP but its accelerator is
    # quarantined — it answers correctly from host planes, slower.
    # Coordinators deprioritize degraded replicas when a healthy one
    # owns the slice (executor._slices_by_node).
    degraded: bool = False

    def set_state(self, s: str) -> None:
        self.state = s

    def to_dict(self) -> dict:
        return {"host": self.host, "internalHost": self.internal_host}


@dataclass
class Transition:
    """A topology change in flight: the old ring (``Cluster.nodes``) and
    the new ring coexist; reads route on the old ring until a slice is
    flipped (checksum-verified on its new owner), writes go to BOTH
    rings' owners, and ``moved`` records the slices whose ownership has
    already cut over.  Both rings stay valid until commit — a crashed
    coordinator mid-copy strands nothing."""

    epoch: int
    old_hosts: list[str]
    new_hosts: list[str]
    new_nodes: list[Node]
    moved: set = field(default_factory=set)  # {(index, slice)}


class Cluster:
    """Node list + placement functions (reference: cluster.go:122-258).

    Membership is VERSIONED: every ring mutation (``add_node`` at boot,
    transition begin/commit) bumps ``epoch``, and per-slice ownership
    flips during a transition bump ``routing_version``.  Routing caches
    key on ``routing_version``; a query captures ``epoch`` once and
    fails loudly (:class:`MixedEpochError`) if the ring moved under it.
    """

    def __init__(
        self,
        nodes: list[Node] | None = None,
        partition_n: int = DEFAULT_PARTITION_N,
        replica_n: int = DEFAULT_REPLICA_N,
        long_query_time: float = 0.0,
    ):
        self.nodes: list[Node] = nodes or []
        self.partition_n = partition_n
        self.replica_n = replica_n
        self.long_query_time = long_query_time
        self.node_set = None  # membership backend; wired by the server
        self._mu = threading.Lock()
        self._epoch = 0
        self._routing_version = 0
        self._health_version = 0
        self._transition: Transition | None = None

    # --- versioned topology --------------------------------------------

    @property
    def epoch(self) -> int:
        """Ring version: bumped on every node-list mutation (boot-time
        add_node, transition begin, transition commit/abort)."""
        return self._epoch

    @property
    def routing_version(self) -> int:
        """Placement version: bumps with ``epoch`` AND on every
        per-slice ownership flip — the cache key for slice->node maps."""
        return self._routing_version

    @property
    def health_version(self) -> int:
        """Replica-health version: bumped whenever any node's
        device-degraded flag flips — the extra cache key that lets
        slice->node routing maps react to degradation without a ring
        mutation."""
        return self._health_version

    def note_degraded(self, host: str, degraded: bool) -> bool:
        """Record a node's device-degraded flag (from the gossip
        device-health piggyback, or the local health manager's state
        changes).  Returns True when the flag actually flipped (and the
        health version bumped); unknown hosts are ignored."""
        node = self.node_by_host(host)
        if node is None or node.degraded == bool(degraded):
            return False
        with self._mu:
            node.degraded = bool(degraded)
            self._health_version += 1
        return True

    @property
    def transition(self) -> Transition | None:
        return self._transition

    def begin_transition(
        self, new_hosts: list[str], epoch: int | None = None
    ) -> Transition:
        """Install a topology transition: the current node list stays
        the read ring, ``new_hosts`` becomes the target ring.  Epoch is
        the coordinator-assigned transition token (fanned to every node
        so all members agree on the transition identity); re-applying
        the same transition is idempotent."""
        new_hosts = sorted(dict.fromkeys(new_hosts))
        if not new_hosts:
            raise TopologyError("transition needs at least one host")
        with self._mu:
            t = self._transition
            if t is not None:
                if t.new_hosts == new_hosts:
                    return t  # idempotent re-apply (coordinator resume)
                raise TopologyError(
                    f"transition to {t.new_hosts} already in flight "
                    f"(epoch {t.epoch}); abort it before starting another"
                )
            e = epoch if epoch is not None else self._epoch + 1
            by_host = {n.host: n for n in self.nodes}
            new_nodes = []
            for h in new_hosts:
                n = by_host.get(h)
                if n is None:
                    n = Node(host=h, state=NODE_STATE_UP)
                new_nodes.append(n)
            t = Transition(
                epoch=e,
                old_hosts=[n.host for n in self.nodes],
                new_hosts=new_hosts,
                new_nodes=new_nodes,
            )
            self._transition = t
            self._epoch = max(self._epoch + 1, e)
            self._routing_version += 1
            return t

    def flip_slice(self, index: str, slice_i: int, epoch: int) -> bool:
        """Atomically cut one slice's ownership over to the new ring.
        Returns False (idempotent no-op) when no matching transition is
        active — a replayed flip after commit must not error."""
        with self._mu:
            t = self._transition
            if t is None or t.epoch != epoch:
                return False
            t.moved.add((index, slice_i))
            self._routing_version += 1
            return True

    def unflip_slice(self, index: str, slice_i: int, epoch: int) -> bool:
        """Reverse one slice's cutover (abort path)."""
        with self._mu:
            t = self._transition
            if t is None or t.epoch != epoch:
                return False
            t.moved.discard((index, slice_i))
            self._routing_version += 1
            return True

    def commit_transition(self, epoch: int) -> None:
        """Swap the new ring in as THE ring and end the transition."""
        with self._mu:
            t = self._transition
            if t is None:
                return  # idempotent (replayed commit)
            if t.epoch != epoch:
                raise TopologyError(
                    f"commit for epoch {epoch} but transition is {t.epoch}"
                )
            self.nodes = sorted(t.new_nodes, key=lambda n: n.host)
            self._transition = None
            self._epoch = max(self._epoch + 1, epoch + 1)
            self._routing_version += 1

    def abort_transition(self, epoch: int | None = None) -> None:
        """Drop the transition, keeping the OLD ring authoritative.
        Refuses while flipped slices exist — they route to the new ring
        and must be migrated back (unflipped) first, or the abort would
        orphan their data."""
        with self._mu:
            t = self._transition
            if t is None:
                return
            if epoch is not None and t.epoch != epoch:
                return
            if t.moved:
                raise TopologyError(
                    f"cannot abort transition {t.epoch}: "
                    f"{len(t.moved)} slice(s) already flipped to the new "
                    "ring; reverse-migrate them first"
                )
            self._transition = None
            self._epoch += 1
            self._routing_version += 1

    def transition_snapshot(self) -> dict | None:
        """JSON-able transition state (persisted across restarts so a
        crashed node rejoins with both rings intact)."""
        with self._mu:
            t = self._transition
            if t is None:
                return None
            return {
                "epoch": t.epoch,
                "old": list(t.old_hosts),
                "new": list(t.new_hosts),
                "moved": sorted([i, s] for i, s in t.moved),
            }

    def restore_transition(self, snap: dict) -> None:
        """Re-install a persisted transition (crash recovery)."""
        self.begin_transition(list(snap["new"]), epoch=int(snap["epoch"]))
        for idx, s in snap.get("moved", []):
            self.flip_slice(str(idx), int(s), int(snap["epoch"]))

    # --- membership -----------------------------------------------------

    def node_by_host(self, host: str) -> Node | None:
        for n in self.nodes:
            if n.host == host:
                return n
        return None

    def add_node(self, host: str) -> Node:
        """Idempotently register a host at BOOT time, keeping the list
        sorted so every member computes the same ring (reference:
        cluster.go:176-187).  This is part of the versioned-topology
        API: an actual mutation bumps the epoch, and any membership
        change while a rebalance transition is in flight is rejected
        loudly — the transition machinery (begin/flip/commit) is the
        only legal way to reshape a serving ring."""
        with self._mu:
            n = self.node_by_host(host)
            if n is not None:
                return n
            if self._transition is not None:
                raise TopologyError(
                    f"cannot add node {host!r}: rebalance transition "
                    f"(epoch {self._transition.epoch}) in flight — "
                    "membership changes go through /cluster/resize"
                )
            n = Node(host=host)
            self.nodes.append(n)
            self.nodes.sort(key=lambda x: x.host)
            self._epoch += 1
            self._routing_version += 1
            return n

    def node_states(self) -> dict[str, str]:
        """Merge node states from the membership backend: a node is UP iff
        the NodeSet currently sees it (reference: cluster.go:149-173)."""
        up = set()
        if self.node_set is not None:
            # NodeSet.nodes() yields host strings (broadcast.NodeSet
            # protocol); tolerate Node objects too.
            for n in self.node_set.nodes():
                up.add(n if isinstance(n, str) else n.host)
        else:
            # Static clusters have no failure detector; every configured
            # node counts as UP (the reference's StaticNodeSet returns
            # the full list, cluster.go:62-86).
            up = {n.host for n in self.nodes}
        out = {}
        for n in self.nodes:
            n.state = NODE_STATE_UP if n.host in up else NODE_STATE_DOWN
            out[n.host] = n.state
        return out

    def hosts(self) -> list[str]:
        return [n.host for n in self.nodes]

    def route_nodes(self) -> list[Node]:
        """Every node a query may route to right now: the read ring
        plus, during a transition, the new ring's additional nodes
        (flipped slices already route to them)."""
        t = self._transition
        if t is None:
            return list(self.nodes)
        seen = {n.host for n in self.nodes}
        return list(self.nodes) + [
            n for n in t.new_nodes if n.host not in seen
        ]

    # --- placement (reference: cluster.go:200-258) ----------------------

    def partition(self, index: str, slice_i: int) -> int:
        data = index.encode() + slice_i.to_bytes(8, "big")
        return fnv64a(data) % self.partition_n

    def partition_nodes_over(
        self, partition_id: int, nodes: list[Node]
    ) -> list[Node]:
        """Jump-hash owner list over an EXPLICIT ring — the one
        placement implementation both rings of a transition share."""
        if not nodes:
            return []
        replica_n = self.replica_n
        if replica_n > len(nodes):
            replica_n = len(nodes)
        elif replica_n == 0:
            replica_n = 1
        node_index = jump_hash(partition_id, len(nodes))
        return [
            nodes[(node_index + i) % len(nodes)] for i in range(replica_n)
        ]

    def partition_nodes(self, partition_id: int) -> list[Node]:
        return self.partition_nodes_over(partition_id, self.nodes)

    def fragment_nodes(self, index: str, slice_i: int) -> list[Node]:
        """READ owners of a slice: the old ring until the slice's
        cutover flips (its fragment is checksum-verified on the new
        owner), the new ring after."""
        t = self._transition
        ring = self.nodes
        if t is not None and (index, slice_i) in t.moved:
            ring = t.new_nodes
        return self.partition_nodes_over(self.partition(index, slice_i), ring)

    def new_ring_nodes(self, index: str, slice_i: int) -> list[Node]:
        """Owners of a slice on the transition's NEW ring ([] when no
        transition is active)."""
        t = self._transition
        if t is None:
            return []
        return self.partition_nodes_over(
            self.partition(index, slice_i), t.new_nodes
        )

    def write_nodes(self, index: str, slice_i: int) -> list[Node]:
        """WRITE targets of a slice: during a transition every write is
        applied on BOTH rings' owners (the old ring keeps serving reads,
        the new owner accumulates state ahead of its cutover), so no
        write is lost whichever ring ultimately serves it."""
        t = self._transition
        out = self.fragment_nodes(index, slice_i)
        if t is None:
            return out
        seen = {n.host for n in out}
        for n in self.partition_nodes_over(
            self.partition(index, slice_i), t.new_nodes
        ):
            if n.host not in seen:
                seen.add(n.host)
                out = out + [n]
        return out

    def owns_fragment(self, host: str, index: str, slice_i: int) -> bool:
        return any(n.host == host for n in self.fragment_nodes(index, slice_i))

    def is_write_owner(self, host: str, index: str, slice_i: int) -> bool:
        """Ownership guard for the write/import paths: during a
        transition the new ring's owners accept writes too."""
        return any(n.host == host for n in self.write_nodes(index, slice_i))

    def split_by_owner(
        self, index: str, slices, hosts: set[str]
    ) -> tuple[list[int], list[int]]:
        """Partition ``slices`` into (placeable, lost) against a
        surviving host set — the failover planner's question: which of a
        dead node's slices still have a replica, and which are gone."""
        placeable: list[int] = []
        lost: list[int] = []
        for s in slices:
            owners = {n.host for n in self.fragment_nodes(index, s)}
            (placeable if owners & hosts else lost).append(s)
        return placeable, lost

    def owns_slices(self, index: str, max_slice: int, host: str) -> list[int]:
        """Slices whose *primary* owner is ``host`` (reference:
        cluster.go:246-258) — transition-aware: a flipped slice's
        primary comes from the new ring."""
        out = []
        for i in range(max_slice + 1):
            owners = self.fragment_nodes(index, i)
            if owners and owners[0].host == host:
                out.append(i)
        return out

    def status_dict(self) -> dict:
        self.node_states()
        out = {
            "nodes": [
                {"host": n.host, "internalHost": n.internal_host, "state": n.state}
                for n in self.nodes
            ],
            "epoch": self._epoch,
        }
        t = self._transition
        if t is not None:
            out["transition"] = {
                "epoch": t.epoch,
                "newHosts": list(t.new_hosts),
                "movedSlices": len(t.moved),
            }
        return out


def new_cluster(n: int) -> Cluster:
    """Test helper mirroring the reference's fixture: n fake ``host%d:0``
    nodes (reference: cluster_test.go:146-176)."""
    c = Cluster()
    for i in range(n):
        c.add_node(f"host{i}:0")
    return c
