"""The BSI ripple — comparison, Sum, and Min/Max over bit-planes.

One implementation, two array backends: the fused device kernels
(exec/plan.py embeds these into jitted XLA programs, ``xp=jax.numpy``)
and the host reference path (plan.eval_expr_np, ``xp=numpy``) share
these functions verbatim, so the device programs can never drift from
the host semantics.

Everything here is an and/andnot/or cascade over limb planes plus
popcount reductions — exactly the op mix ``ops/bitplane.py`` already
executes as one fused bitwise+popcount pass.  Predicates arrive as
DATA (a packed :func:`pilosa_tpu.bsi.pred_row`), so a compiled program
serves every predicate value of its (op kind, depth bucket).
"""

from __future__ import annotations

_FULL = 0xFFFFFFFF


def _bit_mask(word, xp):
    """uint32 scalar word (0/1) -> all-ones/all-zeros uint32 mask,
    without overflow-warning-prone unsigned negation."""
    return (word & xp.uint32(1)) * xp.uint32(_FULL)


def magnitude_cmp(exists, planes, pred_bits, xp):
    """Range-encoded ripple: partition the ``exists`` columns into
    (lt, eq, gt) against the unsigned magnitude whose bit ``k`` is
    ``pred_bits[k] & 1``.  High plane to low: columns still equal on
    every higher bit split on the current one."""
    eq = exists
    lt = xp.zeros_like(exists)
    gt = xp.zeros_like(exists)
    for k in reversed(range(len(planes))):
        b = planes[k]
        m = _bit_mask(pred_bits[k], xp)
        lt = lt | (eq & ~b & m)
        gt = gt | (eq & b & ~m)
        eq = eq & (b ^ ~m)
    return lt, eq, gt


def signed_cmp(op, exists, sign, planes, pred, xp):
    """One signed comparison row.  ``pred`` is a packed predicate row
    (bit ``k`` of the magnitude at word ``k``, sign flag at word
    ``len(planes)``); ``op`` is a static tag (lt/le/eq/ne/ge/gt).

    Sign-magnitude composition: the magnitude partition applies to the
    matching sign group, with ordering inverted among negatives; the
    predicate's own sign selects between the two composition cases via
    a data mask, so positive and negative predicates share one
    compiled program."""
    depth = len(planes)
    lt, eq, gt = magnitude_cmp(exists, planes, pred[:depth], xp)
    nm = _bit_mask(pred[depth], xp)  # all-ones iff the predicate is negative
    pos = exists & ~sign
    neg = exists & sign

    eq_row = (~nm & pos & eq) | (nm & neg & eq)
    if op == "eq":
        return eq_row
    if op == "ne":
        return exists & ~eq_row
    lt_row = (~nm & (neg | (pos & lt))) | (nm & neg & gt)
    if op == "lt":
        return lt_row
    if op == "le":
        return lt_row | eq_row
    gt_row = (~nm & pos & gt) | (nm & (pos | (neg & lt)))
    if op == "gt":
        return gt_row
    if op == "ge":
        return gt_row | eq_row
    raise ValueError(f"unknown BSI comparison op {op!r}")


def between_row(exists, sign, planes, pred_lo, pred_hi, xp):
    """``lo <= v <= hi`` as two fused ripples sharing the plane reads."""
    return signed_cmp("ge", exists, sign, planes, pred_lo, xp) & signed_cmp(
        "le", exists, sign, planes, pred_hi, xp
    )


def sum_vec(exists, sign, planes, filt, xp, popcount):
    """Per-slice Sum partials: int vector
    ``[pos_0..pos_{D-1}, neg_0..neg_{D-1}, n]`` where ``pos_k`` /
    ``neg_k`` count set bits of plane ``k`` among non-negative /
    negative valued columns and ``n`` counts valued columns — the
    popcount-weighted plane dot finishes on the host in unbounded
    Python ints: ``sum = Σ 2^k (pos_k - neg_k)``.  Each partial covers
    one slice-row (<= 2^20 bits), so int32 is exact."""
    base = exists if filt is None else exists & filt
    pos = base & ~sign
    neg = base & sign
    parts = [popcount(p & pos) for p in planes]
    parts += [popcount(p & neg) for p in planes]
    parts.append(popcount(base))
    return xp.stack(parts)


def minmax_vec(which, exists, sign, planes, filt, xp, popcount, where):
    """Per-slice Min/Max partials via greedy plane descent: int vector
    ``[bit_0..bit_{D-1}, negative, count]`` — the chosen magnitude
    bits, whether the extreme is negative, and how many columns hold
    it (count 0 = no valued columns in the slice).

    Min prefers the negative group (where the LARGEST magnitude wins);
    Max prefers the non-negative group (largest magnitude wins too) —
    so both run ONE descent whose direction is maximize-within-group,
    falling back to the opposite group with a minimizing descent.  The
    group choice and both descents are data-dependent selects inside
    the fused program, never separate compiles."""
    base = exists if filt is None else exists & filt
    pos = base & ~sign
    neg = base & sign
    if which == "min":
        prefer, other = neg, pos
    else:
        prefer, other = pos, neg
    use_prefer = xp.asarray(popcount(prefer) > 0)
    cand = where(use_prefer, prefer, other)
    # maximize magnitude within the preferred group, minimize in the
    # fallback group (see docstring) — identical rule for min and max.
    maximize = use_prefer

    bits = [None] * len(planes)
    for k in reversed(range(len(planes))):
        b = planes[k]
        with_one = cand & b
        n1 = popcount(with_one)
        ntot = popcount(cand)
        # maximize: take bit 1 iff any candidate has it;
        # minimize: take bit 1 only when every candidate has it.
        choose1 = where(maximize, xp.asarray(n1 > 0), xp.asarray(n1 == ntot))
        cand = where(choose1, with_one, cand & ~b)
        bits[k] = xp.asarray(choose1).astype(xp.int32)
    negative = (
        use_prefer if which == "min" else xp.logical_not(use_prefer)
    ).astype(xp.int32)
    return xp.stack(bits + [negative, xp.asarray(popcount(cand), dtype=xp.int32)])


# ---------------------------------------------------------------------------
# ripple as interpreter ops (exec/plan.py fused multi-query programs)
# ---------------------------------------------------------------------------
#
# The third backend: instead of an array module, ``em`` is an opcode
# emitter (plan.FuseEmitter) and every ``xp`` operation becomes one
# packed int32 instruction row.  The emitted stream reproduces the
# array functions above operation for operation — same OR-accumulation
# order, same andnot/xor factoring — so a fused interpreter launch is
# byte-identical to the direct compiled ripple.  Value numbering inside
# the emitter shares subterms (pos/neg/ripple state) across the two
# ripples of a ``between`` and across queries lowered into one table.


def lower_magnitude_cmp(em, exists, planes, pred):
    """Emit :func:`magnitude_cmp` as interpreter ops; ``exists`` /
    ``planes[k]`` / ``pred`` are register ids, the return is the
    ``(lt, eq, gt)`` register triple.  ``m_k`` comes from the MASKW op
    (broadcast of predicate word ``k``), so the predicate stays DATA —
    one lowered stream serves every constant of its depth bucket."""
    eq = exists
    lt = gt = None
    for k in reversed(range(len(planes))):
        b = planes[k]
        m = em.maskw(pred, k)
        lt_term = em.and_(em.andnot(eq, b), m)
        lt = lt_term if lt is None else em.or_(lt, lt_term)
        gt_term = em.andnot(em.and_(eq, b), m)
        gt = gt_term if gt is None else em.or_(gt, gt_term)
        # eq & (b ^ ~m)  ==  eq & ~(b ^ m)
        eq = em.andnot(eq, em.xor(b, m))
    # BSI depths bucket to multiples of 8 (bsi.pad_depth), so planes is
    # never empty and lt/gt are always materialized.
    return lt, eq, gt


def lower_signed_cmp(em, op, exists, sign, planes, pred):
    """Emit :func:`signed_cmp` as interpreter ops; returns the result
    row's register id.  Same sign-magnitude composition, with the
    predicate's sign mask (word ``depth``) selecting between the
    positive- and negative-predicate cases as data."""
    depth = len(planes)
    lt, eq, gt = lower_magnitude_cmp(em, exists, planes, pred)
    nm = em.maskw(pred, depth)
    pos = em.andnot(exists, sign)
    neg = em.and_(exists, sign)

    eq_row = em.or_(
        em.andnot(em.and_(pos, eq), nm), em.and_(em.and_(neg, eq), nm)
    )
    if op == "eq":
        return eq_row
    if op == "ne":
        return em.andnot(exists, eq_row)
    lt_row = em.or_(
        em.andnot(em.or_(neg, em.and_(pos, lt)), nm),
        em.and_(em.and_(neg, gt), nm),
    )
    if op == "lt":
        return lt_row
    if op == "le":
        return em.or_(lt_row, eq_row)
    gt_row = em.or_(
        em.andnot(em.and_(pos, gt), nm),
        em.and_(em.or_(pos, em.and_(neg, lt)), nm),
    )
    if op == "gt":
        return gt_row
    if op == "ge":
        return em.or_(gt_row, eq_row)
    raise ValueError(f"unknown BSI comparison op {op!r}")


def lower_between(em, exists, sign, planes, pred_lo, pred_hi):
    """``lo <= v <= hi`` as two lowered ripples; the emitter's value
    numbering shares the pos/neg sign-group rows between them."""
    return em.and_(
        lower_signed_cmp(em, "ge", exists, sign, planes, pred_lo),
        lower_signed_cmp(em, "le", exists, sign, planes, pred_hi),
    )


def decode_minmax(vec, depth: int) -> tuple[int, int] | None:
    """One slice's ``minmax_vec`` output -> ``(value, count)`` in
    Python ints, or None when the slice holds no valued column."""
    count = int(vec[depth + 1])
    if count <= 0:
        return None
    mag = 0
    for k in range(depth):
        if int(vec[k]):
            mag |= 1 << k
    return (-mag if int(vec[depth]) else mag), count


def decode_sum(vec, depth: int) -> tuple[int, int]:
    """One slice's ``sum_vec`` output -> ``(sum, count)`` in Python
    ints (exact at any depth — the weights never touch device
    arithmetic)."""
    total = 0
    for k in range(depth):
        total += (1 << k) * (int(vec[k]) - int(vec[depth + k]))
    return total, int(vec[2 * depth])
