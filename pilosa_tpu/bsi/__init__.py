"""Bit-sliced integer (BSI) fields — schema, layout, and wire shapes.

An integer per column is stored O'Neil/Quass-style as bit-planes inside
an ordinary frame view named ``field_<name>``, using the exact row
layout the rest of the storage stack already understands (fragments,
HBM mirrors, sync, backup/restore — none of them special-case BSI):

* row 0 (``ROW_EXISTS``) — the not-null plane: bit set iff the column
  has a value;
* row 1 (``ROW_SIGN``)   — sign plane: bit set iff the value is
  negative (zero always stores sign 0);
* row ``2+k`` (``ROW_BIT_BASE + k``) — bit ``k`` of the magnitude
  ``abs(value)``.

A field's ``bit_depth`` is the number of magnitude planes needed for
``max(abs(min), abs(max))``.  Compile shapes bucket the depth to
multiples of ``DEPTH_BLOCK`` (padded planes are identically zero), so
every field in a depth bucket shares one fused XLA program per
operation kind — and one coalescer compile key.

Comparison predicates travel to the device as DATA, not compile-time
constants: :func:`pred_row` packs the predicate's magnitude bits and
sign flag into one ordinary uint32 slice-row (word ``k`` holds bit
``k``, word ``bucket`` holds the sign flag), so a new predicate value
never triggers a recompile.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from pilosa_tpu.ops import bitplane as bp

# Field view naming (matches later-Pilosa's field view convention).
VIEW_FIELD_PREFIX = "field_"

# Plane rows within a field view.
ROW_EXISTS = 0
ROW_SIGN = 1
ROW_BIT_BASE = 2

# Depth bucket: magnitude plane counts round up to a multiple of this,
# so fields of depth 3 and 7 share the depth-8 compiled programs.
DEPTH_BLOCK = 8
# Magnitudes must fit an int64 with headroom for host arithmetic.
MAX_DEPTH = 62

# PQL comparison operator -> canonical op tag used in compile keys.
OPS = {
    "<": "lt",
    "<=": "le",
    "==": "eq",
    "!=": "ne",
    ">=": "ge",
    ">": "gt",
    "><": "between",
}


class BSIError(ValueError):
    pass


@dataclass(frozen=True)
class BSIField:
    """One integer field of a range-enabled frame."""

    name: str
    min: int
    max: int

    @property
    def bit_depth(self) -> int:
        return bit_depth_for(self.min, self.max)

    @property
    def view(self) -> str:
        return field_view_name(self.name)

    def to_dict(self) -> dict:
        return {"name": self.name, "type": "int", "min": self.min, "max": self.max}


@dataclass(frozen=True)
class ValCount:
    """Aggregate result: Sum returns (sum, n-columns); Min/Max return
    (extreme value, n-columns holding it).  JSON renders as
    ``{"value":..., "count":...}``; the internal protobuf leg rides the
    existing Pairs message (net/codec.py)."""

    value: int
    count: int


def field_view_name(field: str) -> str:
    return VIEW_FIELD_PREFIX + field


def is_field_view(view: str) -> bool:
    return view.startswith(VIEW_FIELD_PREFIX)


def bit_depth_for(lo: int, hi: int) -> int:
    """Magnitude planes needed to represent every value in [lo, hi]
    sign-magnitude (at least one, so a {0}-only field still has a
    stable layout)."""
    mag = max(abs(int(lo)), abs(int(hi)))
    return max(1, int(mag).bit_length())


def validate_field(name: str, lo: int, hi: int) -> None:
    from pilosa_tpu.core.names import validate_label

    validate_label(name)
    if lo > hi:
        raise BSIError(f"field min ({lo}) must be <= max ({hi})")
    if bit_depth_for(lo, hi) > MAX_DEPTH:
        raise BSIError(f"field range needs more than {MAX_DEPTH} bit planes")


def pad_depth(depth: int) -> int:
    """Round a magnitude depth up to its compile bucket."""
    if depth <= 0:
        return DEPTH_BLOCK
    return ((depth + DEPTH_BLOCK - 1) // DEPTH_BLOCK) * DEPTH_BLOCK


def pred_row(value: int, bucket: int) -> np.ndarray:
    """Pack one signed predicate into a uint32 slice-row: word ``k``
    (k < bucket) holds bit ``k`` of ``abs(value)``, word ``bucket``
    holds the sign flag.  Shaped exactly like a bitmap leaf row, so
    predicates flow through the existing batch assembly, batch cache,
    and coalescer unchanged — predicate VALUES are data, never part of
    a compile key."""
    row = bp.empty_row()
    mag = abs(int(value))
    for k in range(bucket):
        row[k] = (mag >> k) & 1
    row[bucket] = 1 if value < 0 else 0
    return row


def clamp_predicate(op: str, value: int, depth: int) -> tuple[str, int]:
    """Rewrite an out-of-range predicate to an equivalent in-range one.

    Magnitude planes carry ``depth`` bits, so the representable window
    is [-(2^depth - 1), 2^depth - 1]; a predicate outside it truncates
    in the bit packing and would compare WRONG.  Every comparison
    against an out-of-window constant has an exact in-window equivalent
    (all-match ones get the loosest in-window bound, never-match ones a
    strictly-impossible bound), so the device ripple stays oblivious.
    """
    hi = (1 << depth) - 1
    lo = -hi
    value = int(value)
    if lo <= value <= hi:
        return op, value
    if value > hi:
        return {
            "lt": ("le", hi),
            "le": ("le", hi),
            "eq": ("gt", hi),   # empty
            "ne": ("le", hi),   # everything with a value
            "gt": ("gt", hi),   # empty
            "ge": ("gt", hi),   # empty
        }[op]
    return {
        "gt": ("ge", lo),
        "ge": ("ge", lo),
        "eq": ("lt", lo),   # empty
        "ne": ("ge", lo),   # everything with a value
        "lt": ("lt", lo),   # empty
        "le": ("lt", lo),   # empty
    }[op]


def clamp_between(a: int, b: int, depth: int) -> tuple[int, int]:
    """Clamp a between-range to the representable window; an empty
    window stays empty (a > b yields no matches in the ripple)."""
    hi = (1 << depth) - 1
    lo = -hi
    a, b = int(a), int(b)
    if a > b:
        return hi, lo  # canonical empty range
    if b < lo or a > hi:
        return hi, lo
    return max(a, lo), min(b, hi)


def value_bit_rows(
    field: BSIField, column_ids: np.ndarray, values: np.ndarray
) -> tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Vectorized sign-magnitude encoding of a columnar import:
    returns ``(set_rows, set_cols, clear_rows, clear_cols)`` — the
    plane bits to set and the plane bits to clear (stale bits from a
    previous value of the column).  Every plane row of every imported
    column appears in exactly one of the two lists, so re-importing a
    column fully overwrites its old value."""
    cols = np.asarray(column_ids, dtype=np.int64)
    vals = np.asarray(values, dtype=np.int64)
    if len(cols) != len(vals):
        raise BSIError("mismatch of column/value len")
    if len(vals) and (
        int(vals.min()) < field.min or int(vals.max()) > field.max
    ):
        raise BSIError(
            f"value out of range for field {field.name!r}"
            f" [{field.min}, {field.max}]"
        )
    depth = field.bit_depth
    mag = np.abs(vals)
    neg = vals < 0

    set_rows: list[np.ndarray] = [np.zeros(len(cols), np.int64)]  # exists
    set_cols: list[np.ndarray] = [cols]
    clear_rows: list[np.ndarray] = []
    clear_cols: list[np.ndarray] = []

    def route(row_id: int, mask: np.ndarray) -> None:
        on = cols[mask]
        off = cols[~mask]
        if len(on):
            set_rows.append(np.full(len(on), row_id, np.int64))
            set_cols.append(on)
        if len(off):
            clear_rows.append(np.full(len(off), row_id, np.int64))
            clear_cols.append(off)

    route(ROW_SIGN, neg)
    for k in range(depth):
        route(ROW_BIT_BASE + k, ((mag >> k) & 1).astype(bool))

    return (
        np.concatenate(set_rows),
        np.concatenate(set_cols),
        np.concatenate(clear_rows) if clear_rows else np.zeros(0, np.int64),
        np.concatenate(clear_cols) if clear_cols else np.zeros(0, np.int64),
    )
