"""PQL — the Pilosa Query Language.

Grammar and semantics match the reference parser (reference: pql/parser.go,
pql/scanner.go, pql/ast.go): a query is a sequence of calls; a call is
``Name(child1(...), child2(...), key=value, ...)``; values are
bool/null/ident/string/int64/float64/list.  The canonical ``str()`` form
(sorted argument keys, Go-style quoting) is wire-compatible with the
reference so remote call forwarding and test fixtures interoperate.
"""

from pilosa_tpu.pql.parser import (
    Call,
    Cond,
    ParseError,
    Query,
    TIME_FORMAT,
    parse_string,
)

__all__ = ["Call", "Cond", "ParseError", "Query", "TIME_FORMAT", "parse_string"]
