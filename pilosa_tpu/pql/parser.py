"""PQL lexer, recursive-descent parser, and AST.

Behavioral parity with the reference (reference: pql/scanner.go:36-285,
pql/parser.go:45-260, pql/ast.go:27-241), re-written Python-idiomatically:
the lexer is a small regex-driven tokenizer instead of a rune state
machine, and the parser keeps the reference's semantics —

* identifiers: ``[A-Za-z][A-Za-z0-9_.-]*``
* numbers: optional leading ``-``, digits, at most one ``.`` (dot => float)
* strings: single- or double-quoted; escapes ``\\n \\\\ \\" \\'``;
  unterminated / newline / unknown escape are errors ("bad string")
* values: ``true``/``false``/``null`` (bare idents), ident, string,
  int, float, or a bracketed list of primitives
* children are parsed before keyword args; duplicate arg keys are errors
* canonical ``str()``: sorted arg keys, children first, Go-style quoting
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Any

# Go-style time layout used for string timestamps (reference: pql/parser.go:25)
TIME_FORMAT = "%Y-%m-%dT%H:%M"

# Mutating call names (reference: pql/ast.go:32-41)
WRITE_CALLS = frozenset({"SetBit", "ClearBit", "SetRowAttrs", "SetColumnAttrs"})


class ParseError(ValueError):
    def __init__(self, message: str, line: int = 0, char: int = 0):
        super().__init__(f"{message} at line {line}, char {char}")
        self.message = message
        self.line = line
        self.char = char


# --- tokenizer -------------------------------------------------------------

IDENT, STRING, INTEGER, FLOAT, LPAREN, RPAREN, LBRACK, RBRACK, COMMA, EQ, EOF = (
    "IDENT", "STRING", "INTEGER", "FLOAT", "(", ")", "[", "]", ",", "=", "EOF",
)
# Comparison token (BSI range predicates): lit holds the operator text.
CMP = "CMP"

# Comparison operators accepted between an argument key and its value
# (``Range(field > 100)``); ``><`` is the inclusive between operator.
# Longest-first so ``>=`` never lexes as ``>`` ``=``.
COMPARISON_OPS = ("><", ">=", "<=", "==", "!=", ">", "<")

_TOKEN_RE = re.compile(
    r"""
    (?P<ws>\s+)
  | (?P<ident>[A-Za-z][A-Za-z0-9_.\-]*)
  | (?P<number>-?(?:\d+(?:\.\d*)?|\.\d+))
  | (?P<cmp>><|>=|<=|==|!=|>|<)
  | (?P<punct>[()\[\],=])
  | (?P<quote>["'])
    """,
    re.VERBOSE,
)

_ESCAPES = {"n": "\n", "\\": "\\", '"': '"', "'": "'"}


@dataclass
class _Token:
    kind: str
    lit: Any
    line: int
    char: int


def _tokenize(s: str) -> list[_Token]:
    tokens: list[_Token] = []
    pos = 0
    line, char = 0, 0

    def advance(text: str):
        nonlocal line, char
        nl = text.count("\n")
        if nl:
            line += nl
            char = len(text) - text.rfind("\n") - 1
        else:
            char += len(text)

    while pos < len(s):
        m = _TOKEN_RE.match(s, pos)
        if m is None:
            raise ParseError(f"illegal character {s[pos]!r}", line, char)
        start_line, start_char = line, char
        if m.lastgroup == "ws":
            advance(m.group())
            pos = m.end()
            continue
        if m.lastgroup == "ident":
            tokens.append(_Token(IDENT, m.group(), start_line, start_char))
        elif m.lastgroup == "number":
            lit = m.group()
            kind = FLOAT if "." in lit else INTEGER
            tokens.append(_Token(kind, lit, start_line, start_char))
        elif m.lastgroup == "cmp":
            tokens.append(_Token(CMP, m.group(), start_line, start_char))
        elif m.lastgroup == "punct":
            tokens.append(_Token(m.group(), m.group(), start_line, start_char))
        else:  # quoted string
            quote = m.group()
            buf = []
            i = m.end()
            while True:
                if i >= len(s) or s[i] == "\n":
                    raise ParseError("bad string", start_line, start_char)
                c = s[i]
                if c == quote:
                    i += 1
                    break
                if c == "\\":
                    if i + 1 >= len(s) or s[i + 1] not in _ESCAPES:
                        raise ParseError("bad string", start_line, start_char)
                    buf.append(_ESCAPES[s[i + 1]])
                    i += 2
                    continue
                buf.append(c)
                i += 1
            tokens.append(_Token(STRING, "".join(buf), start_line, start_char))
            advance(s[pos:i])
            pos = i
            continue
        advance(m.group())
        pos = m.end()
    tokens.append(_Token(EOF, "", line, char))
    return tokens


# --- AST -------------------------------------------------------------------


def _go_quote(v: str) -> str:
    """Go %q-style double-quoted string."""
    out = ['"']
    for c in v:
        if c == '"':
            out.append('\\"')
        elif c == "\\":
            out.append("\\\\")
        elif c == "\n":
            out.append("\\n")
        elif c == "\t":
            out.append("\\t")
        else:
            out.append(c)
    out.append('"')
    return "".join(out)


def _go_value(v: Any) -> str:
    """Go %v-style formatting for arg values."""
    if isinstance(v, bool):
        return "true" if v else "false"
    if v is None:
        # "null" (not Go's "%v" rendering "<nil>") so the canonical string
        # re-parses: remote forwarding ships str(query) as the wire format.
        return "null"
    if isinstance(v, str):
        return _go_quote(v)
    if isinstance(v, float):
        s = repr(v)
        return s[:-2] if s.endswith(".0") else s
    if isinstance(v, list):
        return "[" + ",".join(
            _go_quote(x) if isinstance(x, str) else _go_value(x) for x in v
        ) + "]"
    return str(v)


@dataclass(frozen=True)
class Cond:
    """A comparison-argument value: ``Range(field > 100)`` parses the
    ``field`` arg to ``Cond(op=">", value=100)``; ``field >< [a, b]``
    (inclusive between) carries a two-int list.  Canonical ``str()``
    renders ``key op value`` so BSI queries survive the remote-
    forwarding round trip (str -> parse) byte-identically."""

    op: str
    value: Any

    def render(self, key: str) -> str:
        return f"{key} {self.op} {_go_value(self.value)}"


@dataclass
class Call:
    """One function call node (reference: pql/ast.go:52-57)."""

    name: str
    args: dict[str, Any] = field(default_factory=dict)
    children: list["Call"] = field(default_factory=list)

    def uint_arg(self, key: str) -> int | None:
        """Read an integer argument; None when absent; TypeError when the
        value is not an integer (reference: Call.UintArg, pql/ast.go:64-77).
        Negative int64s wrap to uint64 like the reference's cast."""
        if key not in self.args:
            return None
        val = self.args[key]
        if isinstance(val, bool) or not isinstance(val, int):
            raise TypeError(
                f"could not convert {val!r} of type {type(val).__name__} to "
                f"uint64 in Call.uint_arg"
            )
        return val & 0xFFFFFFFFFFFFFFFF

    def uint_slice_arg(self, key: str) -> list[int] | None:
        """Read a list-of-integers argument (reference: Call.UintSliceArg,
        pql/ast.go:82-101)."""
        if key not in self.args:
            return None
        val = self.args[key]
        if not isinstance(val, list) or any(
            isinstance(v, bool) or not isinstance(v, int) for v in val
        ):
            raise TypeError(f"unexpected type in uint_slice_arg, val {val!r}")
        return [v & 0xFFFFFFFFFFFFFFFF for v in val]

    def clone(self) -> "Call":
        return Call(
            name=self.name,
            args=dict(self.args),
            children=[c.clone() for c in self.children],
        )

    def supports_inverse(self) -> bool:
        """reference: pql/ast.go:186-189"""
        return self.name in ("Bitmap", "TopN")

    def is_inverse(self, row_label: str, column_label: str) -> bool:
        """Inverse-view orientation detection (reference: pql/ast.go:191-211)."""
        if not self.supports_inverse():
            return False
        if self.name == "TopN":
            return self.args.get("inverse") is True
        try:
            row = self.uint_arg(row_label)
            col = self.uint_arg(column_label)
        except TypeError:
            return False
        return row is None and col is not None

    def conditions(self) -> dict[str, "Cond"]:
        """The comparison-valued args (BSI range predicates)."""
        return {k: v for k, v in self.args.items() if isinstance(v, Cond)}

    def __str__(self) -> str:
        parts = [str(c) for c in self.children]
        parts += [
            v.render(k) if isinstance(v, Cond) else f"{k}={_go_value(v)}"
            for k, v in sorted(self.args.items(), key=lambda kv: kv[0])
        ]
        return f"{self.name or '!UNNAMED'}({', '.join(parts)})"


@dataclass
class Query:
    """A parsed PQL query: a list of calls (reference: pql/ast.go:27-29)."""

    calls: list[Call] = field(default_factory=list)

    def write_call_n(self) -> int:
        """Number of mutating calls (reference: pql/ast.go:32-41)."""
        return sum(1 for c in self.calls if c.name in WRITE_CALLS)

    def __str__(self) -> str:
        return "\n".join(str(c) for c in self.calls)


# --- parser ----------------------------------------------------------------


class _Parser:
    def __init__(self, tokens: list[_Token]):
        self.tokens = tokens
        self.i = 0

    def peek(self, ahead: int = 0) -> _Token:
        return self.tokens[min(self.i + ahead, len(self.tokens) - 1)]

    def next(self) -> _Token:
        t = self.peek()
        if t.kind != EOF:
            self.i += 1
        return t

    def expect(self, kind: str) -> _Token:
        t = self.next()
        if t.kind != kind:
            raise ParseError(f"expected {kind}, found {t.lit!r}", t.line, t.char)
        return t

    def parse_query(self) -> Query:
        calls = []
        while self.peek().kind != EOF:
            calls.append(self.parse_call())
        if not calls:
            raise ParseError("unexpected EOF: query is empty", 0, 0)
        return Query(calls=calls)

    def parse_call(self) -> Call:
        t = self.next()
        if t.kind != IDENT:
            raise ParseError(f"expected identifier, found: {t.lit}", t.line, t.char)
        call = Call(name=t.lit)
        self.expect(LPAREN)

        # children first: lookahead IDENT + LPAREN means a nested call
        while self.peek().kind == IDENT and self.peek(1).kind == LPAREN:
            call.children.append(self.parse_call())
            t = self.peek()
            if t.kind == RPAREN:
                break
            if t.kind != COMMA:
                raise ParseError(
                    f"expected comma or right paren, found {t.lit!r}",
                    t.line, t.char,
                )
            self.next()

        # keyword arguments
        while self.peek().kind != RPAREN:
            t = self.next()
            if t.kind != IDENT:
                raise ParseError(
                    f"expected argument key, found {t.lit!r}", t.line, t.char
                )
            key = t.lit
            eq = self.next()
            if eq.kind == CMP:
                value = Cond(op=eq.lit, value=self.parse_value())
            elif eq.kind == EQ:
                value = self.parse_value()
            else:
                raise ParseError(
                    f"expected equals sign, found {eq.lit!r}", eq.line, eq.char
                )
            if key in call.args:
                raise ParseError(f"argument key already used: {key}", t.line, t.char)
            call.args[key] = value
            t = self.peek()
            if t.kind == RPAREN:
                break
            if t.kind != COMMA:
                raise ParseError(
                    f"expected comma or right paren, found {t.lit!r}",
                    t.line, t.char,
                )
            self.next()

        self.expect(RPAREN)
        return call

    def parse_value(self) -> Any:
        t = self.next()
        if t.kind == IDENT:
            if t.lit == "true":
                return True
            if t.lit == "false":
                return False
            if t.lit == "null":
                return None
            return t.lit
        if t.kind == STRING:
            return t.lit
        if t.kind == INTEGER:
            return int(t.lit)
        if t.kind == FLOAT:
            return float(t.lit)
        if t.kind == LBRACK:
            return self.parse_list()
        raise ParseError(f"invalid argument value: {t.lit!r}", t.line, t.char)

    def parse_list(self) -> list:
        """Bracketed list of primitives (reference: pql/parser.go:262-296;
        used by TopN filters)."""
        values = []
        while True:
            t = self.next()
            if t.kind == IDENT:
                if t.lit == "true":
                    values.append(True)
                elif t.lit == "false":
                    values.append(False)
                else:
                    values.append(t.lit)
            elif t.kind == STRING:
                values.append(t.lit)
            elif t.kind == INTEGER:
                values.append(int(t.lit))
            else:
                raise ParseError(f"invalid list value: {t.lit!r}", t.line, t.char)
            t = self.next()
            if t.kind == RBRACK:
                return values
            if t.kind != COMMA:
                raise ParseError(f"expected comma, found {t.lit!r}", t.line, t.char)


def parse_string(s: str) -> Query:
    """Parse a PQL string into a Query (reference: pql.ParseString,
    pql/parser.go:40-42)."""
    return _Parser(_tokenize(s)).parse_query()
