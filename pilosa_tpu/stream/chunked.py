"""HTTP/1.1 chunked transfer-coding framing (RFC 7230 §4.1).

The encoder side frames iterator response bodies; the reader side gives
handlers a file object that decodes an incoming chunked request body
incrementally, so a streamed restore never materializes the archive.
``LengthBodyReader`` is the Content-Length twin — same interface, so
handler code is agnostic to how the client framed the body.
"""

from __future__ import annotations

CHUNK_TERMINATOR = b"0\r\n\r\n"

# drain() gives up past this many unread body bytes and tells the
# caller to drop the connection instead: reading a huge abandoned body
# just to keep one keep-alive socket is a bad trade.
_DRAIN_LIMIT = 1 << 20


def encode_chunk(data: bytes) -> bytes:
    """One chunked-coding frame: hex length, CRLF, payload, CRLF."""
    return b"%x\r\n%s\r\n" % (len(data), data)


class LengthBodyReader:
    """File-like over exactly ``length`` bytes of ``fp`` — the
    Content-Length body framing."""

    def __init__(self, fp, length: int):
        self._fp = fp
        self._remaining = max(0, int(length))
        # Total body bytes consumed — the adapter's stream.bytesReceived
        # counter reads this after the request completes.
        self.bytes_read = 0

    def read(self, n: int = -1) -> bytes:
        if self._remaining <= 0:
            return b""
        want = self._remaining if n is None or n < 0 else min(n, self._remaining)
        data = self._fp.read(want)
        self._remaining -= len(data)
        self.bytes_read += len(data)
        if not data:
            self._remaining = 0  # peer hung up early
        return data

    def drain(self) -> bool:
        """Consume the unread remainder so the connection can be
        reused; False when past the drain budget (caller should close
        the connection instead)."""
        if self._remaining > _DRAIN_LIMIT:
            return False
        while self._remaining > 0:
            if not self.read(min(self._remaining, 64 * 1024)):
                break
        return True


class ChunkedBodyReader:
    """File-like over a chunked-coded body on ``fp``, decoding frames
    incrementally (never more than one frame buffered)."""

    def __init__(self, fp):
        self._fp = fp
        self._chunk_left = 0  # unread bytes of the current frame
        self._done = False
        # Decoded body bytes consumed (frame payloads only, not the
        # chunked framing) — see LengthBodyReader.bytes_read.
        self.bytes_read = 0

    def _next_frame(self) -> None:
        line = self._fp.readline(1024)
        if not line:
            self._done = True
            return
        # Tolerate the CRLF that terminates the previous frame's data.
        if line in (b"\r\n", b"\n"):
            line = self._fp.readline(1024)
        size_s = line.split(b";", 1)[0].strip()  # ignore chunk extensions
        try:
            size = int(size_s, 16)
        except ValueError:
            raise ValueError(f"invalid chunk size: {size_s[:32]!r}") from None
        if size == 0:
            # Trailer section: read through the blank line.
            while True:
                t = self._fp.readline(1024)
                if t in (b"\r\n", b"\n", b""):
                    break
            self._done = True
        else:
            self._chunk_left = size

    def read(self, n: int = -1) -> bytes:
        if n is None or n < 0:
            parts = []
            while True:
                part = self.read(64 * 1024)
                if not part:
                    break
                parts.append(part)
            return b"".join(parts)
        out = b""
        while len(out) < n and not self._done:
            if self._chunk_left == 0:
                self._next_frame()
                continue
            want = min(n - len(out), self._chunk_left)
            data = self._fp.read(want)
            if not data:
                self._done = True  # peer hung up mid-frame
                break
            self._chunk_left -= len(data)
            self.bytes_read += len(data)
            out += data
        return out

    def drain(self) -> bool:
        """Read through the terminal frame; False past the budget."""
        seen = 0
        while not self._done:
            seen += len(self.read(64 * 1024))
            if seen > _DRAIN_LIMIT:
                return False
        return True
