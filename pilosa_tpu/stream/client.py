"""Consumer-side streaming: retrying stream opener + chunk iterator.

``http.client`` responses already decode chunked transfer-coding, so
the consuming side only needs (a) a closeable constant-size chunk
iterator that owns the connection, and (b) retry/backoff around
OPENING a stream — the window where retrying an idempotent GET is
always safe.  Mid-stream failures surface to the caller: without range
requests a half-consumed body cannot be resumed transparently.
"""

from __future__ import annotations

import http.client
import time
from collections.abc import Callable, Iterator

# Transient transport failures worth a fresh dial; HTTP-status errors
# (our ClientError) are NOT retried — the server answered.
RETRYABLE = (OSError, http.client.HTTPException)


def open_with_retry(
    open_fn: Callable,
    attempts: int = 3,
    backoff: float = 0.1,
    logger=None,
):
    """Call ``open_fn()`` until it returns, retrying RETRYABLE failures
    with exponential backoff (``backoff``, 2x per attempt).  The last
    failure propagates."""
    delay = backoff
    for attempt in range(attempts):
        try:
            return open_fn()
        except RETRYABLE as e:
            if attempt == attempts - 1:
                raise
            if logger is not None:
                logger(f"stream open failed (attempt {attempt + 1}): {e}")
            time.sleep(delay)
            delay *= 2


class HTTPBodyStream:
    """A response body being consumed incrementally.

    Owns the connection: close() (or exhausting the iterator, or the
    ``with`` block) releases it.  ``read``/``__iter__`` move constant
    ``chunk_bytes`` chunks, whatever the server's frame sizes were.
    """

    def __init__(self, resp, conn, chunk_bytes: int = 0):
        from pilosa_tpu import stream

        self._resp = resp
        self._conn = conn
        self.chunk_bytes = chunk_bytes or stream.DEFAULT_CHUNK_BYTES
        self.status = resp.status
        self.headers = resp.headers

    def read(self, n: int = -1) -> bytes:
        return self._resp.read(n if n is not None and n >= 0 else None)

    def __iter__(self) -> Iterator[bytes]:
        try:
            while True:
                chunk = self._resp.read(self.chunk_bytes)
                if not chunk:
                    return
                yield chunk
        finally:
            self.close()

    def close(self) -> None:
        try:
            self._resp.close()
        finally:
            self._conn.close()

    def __enter__(self) -> "HTTPBodyStream":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
