"""Streaming data plane — chunked producer/consumer primitives.

The reference moves every large HTTP body incrementally: the CSV export
handler writes rows straight to the ResponseWriter (reference:
handler.go:1049-1098), backup/restore copy fragment archives through
io.Reader/io.Writer pairs (reference: client.go:478-702), and the
importer never materializes a file.  This package gives the Python side
the same shape, so a 1B-column fragment export/backup moves as
constant-size chunks end to end instead of one process-killing blob:

* :class:`ChunkPipe` (pipe.py) — a bounded byte-chunk queue with
  producer backpressure; adapts writer-style producers (``fn(w)``) to
  pull-style chunk iterators via :func:`generate_from_writer`.
* :class:`IterBody` (body.py) — response-body wrapper around any
  iterable of bytes, re-chunked to a constant chunk size so socket
  writes stay bounded no matter how the producer batches.
* chunked.py — HTTP/1.1 chunked transfer-coding framing: the encoder
  used by the server adapter for iterator response bodies, and
  file-like readers that decode chunked (or Content-Length-bounded)
  request bodies incrementally.
* client.py — the consuming side: a retry/backoff-aware stream opener
  for idempotent GETs plus :class:`HTTPBodyStream`, a closeable
  constant-size chunk iterator over an ``http.client`` response.

Everything here is transport-plumbing only: no holder/fragment imports,
so net, cli, sync, and core can all ride it without cycles.
"""

from __future__ import annotations

from collections.abc import Iterable, Iterator
from typing import TypeVar

# One knob for every streaming path: response re-chunking, pipe chunk
# assembly, and client-side reads all default to this size.  Configured
# per server via [net] stream-chunk-bytes.
DEFAULT_CHUNK_BYTES = 64 * 1024

from pilosa_tpu.stream.pipe import (  # noqa: E402
    ChunkPipe,
    PipeAbortedError,
    generate_from_writer,
)
from pilosa_tpu.stream.body import IterBody, rechunk  # noqa: E402
from pilosa_tpu.stream.chunked import (  # noqa: E402
    CHUNK_TERMINATOR,
    ChunkedBodyReader,
    LengthBodyReader,
    encode_chunk,
)
from pilosa_tpu.stream.client import HTTPBodyStream, open_with_retry  # noqa: E402

_T = TypeVar("_T")


def batched(items: Iterable[_T], n: int) -> Iterator[list[_T]]:
    """Yield ``items`` in lists of at most ``n`` — the bounded-batch
    analog of rechunk() for non-byte streams (e.g. the syncer's repair
    pushes, which must stay under max-writes-per-request)."""
    if n <= 0:
        raise ValueError("batch size must be positive")
    buf: list[_T] = []
    for item in items:
        buf.append(item)
        if len(buf) >= n:
            yield buf
            buf = []
    if buf:
        yield buf


__all__ = [
    "DEFAULT_CHUNK_BYTES",
    "CHUNK_TERMINATOR",
    "ChunkPipe",
    "ChunkedBodyReader",
    "HTTPBodyStream",
    "IterBody",
    "LengthBodyReader",
    "PipeAbortedError",
    "batched",
    "encode_chunk",
    "generate_from_writer",
    "open_with_retry",
    "rechunk",
]
