"""IterBody — iterator response bodies with constant-size chunks.

Producers batch however suits them (csv_chunks yields per row-block,
tar writers per archive entry); the transport wants bounded writes.
IterBody sits between: any iterable of bytes in, fixed-size chunks out,
with ``close()`` teardown reaching the underlying generator so an
abandoned response (client disconnect) releases producer resources.
"""

from __future__ import annotations

from collections.abc import Iterable, Iterator


def rechunk(chunks: Iterable[bytes], chunk_bytes: int) -> Iterator[bytes]:
    """Re-slice a byte-chunk stream into chunks of exactly
    ``chunk_bytes`` (except the final tail), buffering at most one
    output chunk plus one input chunk."""
    if chunk_bytes <= 0:
        raise ValueError("chunk_bytes must be positive")
    pend: list[bytes] = []
    pend_n = 0
    for data in chunks:
        pend.append(data)
        pend_n += len(data)
        while pend_n >= chunk_bytes:
            buf = b"".join(pend)
            out, rest = buf[:chunk_bytes], buf[chunk_bytes:]
            pend = [rest] if rest else []
            pend_n = len(rest)
            yield out
    if pend_n:
        yield b"".join(pend)


class IterBody:
    """A response body produced incrementally.

    Wraps an iterable of byte chunks; iterating yields constant
    ``chunk_bytes``-sized chunks regardless of producer batching.  The
    HTTP adapter streams these with chunked transfer encoding instead
    of materializing one blob (net/handler.py make_http_server).
    """

    def __init__(self, chunks: Iterable[bytes], chunk_bytes: int = 0):
        from pilosa_tpu import stream

        self._source = chunks
        self.chunk_bytes = chunk_bytes or stream.DEFAULT_CHUNK_BYTES

    def __iter__(self) -> Iterator[bytes]:
        return rechunk(self._source, self.chunk_bytes)

    def close(self) -> None:
        close = getattr(self._source, "close", None)
        if close is not None:
            close()
