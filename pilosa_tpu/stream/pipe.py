"""ChunkPipe — bounded byte-chunk queue with producer backpressure.

The bridge between writer-style producers (``tarfile`` wants a file
object; ``Fragment.write_to`` takes ``w``) and the pull-style chunk
iterators the HTTP layer streams from.  The queue is bounded, so a
producer running ahead of a slow consumer blocks instead of buffering
the whole body — the in-process analog of the reference handing an
io.PipeWriter to the tar writer while the ResponseWriter drains the
read end (reference: client.go:478-560).
"""

from __future__ import annotations

import threading
from collections import deque
from collections.abc import Callable, Iterator


class PipeAbortedError(RuntimeError):
    """The consumer went away (or the producer failed) mid-stream."""


class ChunkPipe:
    """Bounded queue of byte chunks: file-like on the write side,
    iterator on the read side.

    * ``write`` assembles input into ``chunk_bytes``-sized chunks and
      blocks while ``capacity`` chunks are already queued
      (backpressure); ``close`` flushes the partial tail chunk and
      marks EOF.
    * Iterating yields chunks until EOF; ``abort`` from either side
      unblocks both (the writer raises :class:`PipeAbortedError`, the
      reader raises the given exception — or stops, when aborted
      without one).
    """

    def __init__(self, capacity: int = 8, chunk_bytes: int = 0):
        from pilosa_tpu import stream

        if capacity <= 0:
            raise ValueError("capacity must be positive")
        self.chunk_bytes = chunk_bytes or stream.DEFAULT_CHUNK_BYTES
        self.capacity = capacity
        self._chunks: deque[bytes] = deque()
        self._pend: list[bytes] = []  # partial tail, < chunk_bytes total
        self._pend_n = 0
        self._eof = False
        self._exc: BaseException | None = None
        self._aborted = False
        self._cond = threading.Condition()

    # -- writer side (file-like) ---------------------------------------

    def write(self, data) -> int:
        data = bytes(data)
        with self._cond:
            if self._aborted:
                raise PipeAbortedError("pipe aborted")
            if self._eof:
                raise ValueError("write to closed pipe")
            self._pend.append(data)
            self._pend_n += len(data)
            while self._pend_n >= self.chunk_bytes:
                buf = b"".join(self._pend)
                chunk, rest = buf[: self.chunk_bytes], buf[self.chunk_bytes :]
                self._pend = [rest] if rest else []
                self._pend_n = len(rest)
                self._put_locked(chunk)
                if self._aborted:
                    raise PipeAbortedError("pipe aborted")
        return len(data)

    def _put_locked(self, chunk: bytes) -> None:
        while len(self._chunks) >= self.capacity and not self._aborted:
            self._cond.wait()
        if self._aborted:
            return
        self._chunks.append(chunk)
        self._cond.notify_all()

    def flush(self) -> None:  # file-object protocol
        pass

    def close(self) -> None:
        """Producer EOF: flush the partial tail and wake the consumer."""
        with self._cond:
            if self._eof or self._aborted:
                return
            if self._pend_n:
                self._put_locked(b"".join(self._pend))
                self._pend, self._pend_n = [], 0
            self._eof = True
            self._cond.notify_all()

    def abort(self, exc: BaseException | None = None) -> None:
        """Tear the pipe down from either side: pending chunks drop, the
        blocked peer wakes, and (when ``exc`` is given) the consumer
        re-raises it."""
        with self._cond:
            if self._exc is None:
                self._exc = exc
            self._aborted = True
            self._eof = True
            self._chunks.clear()
            self._pend, self._pend_n = [], 0
            self._cond.notify_all()

    # -- reader side ---------------------------------------------------

    def __iter__(self) -> Iterator[bytes]:
        while True:
            with self._cond:
                while not self._chunks and not self._eof:
                    self._cond.wait()
                if self._chunks:
                    chunk = self._chunks.popleft()
                    self._cond.notify_all()
                else:  # EOF (or abort) with nothing queued
                    if self._exc is not None:
                        raise self._exc
                    return
            yield chunk


def generate_from_writer(
    write_fn: Callable, capacity: int = 8, chunk_bytes: int = 0
) -> Iterator[bytes]:
    """Run ``write_fn(pipe)`` on a producer thread and yield its output
    as bounded chunks.

    The producer sees an ordinary writable file object; the caller gets
    a generator.  Closing the generator early (consumer gone) aborts
    the pipe so the producer thread unblocks and exits instead of
    leaking; a producer exception re-raises on the consumer side at the
    point of failure.
    """
    pipe = ChunkPipe(capacity=capacity, chunk_bytes=chunk_bytes)

    def _produce() -> None:
        try:
            write_fn(pipe)
        except PipeAbortedError:
            pass  # consumer went away first; nothing to report
        except BaseException as e:  # noqa: BLE001 — crosses the pipe
            pipe.abort(e)
        else:
            pipe.close()

    t = threading.Thread(target=_produce, daemon=True, name="chunk-pipe")
    t.start()
    try:
        yield from pipe
        t.join(timeout=5.0)
    finally:
        pipe.abort()
        t.join(timeout=1.0)
