"""Configuration — TOML file + environment + flag layering.

Schema matches the reference's TOML config (reference: config.go:19-90):
data-dir, host, cluster{replicas, type, hosts, internal-hosts,
polling-interval, internal-port, long-query-time}, anti-entropy.interval,
max-writes-per-request, log-path, metrics{service, host}, plus TPU-mesh
settings that are new here.  Precedence is flag > env (PILOSA_*) > file >
default (reference: cmd/root.go:85-150), and unknown keys in the file
are rejected (reference: cmd/root.go:113-118).
"""

from __future__ import annotations

import os
import tomllib
from dataclasses import dataclass, field

# reference: server.go:33-36, config.go:19-58
DEFAULT_HOST = "localhost:10101"
DEFAULT_INTERNAL_PORT = 14000
DEFAULT_DATA_DIR = "~/.pilosa_tpu"
DEFAULT_ANTI_ENTROPY_INTERVAL = 600
DEFAULT_POLLING_INTERVAL = 60
DEFAULT_MAX_WRITES = 5000

CLUSTER_TYPES = ("static", "http", "gossip")

_KNOWN_KEYS = {
    "data-dir",
    "host",
    "log-path",
    "max-writes-per-request",
    "cluster",
    "cluster.replicas",
    "cluster.type",
    "cluster.hosts",
    "cluster.internal-hosts",
    "cluster.polling-interval",
    "cluster.internal-port",
    "cluster.gossip-seed",
    "cluster.long-query-time",
    "anti-entropy",
    "anti-entropy.interval",
    "metrics",
    "metrics.service",
    "metrics.host",
    "tpu",
    "tpu.mesh-shape",
    "tpu.use-pallas",
}


class ConfigError(ValueError):
    pass


@dataclass
class ClusterConfig:
    replicas: int = 1
    type: str = "static"
    hosts: list[str] = field(default_factory=list)
    internal_hosts: list[str] = field(default_factory=list)
    polling_interval: int = DEFAULT_POLLING_INTERVAL
    internal_port: int = DEFAULT_INTERNAL_PORT
    gossip_seed: str = ""
    long_query_time: float = 0.0


@dataclass
class MetricsConfig:
    service: str = "nop"  # nop | expvar | statsd
    host: str = ""


@dataclass
class TPUConfig:
    """TPU-native additions (no reference counterpart)."""

    mesh_shape: str = ""  # e.g. "8" or "4x2"; empty = all local devices
    use_pallas: bool = False


@dataclass
class Config:
    data_dir: str = DEFAULT_DATA_DIR
    host: str = DEFAULT_HOST
    log_path: str = ""
    max_writes_per_request: int = DEFAULT_MAX_WRITES
    anti_entropy_interval: int = DEFAULT_ANTI_ENTROPY_INTERVAL
    cluster: ClusterConfig = field(default_factory=ClusterConfig)
    metrics: MetricsConfig = field(default_factory=MetricsConfig)
    tpu: TPUConfig = field(default_factory=TPUConfig)

    def validate(self) -> None:
        if self.cluster.type not in CLUSTER_TYPES:
            raise ConfigError(f"invalid cluster type: {self.cluster.type!r}")
        if self.cluster.replicas < 1:
            raise ConfigError("cluster replicas must be >= 1")

    def to_toml(self) -> str:
        """Canonical TOML rendering (generate-config parity,
        reference: ctl/generate_config.go)."""
        lines = [
            f'data-dir = "{self.data_dir}"',
            f'host = "{self.host}"',
            f'log-path = "{self.log_path}"',
            f"max-writes-per-request = {self.max_writes_per_request}",
            "",
            "[cluster]",
            f"  replicas = {self.cluster.replicas}",
            f'  type = "{self.cluster.type}"',
            f"  hosts = {_toml_list(self.cluster.hosts)}",
            f"  internal-hosts = {_toml_list(self.cluster.internal_hosts)}",
            f"  polling-interval = {self.cluster.polling_interval}",
            f"  internal-port = {self.cluster.internal_port}",
            f'  gossip-seed = "{self.cluster.gossip_seed}"',
            f"  long-query-time = {self.cluster.long_query_time}",
            "",
            "[anti-entropy]",
            f"  interval = {self.anti_entropy_interval}",
            "",
            "[metrics]",
            f'  service = "{self.metrics.service}"',
            f'  host = "{self.metrics.host}"',
            "",
            "[tpu]",
            f'  mesh-shape = "{self.tpu.mesh_shape}"',
            f"  use-pallas = {str(self.tpu.use_pallas).lower()}",
        ]
        return "\n".join(lines) + "\n"


def _toml_list(items: list[str]) -> str:
    return "[" + ", ".join(f'"{i}"' for i in items) + "]"


def _reject_unknown(doc: dict, prefix: str = "") -> None:
    for key, value in doc.items():
        dotted = f"{prefix}{key}"
        if dotted not in _KNOWN_KEYS:
            raise ConfigError(f"unknown config key: {dotted!r}")
        if isinstance(value, dict):
            _reject_unknown(value, prefix=dotted + ".")


def from_toml(text: str) -> Config:
    try:
        doc = tomllib.loads(text)
    except tomllib.TOMLDecodeError as e:
        raise ConfigError(str(e)) from e
    _reject_unknown(doc)
    cfg = Config()
    cfg.data_dir = doc.get("data-dir", cfg.data_dir)
    cfg.host = doc.get("host", cfg.host)
    cfg.log_path = doc.get("log-path", cfg.log_path)
    cfg.max_writes_per_request = doc.get(
        "max-writes-per-request", cfg.max_writes_per_request
    )
    cl = doc.get("cluster", {})
    cfg.cluster.replicas = cl.get("replicas", cfg.cluster.replicas)
    cfg.cluster.type = cl.get("type", cfg.cluster.type)
    cfg.cluster.hosts = list(cl.get("hosts", cfg.cluster.hosts))
    cfg.cluster.internal_hosts = list(
        cl.get("internal-hosts", cfg.cluster.internal_hosts)
    )
    cfg.cluster.polling_interval = cl.get(
        "polling-interval", cfg.cluster.polling_interval
    )
    cfg.cluster.internal_port = cl.get("internal-port", cfg.cluster.internal_port)
    cfg.cluster.gossip_seed = cl.get("gossip-seed", cfg.cluster.gossip_seed)
    cfg.cluster.long_query_time = cl.get(
        "long-query-time", cfg.cluster.long_query_time
    )
    ae = doc.get("anti-entropy", {})
    cfg.anti_entropy_interval = ae.get("interval", cfg.anti_entropy_interval)
    mt = doc.get("metrics", {})
    cfg.metrics.service = mt.get("service", cfg.metrics.service)
    cfg.metrics.host = mt.get("host", cfg.metrics.host)
    tp = doc.get("tpu", {})
    cfg.tpu.mesh_shape = tp.get("mesh-shape", cfg.tpu.mesh_shape)
    cfg.tpu.use_pallas = tp.get("use-pallas", cfg.tpu.use_pallas)
    return cfg


_ENV_MAP = {
    "PILOSA_DATA_DIR": ("data_dir", str),
    "PILOSA_HOST": ("host", str),
    "PILOSA_LOG_PATH": ("log_path", str),
    "PILOSA_MAX_WRITES_PER_REQUEST": ("max_writes_per_request", int),
    "PILOSA_CLUSTER_REPLICAS": ("cluster.replicas", int),
    "PILOSA_CLUSTER_TYPE": ("cluster.type", str),
    "PILOSA_CLUSTER_HOSTS": ("cluster.hosts", "csv"),
    "PILOSA_CLUSTER_INTERNAL_HOSTS": ("cluster.internal_hosts", "csv"),
    "PILOSA_CLUSTER_POLLING_INTERVAL": ("cluster.polling_interval", int),
    "PILOSA_CLUSTER_INTERNAL_PORT": ("cluster.internal_port", int),
    "PILOSA_CLUSTER_GOSSIP_SEED": ("cluster.gossip_seed", str),
    "PILOSA_CLUSTER_LONG_QUERY_TIME": ("cluster.long_query_time", float),
    "PILOSA_ANTI_ENTROPY_INTERVAL": ("anti_entropy_interval", int),
    "PILOSA_METRICS_SERVICE": ("metrics.service", str),
    "PILOSA_METRICS_HOST": ("metrics.host", str),
    "PILOSA_TPU_MESH_SHAPE": ("tpu.mesh_shape", str),
    "PILOSA_TPU_USE_PALLAS": ("tpu.use_pallas", "bool"),
}


def _set_dotted(cfg: Config, dotted: str, value) -> None:
    obj = cfg
    *parents, leaf = dotted.split(".")
    for p in parents:
        obj = getattr(obj, p)
    setattr(obj, leaf, value)


def apply_env(cfg: Config, environ=None) -> Config:
    """PILOSA_* environment overlay (reference: cmd/root.go:85-112 uses
    viper's PILOSA prefix)."""
    environ = environ if environ is not None else os.environ
    for env_key, (dotted, typ) in _ENV_MAP.items():
        raw = environ.get(env_key)
        if raw is None:
            continue
        if typ == "csv":
            value = [s.strip() for s in raw.split(",") if s.strip()]
        elif typ == "bool":
            value = raw.lower() in ("1", "true", "yes", "on")
        else:
            value = typ(raw)
        _set_dotted(cfg, dotted, value)
    return cfg


def load(path: str | None = None, environ=None, overrides: dict | None = None) -> Config:
    """flag > env > file > default."""
    if path:
        with open(path, "rb") as f:
            cfg = from_toml(f.read().decode())
    else:
        cfg = Config()
    apply_env(cfg, environ)
    for dotted, value in (overrides or {}).items():
        if value is not None:
            _set_dotted(cfg, dotted, value)
    cfg.validate()
    return cfg
