"""pilosa_tpu — a TPU-native distributed bitmap index.

A brand-new framework with the capabilities of Pilosa (reference:
dingguitao/pilosa): a huge sparse boolean matrix sharded into 2^20-column
"slices", queried through PQL (Bitmap/Union/Intersect/Difference/Count/
TopN/Range + SetBit/ClearBit/attr writes) over an HTTP+protobuf API.

Where the reference executes bitmap algebra with Go roaring containers and
amd64 POPCNT assembly (reference: roaring/roaring.go, roaring/assembly_amd64.s),
this framework keeps fragments as dense HBM-resident bit-planes and compiles
the container ops (AND/OR/XOR/ANDNOT + popcount) to fused XLA programs,
and reduces across a TPU mesh with XLA collectives (Count -> psum,
Union -> OR-reduce) instead of HTTP fan-in.

Layer map (mirrors SURVEY.md §1):
  ops/       bitmap kernel layer (bit-planes, XLA kernels, roaring codec)
  core/      Bitmap row type, Fragment, caches, View/Frame/Index/Holder, attrs
  pql/       the PQL query language (lexer/parser/AST)
  exec/      the distributed query executor (map/reduce)
  parallel/  slice -> TPU-device sharding, mesh collectives
  cluster/   topology: partitioning, jump-hash placement, membership, broadcast
  net/       HTTP API handler, internal client, wire schema
  cli/       command line: server/import/export/backup/restore/check/...
"""

__version__ = "0.1.0"

import os as _os

if _os.environ.get("PILOSA_LOCK_CHECK"):
    # Runtime lock-order validation (analyze/runtime.py): wrap every
    # lock the package creates so acquisition order observed while the
    # suites run is checked against the static analyzer's graph.  Must
    # install BEFORE any submodule creates its module-level locks.
    from pilosa_tpu.analyze import runtime as _lock_check

    _lock_check.install()

from pilosa_tpu.ops.bitplane import SLICE_WIDTH  # noqa: E402

__all__ = ["SLICE_WIDTH", "__version__"]
