"""Roaring codec: encode/decode round trips, op-log replay, corruption
detection (parity tier for roaring/roaring_test.go serialization tests)."""

import struct

import numpy as np
import pytest

from pilosa_tpu.ops import bitplane as bp
from pilosa_tpu.ops import roaring


def bits_to_containers(values):
    out = {}
    for v in values:
        key, off = divmod(int(v), roaring.CONTAINER_BITS)
        if key not in out:
            out[key] = np.zeros(roaring.CONTAINER_WORDS64, dtype=np.uint64)
        out[key][off // 64] |= np.uint64(1) << np.uint64(off % 64)
    return out


def containers_to_bits(containers):
    vals = []
    for key, words in containers.items():
        bits = np.unpackbits(words.view(np.uint8), bitorder="little")
        (pos,) = np.nonzero(bits)
        vals.extend(int(key) * roaring.CONTAINER_BITS + int(p) for p in pos)
    return sorted(vals)


def test_roundtrip_array_container(rng):
    values = sorted(rng.choice(100000, size=500, replace=False))
    data = roaring.encode(bits_to_containers(values))
    got = containers_to_bits(roaring.decode(data))
    assert got == [int(v) for v in values]


def test_roundtrip_bitmap_container(rng):
    # >4096 bits in one container forces bitmap form
    values = sorted(rng.choice(roaring.CONTAINER_BITS, size=10000, replace=False))
    data = roaring.encode(bits_to_containers(values))
    # container payload must be 8 KiB bitmap, not 40 KB array
    info = roaring.info(data)
    assert info.containers[0].type == "bitmap"
    got = containers_to_bits(roaring.decode(data))
    assert got == [int(v) for v in values]


def test_array_bitmap_threshold():
    vals = list(range(4096))
    data = roaring.encode(bits_to_containers(vals))
    assert roaring.info(data).containers[0].type == "array"
    vals = list(range(4097))
    data = roaring.encode(bits_to_containers(vals))
    assert roaring.info(data).containers[0].type == "bitmap"


def test_oplog_replay(rng):
    values = [1, 2, 3, 100000, 2 ** 30]
    data = roaring.encode(bits_to_containers(values))
    data += roaring.encode_op(roaring.OP_ADD, 7)
    data += roaring.encode_op(roaring.OP_REMOVE, 2)
    data += roaring.encode_op(roaring.OP_ADD, 2 ** 40)
    got = containers_to_bits(roaring.decode(data))
    assert got == sorted([1, 3, 7, 100000, 2 ** 30, 2 ** 40])
    assert roaring.info(data).ops == 3


def test_bad_cookie():
    with pytest.raises(roaring.CorruptError):
        roaring.decode(struct.pack("<II", 9999, 0))


def test_bad_op_checksum():
    data = roaring.encode({})
    op = bytearray(roaring.encode_op(roaring.OP_ADD, 5))
    op[9] ^= 0xFF
    with pytest.raises(roaring.CorruptError, match="checksum mismatch"):
        roaring.decode(data + bytes(op))
    assert roaring.check(data + bytes(op))  # non-empty problem list


def test_check_healthy(rng):
    data = roaring.encode(bits_to_containers([5, 10, 70000]))
    assert roaring.check(data) == []


def test_plane_bridge(rng):
    plane = bp.empty_plane(3)
    bits = [0, 63, 64, 2 ** 16, bp.SLICE_WIDTH - 1, bp.SLICE_WIDTH + 5,
            2 * bp.SLICE_WIDTH + 12345]
    for b in bits:
        bp.np_set_bit(plane, b)
    containers = roaring.plane_to_containers(plane, bp.SLICE_WIDTH)
    assert containers_to_bits(containers) == sorted(bits)
    plane2 = roaring.containers_to_plane(containers, bp.SLICE_WIDTH)
    assert plane2.shape[0] == 3
    assert np.array_equal(plane[:3], plane2)


def test_plane_roundtrip_through_file(rng):
    plane = bp.empty_plane(2)
    offs = rng.choice(2 * bp.SLICE_WIDTH, size=30000, replace=False)
    for o in offs:
        bp.np_set_bit(plane, int(o))
    data = roaring.encode(roaring.plane_to_containers(plane, bp.SLICE_WIDTH))
    plane2 = roaring.containers_to_plane(roaring.decode(data), bp.SLICE_WIDTH)
    assert np.array_equal(plane[:2], plane2[:2])


def test_fnv1a():
    # FNV-1a reference vectors
    assert roaring.fnv1a32(b"") == 0x811C9DC5
    assert roaring.fnv1a32(b"a") == 0xE40C292C
    assert roaring.fnv1a32(b"foobar") == 0xBF9CF968


def test_truncated_payload():
    data = roaring.encode(bits_to_containers([1, 2, 3]))
    with pytest.raises(roaring.CorruptError, match="out of bounds"):
        roaring.decode(data[:-4])
    assert roaring.check(data[:-4])  # reported, not crashed


def test_malformed_header_and_values():
    # header claims 5 containers but no key table
    bad = struct.pack("<II", roaring.COOKIE, 5)
    assert roaring.check(bad)  # reported, not crashed
    with pytest.raises(roaring.CorruptError, match="claims 5 containers"):
        roaring.decode(bad)
    # array container payload with a low-bits value >= 2^16
    good = roaring.encode(bits_to_containers([1]))
    corrupt = bytearray(good)
    corrupt[-4:] = struct.pack("<I", 70000)  # overwrite the one array value
    with pytest.raises(roaring.CorruptError, match="out of range"):
        roaring.decode(bytes(corrupt))
    assert roaring.check(bytes(corrupt))


def test_decode_tiered_mmap_parity(tmp_path):
    """decode_tiered over an mmap (the fragment-open path: zero heap
    copy of the file bytes, offset-tier + copy-on-write op replay in
    the native decoder) must equal decode_tiered over bytes, including
    ops that mutate both container kinds."""
    import mmap as mmap_mod

    # bitmap container (key 0) + array container (key 9)
    words = {0: np.zeros(1024, dtype=np.uint64)}
    words[0][:] = np.arange(1024, dtype=np.uint64) * np.uint64(2654435761)
    arrays = {9: np.array([1, 5, 1000], dtype=np.uint32)}
    blob = bytearray(roaring.encode_tiered(words, arrays))
    # ops: set+clear in the bitmap container, insert in the array one,
    # and create a brand-new key
    blob += roaring.encode_op(roaring.OP_ADD, 7)
    blob += roaring.encode_op(roaring.OP_REMOVE, 64)
    blob += roaring.encode_op(roaring.OP_ADD, 9 * (1 << 16) + 6)
    blob += roaring.encode_op(roaring.OP_ADD, 33 * (1 << 16) + 2)
    path = tmp_path / "d"
    path.write_bytes(bytes(blob))

    w_b, a_b, ops_b = roaring.decode_tiered(bytes(blob))
    with open(path, "rb") as f:
        mm = mmap_mod.mmap(f.fileno(), 0, access=mmap_mod.ACCESS_READ)
        try:
            w_m, a_m, ops_m = roaring.decode_tiered(mm)
        finally:
            mm.close()
    assert ops_b == ops_m == 4
    assert sorted(w_b) == sorted(w_m)
    for k in w_b:
        np.testing.assert_array_equal(w_b[k], w_m[k])
    assert sorted(a_b) == sorted(a_m)
    for k in a_b:
        np.testing.assert_array_equal(a_b[k], a_m[k])
    # the returned arrays must be OWNING copies, valid after mm.close()
    assert all(w.flags.owndata or w.base is not mm for w in w_m.values())
    assert int(w_m[0][0]) == int(w_b[0][0])
